"""Jitted SPMD train/eval step builders.

One compiled function is the whole per-step hot path — forward, backward,
cross-replica gradient reduction, optimizer update — where the reference
crosses process boundaries multiple times per step (worker->master RunStep,
worker->PS gradient push/variable fetch; SURVEY.md section 3.1).  The
gradient all-reduce is *implicit*: the loss is a global-batch mean over a
batch sharded on the ``data`` axis, so XLA emits the ICI all-reduce where
``SyncReplicasOptimizer``/NCCL did it by hand.

Multi-step unrolling (``unroll=k``): runs k steps per dispatch via
``lax.scan`` over a [k, ...] super-batch — amortising host dispatch for
microsecond-scale models (MNIST MLP at v5e-64; SURVEY.md section 7 hard-part
#2).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.sharding import ShardingRules, batch_sharding, sharding_tree
from .state import TrainState

#: loss_fn signature: (params, model_state, batch, rng)
#:                    -> (loss, (new_model_state, metrics_dict))
LossFn = Callable[..., tuple[jax.Array, tuple[Any, dict[str, jax.Array]]]]


def build_train_step(
    loss_fn: LossFn,
    optimizer: optax.GradientTransformation,
    *,
    mesh: Mesh | None = None,
    rules: ShardingRules = (),
    state_shardings: Any = None,
    donate: bool = True,
    unroll: int = 1,
    batch_spec: P | None = None,
    grad_accum: int = 1,
):
    """Returns ``step(state, batch) -> (state, metrics)``, fully jitted.

    With ``mesh``: in/out shardings are pinned (params per rule table, batch
    over the data axis) so the compiled executable is the same SPMD program on
    1 chip or a pod.  ``donate`` releases the input state's buffers to the
    output (halves peak HBM — the in-place variable update analog).

    ``grad_accum=k``: the batch is split into k microbatches inside the step
    (``lax.scan``), gradients averaged, ONE optimizer update — activation
    memory of a k-times-smaller batch at the numerics of the full batch
    (exact for global-mean losses; running statistics like BatchNorm see
    microbatches, so their momentum updates differ — same caveat as every
    accumulating trainer).  Requires batch % k == 0; composes with unroll.
    """
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")

    def one_step(state: TrainState, batch) -> tuple[TrainState, dict]:
        step_rng = jax.random.fold_in(state.rng, state.step)
        if grad_accum == 1:
            (loss, (new_model_state, metrics)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(state.params, state.model_state, batch, step_rng)
        else:
            def _split(x):
                if x.shape[0] % grad_accum:
                    raise ValueError(
                        f"batch dim {x.shape[0]} not divisible by "
                        f"grad_accum={grad_accum}"
                    )
                return x.reshape(grad_accum, x.shape[0] // grad_accum, *x.shape[1:])

            micro = jax.tree.map(_split, batch)

            def accum(carry, mb):
                # Running-sum in the carry: stacking k gradient pytrees as
                # scan outputs would cost k param-sized HBM buffers — the
                # exact memory accumulation exists to avoid.
                mstate, rng, gsum = carry
                rng, sub = jax.random.split(rng)
                (l, (mstate, m)), g = jax.value_and_grad(loss_fn, has_aux=True)(
                    state.params, mstate, mb, sub
                )
                gsum = jax.tree.map(jnp.add, gsum, g)
                return (mstate, rng, gsum), m

            gzero = jax.tree.map(jnp.zeros_like, state.params)
            (new_model_state, _, gsum), ms = jax.lax.scan(
                accum, (state.model_state, step_rng, gzero), micro
            )
            grads = jax.tree.map(lambda g: g / grad_accum, gsum)
            metrics = jax.tree.map(lambda m: jnp.mean(m, axis=0), ms)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            model_state=new_model_state,
            rng=state.rng,
        )
        return new_state, metrics

    if unroll > 1:

        def stepper(state: TrainState, super_batch):
            def body(s, b):
                return one_step(s, b)

            state, metrics = jax.lax.scan(body, state, super_batch)
            # Only the last sub-step's metrics are reported.
            last = jax.tree.map(lambda m: m[-1], metrics)
            return state, last

    else:
        stepper = one_step

    if mesh is None:
        return jax.jit(stepper, donate_argnums=(0,) if donate else ())

    if state_shardings is None:
        raise ValueError(
            "build_train_step(mesh=...) needs state_shardings= (from "
            "create_sharded_state) so jit can pin the state layout; pass it "
            "or omit mesh for sharding-free jit."
        )
    if batch_spec is not None:
        b_sharding = NamedSharding(mesh, batch_spec)
    else:
        b_sharding = batch_sharding(mesh)
    if unroll > 1:
        spec = b_sharding.spec
        b_sharding = NamedSharding(mesh, P(None, *spec))
    return jax.jit(
        stepper,
        in_shardings=(state_shardings, _tree_of(b_sharding)),
        out_shardings=(state_shardings, _tree_of_replicated(mesh)),
        donate_argnums=(0,) if donate else (),
    )


def _tree_of(sharding):
    # Batches are dicts of arrays; one sharding broadcasts over the dict via
    # jit's prefix-pytree rules.
    return sharding


def _tree_of_replicated(mesh):
    return NamedSharding(mesh, P())


def build_eval_step(
    eval_fn: Callable,
    *,
    mesh: Mesh | None = None,
    state_shardings: Any = None,
    batch_spec: P | None = None,
):
    """``eval(state, batch) -> metrics`` (replicated outputs)."""

    def stepper(state: TrainState, batch):
        return eval_fn(state.params, state.model_state, batch)

    if mesh is None:
        return jax.jit(stepper)
    b_sharding = (
        NamedSharding(mesh, batch_spec)
        if batch_spec is not None
        else batch_sharding(mesh)
    )
    return jax.jit(
        stepper,
        in_shardings=(state_shardings, b_sharding),
        out_shardings=_tree_of_replicated(mesh),
    )
