"""Training-loop layer: the ``MonitoredTrainingSession`` stack rebuilt
TPU-native (SURVEY.md section 1 L3, section 2c T1-T4).

- ``state``      — ``TrainState`` pytree (step, params, opt_state,
                   model_state, rng) + sharded initialisation.
- ``step``       — ``build_train_step``: one fully-jitted SPMD training step
                   (grad, all-reduce via sharding, optimizer update), with
                   optional multi-step unrolling via ``lax.scan``.
- ``loop``       — ``TrainSession``: hook dispatch, should_stop, auto-resume.
- ``hooks``      — StopAtStep / StepCounter / Logging / CheckpointSaver /
                   Summary hook equivalents.
"""

from .state import TrainState, create_state, create_sharded_state  # noqa: F401
from .step import build_eval_step, build_train_step  # noqa: F401
from .loop import TrainSession  # noqa: F401
from .runner import Experiment  # noqa: F401
from .ps_experiment import run_ps_emulation, array_eval_fn, worker_count  # noqa: F401
from . import checkpoint  # noqa: F401
from . import hooks  # noqa: F401
from . import preemption  # noqa: F401
