"""CLI-level runner for the PS-emulation modes (SURVEY.md D5, section 3.1/3.2).

One shared path so every example honors ``--sync_replicas`` uniformly
(round-1 review: only cifar10_cnn did, and the token-gated ``sync_replicas``
mode — W1's actual SyncReplicasOptimizer semantics — was reachable only from
tests):

- ``--sync_replicas=false``           -> async mode (W2: each worker's
  gradient applies immediately, in arrival order).
- ``--ps_emulation --sync_replicas``  -> token-gated sync_replicas mode (W1:
  accumulate ``--replicas_to_aggregate`` grads, drop stale, chief applies,
  workers proceed on tokens).

Both run on ``parallel.async_ps.AsyncPSTrainer`` (native C++ accumulator /
token-queue / gradient-queue services) with checkpoint/resume under
``--log_dir`` and print the same scrapable FINAL line as ``Experiment``.

Note on model_state: the emulation keeps non-parameter state (e.g. BatchNorm
statistics) at its initial value — the reference's async-PS scripts hosted
only *variables* on PS tasks; workloads with running statistics (W3) are not
PS workloads in the reference either.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Iterator

import numpy as np

log = logging.getLogger("dtx.ps_experiment")


def worker_count(FLAGS) -> int:
    """Emulated worker count from the legacy cluster flags (the ONE place
    this is computed — CLIs that shard data per worker must use it too)."""
    return max(2, len(FLAGS.worker_hosts.split(",")) if FLAGS.worker_hosts else 2)


def run_ps_emulation(
    *,
    init_fn: Callable,
    loss_fn: Callable,
    optimizer,
    batches_for_worker: Callable[[int, int, int], Iterator[dict]],
    FLAGS,
    mode: str,
    eval_fn: Callable[[Any], dict[str, float]] | None = None,
    model_state: Any = None,
) -> Any:
    """Run W1/W2 PS-emulation training; returns final params.

    ``batches_for_worker(worker_id, local_batch_size, n_workers)`` yields
    that worker's local batches (its data shard; the count is passed so data
    sharding can never diverge from the thread count); ``eval_fn(params)``
    computes final metrics for the FINAL line.
    """
    import jax

    from ..parallel.async_ps import AsyncPSConfig, AsyncPSTrainer

    n_workers = worker_count(FLAGS)
    r2a = getattr(FLAGS, "replicas_to_aggregate", 0) or n_workers
    if getattr(FLAGS, "grad_accum", 1) > 1:
        log.warning(
            "--grad_accum=%d is ignored in PS-emulation mode (per-worker "
            "gradients apply individually; accumulation is a mesh-trainer "
            "feature)", FLAGS.grad_accum,
        )
    log.info(
        "PS emulation mode=%s: %d workers%s (native accumulator/token "
        "services; semantics notes in parallel.async_ps)",
        mode,
        n_workers,
        f", replicas_to_aggregate={r2a}" if mode == "sync_replicas" else "",
    )
    acfg = AsyncPSConfig(
        num_workers=n_workers,
        mode=mode,
        replicas_to_aggregate=r2a,
        max_staleness=getattr(FLAGS, "max_staleness", None) or None,
        train_steps=FLAGS.train_steps,
        ckpt_dir=os.path.join(FLAGS.log_dir, "ps_ckpt") if FLAGS.log_dir else None,
        checkpoint_every=FLAGS.checkpoint_every_steps,
    )
    params = init_fn(jax.random.key(FLAGS.seed))
    if isinstance(params, tuple):  # init_fn returning (params, model_state)
        params, model_state = params
    trainer = AsyncPSTrainer(
        acfg,
        loss_fn,
        optimizer,
        params,
        model_state=model_state,
        rng=jax.random.key(FLAGS.seed),
    )
    local_bs = max(1, FLAGS.batch_size // n_workers)
    t0 = time.perf_counter()
    final_params = trainer.run(
        [
            iter(batches_for_worker(w, local_bs, n_workers))
            for w in range(n_workers)
        ]
    )
    dt = time.perf_counter() - t0  # training window only (eval excluded)

    metrics = eval_fn(final_params) if eval_fn is not None else {}
    sps = trainer.global_step / dt if dt > 0 else 0.0
    eps_per_chip = sps * local_bs / max(1, len(jax.devices()))
    losses = [l for (_, _, l) in trainer.history] or [float("nan")]
    parts = [
        f"FINAL step={trainer.global_step}",
        f"steps_per_sec={sps:.1f}",
        f"examples_per_sec_per_chip={eps_per_chip:.0f}",
        f"mode={mode}",
        f"stale_dropped={trainer.total_dropped}",
        f"first_loss={losses[0]:.4f}",
        f"last_loss={losses[-1]:.4f}",
    ]
    for k, v in metrics.items():
        parts.append(f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}")
    print(" ".join(parts))
    return final_params


def array_eval_fn(apply_logits: Callable, test: dict[str, np.ndarray], batch_size: int):
    """Standard accuracy eval over array test splits for the FINAL line."""
    import jax

    from ..models import layers

    @jax.jit
    def _acc(p, b):
        return layers.accuracy(apply_logits(p, b), b["label"])

    def eval_fn(params):
        n = len(test["label"])
        ebs = min(batch_size, n)
        accs = [
            float(_acc(params, {k: v[i : i + ebs] for k, v in test.items()}))
            for i in range(0, (n // ebs) * ebs, ebs)
        ]
        return {"test_accuracy": float(np.mean(accs))}

    return eval_fn
