"""CLI-level runner for the PS-emulation modes (SURVEY.md D5, section 3.1/3.2).

One shared path so every example honors ``--sync_replicas`` uniformly
(round-1 review: only cifar10_cnn did, and the token-gated ``sync_replicas``
mode — W1's actual SyncReplicasOptimizer semantics — was reachable only from
tests):

- ``--sync_replicas=false``           -> async mode (W2: each worker's
  gradient applies immediately, in arrival order).
- ``--ps_emulation --sync_replicas``  -> token-gated sync_replicas mode (W1:
  accumulate ``--replicas_to_aggregate`` grads, drop stale, chief applies,
  workers proceed on tokens).

Both run on ``parallel.async_ps.AsyncPSTrainer`` (native C++ accumulator /
token-queue / gradient-queue services) with checkpoint/resume under
``--log_dir`` and print the same scrapable FINAL line as ``Experiment``.

Note on model_state: the emulation keeps non-parameter state (e.g. BatchNorm
statistics) at its initial value — the reference's async-PS scripts hosted
only *variables* on PS tasks; workloads with running statistics (W3) are not
PS workloads in the reference either.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import sys
import time
from typing import Any, Callable, Iterator

import numpy as np

log = logging.getLogger("dtx.ps_experiment")


def worker_count(FLAGS) -> int:
    """Emulated worker count from the legacy cluster flags (the ONE place
    this is computed — CLIs that shard data per worker must use it too)."""
    return max(2, len(FLAGS.worker_hosts.split(",")) if FLAGS.worker_hosts else 2)


def run_ps_emulation(
    *,
    init_fn: Callable,
    loss_fn: Callable,
    optimizer,
    batches_for_worker: Callable[[int, int, int], Iterator[dict]],
    FLAGS,
    mode: str,
    eval_fn: Callable[[Any], dict[str, float]] | None = None,
    model_state: Any = None,
    predict_fn: Callable | None = None,
) -> Any:
    """Run W1/W2 PS-emulation training; returns final params.

    ``batches_for_worker(worker_id, local_batch_size, n_workers)`` yields
    that worker's local batches (its data shard; the count is passed so data
    sharding can never diverge from the thread count); ``eval_fn(params)``
    computes final metrics for the FINAL line.  ``predict_fn(params,
    inputs)`` is the row-wise inference apply a ``--job_name=serve``
    replica (r10) would serve — only that task role needs it.

    With ``--job_name=ps|chief|worker`` and ``--ps_hosts`` (the reference's
    one-process-per-task launch, SURVEY.md sections 3.1/3.2) this process
    runs ONLY its task's role over the native socket service instead of the
    in-process thread emulation — see :func:`run_ps_cluster_task`.
    """
    import jax

    from ..parallel.async_ps import AsyncPSConfig, AsyncPSTrainer
    from ..utils.flags import is_cross_process_ps

    if is_cross_process_ps(FLAGS):
        return run_ps_cluster_task(
            init_fn=init_fn,
            loss_fn=loss_fn,
            optimizer=optimizer,
            batches_for_worker=batches_for_worker,
            FLAGS=FLAGS,
            mode=mode,
            eval_fn=eval_fn,
            model_state=model_state,
            predict_fn=predict_fn,
        )

    n_workers = worker_count(FLAGS)
    r2a = getattr(FLAGS, "replicas_to_aggregate", 0) or n_workers
    if getattr(FLAGS, "grad_accum", 1) > 1:
        log.warning(
            "--grad_accum=%d is ignored in PS-emulation mode (per-worker "
            "gradients apply individually; accumulation is a mesh-trainer "
            "feature)", FLAGS.grad_accum,
        )
    log.info(
        "PS emulation mode=%s: %d workers%s (native accumulator/token "
        "services; semantics notes in parallel.async_ps)",
        mode,
        n_workers,
        f", replicas_to_aggregate={r2a}" if mode == "sync_replicas" else "",
    )
    acfg = _ps_cfg(FLAGS, mode, n_workers)
    params = init_fn(jax.random.key(FLAGS.seed))
    if isinstance(params, tuple):  # init_fn returning (params, model_state)
        params, model_state = params
    trainer = AsyncPSTrainer(
        acfg,
        loss_fn,
        optimizer,
        params,
        model_state=model_state,
        rng=jax.random.key(FLAGS.seed),
    )
    local_bs = max(1, FLAGS.batch_size // n_workers)
    t0 = time.perf_counter()
    final_params = trainer.run(
        [
            iter(batches_for_worker(w, local_bs, n_workers))
            for w in range(n_workers)
        ]
    )
    dt = time.perf_counter() - t0  # training window only (eval excluded)

    metrics = eval_fn(final_params) if eval_fn is not None else {}
    sps = trainer.global_step / dt if dt > 0 else 0.0
    losses = [l for (_, _, l) in trainer.history] or [float("nan")]
    _print_final(
        step=trainer.global_step, dt=dt, local_bs=local_bs, mode=mode,
        metrics=metrics,
        # Sync mode consumes replicas_to_aggregate worker batches per
        # applied step — count them all, not just the chief's one
        # (ADVICE r5: the old definition undercounted by ~n_workers).
        eps_per_chip=sps * local_bs * (r2a if mode == "sync_replicas" else 1)
        / max(1, len(jax.devices())),
        extra={
            "stale_dropped": trainer.total_dropped,
            "first_loss": f"{losses[0]:.4f}",
            "last_loss": f"{losses[-1]:.4f}",
        },
    )
    return final_params


def _print_final(
    *, step: int, dt: float, local_bs: int, mode: str,
    metrics: dict, extra: dict, eps_per_chip: float | None = None,
):
    """The ONE scrapable FINAL line both PS paths (thread emulation and
    cross-process cluster) print — same fields, same order."""
    sps = step / dt if dt > 0 else 0.0
    if eps_per_chip is None:
        eps_per_chip = sps * local_bs
    parts = [
        f"FINAL step={step}",
        f"steps_per_sec={sps:.1f}",
        f"examples_per_sec_per_chip={eps_per_chip:.0f}",
        f"mode={mode}",
    ]
    for k, v in extra.items():
        parts.append(f"{k}={v}")
    for k, v in metrics.items():
        parts.append(f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}")
    print(" ".join(parts))


def _ps_cfg(FLAGS, mode: str, n_workers: int):
    from ..parallel.async_ps import AsyncPSConfig

    r2a = getattr(FLAGS, "replicas_to_aggregate", 0) or n_workers
    return AsyncPSConfig(
        num_workers=n_workers,
        mode=mode,
        replicas_to_aggregate=r2a if mode == "sync_replicas" else None,
        max_staleness=getattr(FLAGS, "max_staleness", None) or None,
        # --deterministic: async applies keep their stale-params semantics
        # but run on the fixed round-robin schedule (reproducible runs —
        # and a retry-free CLI acceptance gate).
        fixed_interleave=bool(getattr(FLAGS, "deterministic", False)),
        train_steps=FLAGS.train_steps,
        ckpt_dir=os.path.join(FLAGS.log_dir, "ps_ckpt") if FLAGS.log_dir else None,
        checkpoint_every=FLAGS.checkpoint_every_steps,
        # r7 transport knobs (getattr: embedded callers' FLAGS namespaces
        # predate them).  PSClient validates the dtype, so a typo'd
        # --ps_wire_dtype fails the launch loudly.
        ps_wire_dtype=getattr(FLAGS, "ps_wire_dtype", "f32") or "f32",
        ps_prefetch=bool(getattr(FLAGS, "ps_prefetch", True)),
        # r14 elasticity knobs (getattr for embedded callers, as above).
        membership_leases=bool(getattr(FLAGS, "membership_leases", True)),
        lease_ttl_s=float(getattr(FLAGS, "lease_ttl_s", 10.0) or 10.0),
        # r20 multi-tenancy: the run's tenant namespace (getattr for
        # embedded callers).  tenancy.check_tenant inside the clients
        # rejects a typo'd --tenant loudly at dial time.
        tenant=getattr(FLAGS, "tenant", "default") or "default",
    )


def _tenant_quotas(FLAGS):
    """--tenant_quotas parsed to the ServerCore quota table (r20), or None.
    A malformed spec fails the SERVER launch loudly here — never silently
    serving with fairness off."""
    from ..parallel import tenancy

    spec = getattr(FLAGS, "tenant_quotas", "") or ""
    return tenancy.parse_quotas(spec) if spec else None


def _resolve_listen_all(FLAGS, host: str, flag: str = "--ps_hosts") -> bool:
    """Network exposure is an explicit operator decision (--ps_listen_all),
    never inferred from how the hostname is spelled: '::1' or a
    loopback-resolving FQDN must not silently bind INADDR_ANY, and a
    non-loopback entry without the flag is a launch error, not a silent
    network-wide bind of an unauthenticated service (ADVICE r4).  Applies
    to EVERY service-hosting path: the dedicated PS task, the chief-hosted
    (--ps_tasks=0) service, the data-service task, and the serve replicas
    (``flag`` names the host list the entry came from)."""
    listen_all = bool(getattr(FLAGS, "ps_listen_all", False))
    if not listen_all and host not in ("127.0.0.1", "localhost"):
        raise ValueError(
            f"{flag} entry {host!r} is not a literal loopback "
            "address; serving other hosts needs the unauthenticated "
            "state service bound on all interfaces — opt in explicitly "
            "with --ps_listen_all (trusted networks only)"
        )
    if listen_all:
        log.warning(
            "--ps_listen_all: PS state service binding ALL interfaces "
            "(UNAUTHENTICATED — trusted networks only)"
        )
    return listen_all


def _probe_ps(host: str, port: int, deadline_s: float) -> bool:
    """True when a PS service answers PING at host:port within the window."""
    from ..parallel import ps_service

    t_end = time.time() + deadline_s
    while time.time() < t_end:
        try:
            c = ps_service.PSClient(host, port, timeout_s=2.0)
            c.ping()
            c.close()
            return True
        except (OSError, ps_service.PSError):
            # PSError covers a PS that accepts the connection but drops it
            # mid-ping (e.g. mid-restart under the supervisor) — keep
            # polling, exactly like a refused connection.
            time.sleep(0.2)
    return False


def _supervised_reexec(FLAGS, *, child_env_flag: str) -> int | None:
    """Re-exec this launch under ``utils.supervisor.supervise()`` — the
    service-task crash-heal path shared by the ``ps`` and ``data_service``
    roles.  Returns the supervisor's exit code when THIS process acted as
    the supervisor (the caller exits with it), or None when the caller
    should host the service itself: supervision disabled, a
    non-re-executable launcher, or this process IS the supervised child
    (``child_env_flag`` set).  A fault-INJECTED death is healed by
    stripping the fired ``die`` spec from the restarted child's plan."""
    from ..utils import faults

    restarts = int(getattr(FLAGS, "ps_restarts", 0) or 0)
    launcher = os.path.abspath(sys.argv[0]) if sys.argv else ""
    if restarts > 0 and not (launcher.endswith(".py") and os.path.isfile(launcher)):
        # Supervision re-execs the launch script; a programmatic or
        # embedded caller whose argv does not reproduce this config
        # would supervise the WRONG thing — host unsupervised instead.
        log.warning(
            "--ps_restarts=%d: launcher %r is not a re-executable "
            "script; hosting the service unsupervised (a crash falls "
            "back to whole-job restart)", restarts, sys.argv[:1],
        )
        restarts = 0
    if restarts <= 0 or os.environ.get(child_env_flag) == "1":
        return None
    from ..utils import supervisor

    env = dict(os.environ)
    env[child_env_flag] = "1"

    def heal_fault_plan(env: dict, attempt: int, returncode: int) -> dict:
        # A fault-INJECTED death must not re-fire in the healing
        # incarnation (the plan is inherited through the env);
        # organic crashes keep the plan untouched.
        if returncode == faults.FAULT_EXIT_CODE and env.get("DTX_FAULT_PLAN"):
            env["DTX_FAULT_PLAN"] = faults.plan_without(
                env["DTX_FAULT_PLAN"], "die", faults.current_role()
            )
            faults.log_event(
                "supervisor_healed_plan", role=faults.current_role(),
                attempt=attempt,
            )
        return env

    return supervisor.supervise(
        [sys.executable, os.path.abspath(sys.argv[0]), *sys.argv[1:]],
        max_restarts=restarts,
        env=env,
        mutate_env=heal_fault_plan,
    )


def run_ps_cluster_task(
    *, init_fn, loss_fn, optimizer, batches_for_worker, FLAGS, mode, eval_fn=None,
    model_state=None, predict_fn=None,
):
    """One task of the reference's multi-process PS cluster (its defining
    launch pattern — one process per ``--job_name``/``--task_index``,
    SURVEY.md sections 3.1/3.2), over the native socket service:

    - ``ps``:     hosts the C++ state service at ``--ps_hosts[task_index]``
                  until the chief signals shutdown (``server.join()`` role).
                  Task i owns SHARD i of the flat parameter vector (r9,
                  ``parallel/ps_shard.ShardLayout`` over ``--ps_shards``
                  servers; -1 = one per host — the reference's
                  ``replica_device_setter`` spreading): param pulls,
                  publishes and gradient pushes scatter/gather over every
                  shard in parallel, while step tokens and the shutdown
                  signal stay on shard 0 (the coordinator).
    - ``chief``:  aggregation/apply/publish loop (``RemotePSChief``).
                  Topology is DETERMINISTIC, not probed: with
                  ``--ps_tasks=0`` the chief hosts every shard server
                  in-process (3-process minimum launch); otherwise
                  dedicated PS tasks are expected at ``ps_hosts[0:N]`` and
                  waited for (120 s each).
    - ``worker``: gradient computation against the published snapshots
                  (``remote_worker_loop``), data-sharded by ``task_index``.
    - ``data_service`` (r8): dedicated input worker — serves decoded,
                  batched shards from its ``--data_dir`` at
                  ``--data_service_hosts[task_index]``; training workers
                  consume via ``--data_dir=dsvc://host:port``
                  (``data/data_service.py``).  Needs no PS service.
    - ``serve`` (r10): online inference replica — hot-tracks the (sharded)
                  parameter store with versioned pulls and serves
                  micro-batched predictions at
                  ``--serve_hosts[task_index]`` under the ``msrv`` service
                  tag (``serve/model_server.py``; needs ``predict_fn``).
                  Clients load-balance over the full list
                  (``serve.ServePool``).  Restarts under ``--ps_restarts``
                  like the other service tasks: a killed replica re-pulls
                  the current params from the PS and rejoins with zero
                  coordination.

    Fault posture (r6): each task gets a fault role (``ps0``, ``chief0``,
    ``worker<i>``, ``data_service0``) for ``DTX_FAULT_PLAN`` matching, and the PS task runs
    under ``utils.supervisor.supervise()`` (``--ps_restarts``), so a PS
    crash is healed by PS restart + client reconnect/reseed instead of the
    whole-job crash-restart path — see RUNBOOK.md "Fault injection &
    recovery".

    Launch recipe: RUNBOOK.md "Cross-process PS".
    """
    import jax

    from ..parallel import async_ps
    from ..utils import faults, telemetry

    n_workers = worker_count(FLAGS)
    local_bs = max(1, FLAGS.batch_size // n_workers)
    job = FLAGS.job_name
    if not faults.current_role():
        faults.set_role(f"{job}{FLAGS.task_index}")
    # Observability (r13 dtxobs): export the flight-recorder dump directory
    # to this task AND everything it spawns (supervised re-execs inherit
    # the environment), so every role of the cluster dumps its event ring
    # to one place on fatal conditions.  Env wins when both are set — the
    # launcher may already have threaded it through.
    obs_dir = getattr(FLAGS, "obs_events_dir", "") or ""
    if obs_dir and not os.environ.get(telemetry.EVENTS_DIR_ENV):
        os.environ[telemetry.EVENTS_DIR_ENV] = obs_dir

    if job == "data_service":
        # Disaggregated input worker (r8): serves ready batches from this
        # task's --data_dir shards to training workers that resolve
        # --data_dir=dsvc://host:port (data/data_service.py).  Same
        # supervised-restart contract as the PS task — a killed data server
        # comes back on the same port and the clients re-claim their
        # in-flight splits mid-epoch.  Needs no PS service of its own.
        from ..data import data_service as dsvc_lib

        ds_hosts = getattr(FLAGS, "data_service_hosts", "") or ""
        if not ds_hosts:
            raise ValueError(
                "--job_name=data_service needs --data_service_hosts "
                "(host:port this task binds)"
            )
        ds_entries = ds_hosts.split(",")
        my_host, my_port = ds_entries[
            min(FLAGS.task_index, len(ds_entries) - 1)
        ].rsplit(":", 1)
        listen_all = _resolve_listen_all(FLAGS, my_host, "--data_service_hosts")
        rc = _supervised_reexec(FLAGS, child_env_flag="DTX_DSVC_SUPERVISED")
        if rc is not None:
            if rc != 0:
                raise SystemExit(rc)
            return None
        # Elasticity (r14): when the launch carries a PS topology, watch
        # the coordinator shard's lease registry so a departed worker's
        # splits reassign on the membership signal, not the liveness
        # window.
        lease_addrs = None
        if getattr(FLAGS, "ps_hosts", "") and bool(
            getattr(FLAGS, "membership_leases", True)
        ):
            from ..parallel.membership import coordinator_addrs
            from ..utils.flags import ps_shard_topology

            entries, n_shards, n_replicas = ps_shard_topology(FLAGS)
            lease_addrs = coordinator_addrs(entries, n_shards, n_replicas)
        bound = dsvc_lib.host_data_service_task(
            FLAGS.data_dir, int(my_port), batch_size=local_bs,
            seed=FLAGS.seed, loopback_only=not listen_all,
            ps_addrs=lease_addrs,
            ps_layout_version=int(
                getattr(FLAGS, "ps_layout_version", 0) or 0
            ),
            tenant_quotas=_tenant_quotas(FLAGS),
        )
        print(f"DSVC_DONE port={bound}")
        return None

    from ..utils.flags import ps_shard_topology

    entries, n_shards, n_replicas = ps_shard_topology(FLAGS)
    # The sharded-store topology (r9): shard i's PRIMARY server is
    # entries[i]; every client scatters/gathers over all of them in
    # parallel.  Shard 0 doubles as the coordinator (tokens, shutdown
    # signal).  Replication (r12): replica r of shard i is
    # entries[r*n_shards + i] — clients carry the full per-shard replica
    # list and fail over inside their own recovery loop.
    shard_addrs = entries[: n_shards * n_replicas]
    primary_addrs = entries[:n_shards]
    layout_version = int(getattr(FLAGS, "ps_layout_version", 0) or 0)
    host, port = shard_addrs[0]

    if job == "serve":
        # Online inference replica (r10): hot-track the parameter store
        # these same shard servers host and serve micro-batched
        # predictions.  Same supervised-restart contract as the PS and
        # data-service tasks — a killed replica comes back on the same
        # port, re-pulls the CURRENT params from the PS (the store is the
        # rendezvous; zero coordination) and rejoins the client rotation.
        from .. import serve as serve_pkg
        from ..utils.flags import parse_hostports

        if predict_fn is None:
            raise ValueError(
                "--job_name=serve needs a predict_fn (the row-wise "
                "inference apply) passed through run_ps_emulation / "
                "run_ps_cluster_task"
            )
        sv_hosts = getattr(FLAGS, "serve_hosts", "") or ""
        if not sv_hosts:
            raise ValueError(
                "--job_name=serve needs --serve_hosts (host:port this "
                "replica binds)"
            )
        sv_entries = parse_hostports(sv_hosts, "--serve_hosts")
        my_host, my_port = sv_entries[
            min(FLAGS.task_index, len(sv_entries) - 1)
        ]
        listen_all = _resolve_listen_all(FLAGS, my_host, "--serve_hosts")
        rc = _supervised_reexec(FLAGS, child_env_flag="DTX_SERVE_SUPERVISED")
        if rc is not None:
            if rc != 0:
                raise SystemExit(rc)
            return None
        for sh, sp in primary_addrs:
            if not _probe_ps(sh, sp, 120.0):
                raise ConnectionError(
                    f"no PS service at {sh}:{sp} after 120 s (the serve "
                    "replica pulls its params from there)"
                )
        # Registry pin mode (r19): --registry_dir + --serve_model_version
        # serve an immutable registry version instead of hot-tracking;
        # the PS legs stay up for membership leases, so rolling deploys
        # ride the same discovery as the elastic pool.
        bound = serve_pkg.host_serve_task(
            registry_dir=getattr(FLAGS, "registry_dir", "") or None,
            model_version=(
                int(getattr(FLAGS, "serve_model_version", 0) or 0) or None
            ),
            init_fn=init_fn,
            predict_fn=predict_fn,
            # Full replica-major list (r15): the replica's PS legs get the
            # same failover the training clients have, and its refresher
            # follows committed layout epochs from the same topology.
            ps_addrs=shard_addrs,
            ps_replicas=n_replicas,
            layout_version=layout_version,
            port=int(my_port),
            loopback_only=not listen_all,
            max_batch=int(getattr(FLAGS, "serve_max_batch", 32)),
            max_wait_ms=float(getattr(FLAGS, "serve_max_wait_ms", 5.0)),
            queue_depth=int(getattr(FLAGS, "serve_queue_depth", 128)),
            queue_deadline_ms=float(
                getattr(FLAGS, "serve_queue_deadline_ms", 0.0)
            ),
            refresh_ms=float(getattr(FLAGS, "serve_refresh_ms", 50.0)),
            membership=bool(getattr(FLAGS, "membership_leases", True)),
            lease_ttl_s=float(getattr(FLAGS, "lease_ttl_s", 10.0) or 10.0),
            advertise_addr=f"{my_host}:{my_port}",
            metrics_dir=(
                os.path.join(FLAGS.log_dir, f"serve{FLAGS.task_index}")
                if getattr(FLAGS, "log_dir", None)
                else None
            ),
            # r20: the replica serves ITS tenant's model namespace (PS
            # params + registry pins + lease all tenant-scoped) while the
            # quota table admission-controls every tenant that dials it.
            tenant=getattr(FLAGS, "tenant", "default") or "default",
            tenant_quotas=_tenant_quotas(FLAGS),
        )
        print(f"SERVE_DONE port={bound}")
        return None

    acfg = _ps_cfg(FLAGS, mode, n_workers)
    if acfg.fixed_interleave:
        # Real processes free-run — there is no scheduler to fix their
        # interleaving, so --deterministic must not silently promise a
        # reproducible trajectory here (it still pins seeds/precision).
        log.warning(
            "--deterministic: the fixed async interleave applies only to "
            "the single-process thread emulation; cross-process cluster "
            "ordering remains arrival-order nondeterministic."
        )
        acfg = dataclasses.replace(acfg, fixed_interleave=False)
    chief_hosts_service = FLAGS.ps_tasks == 0

    if job == "ps":
        if chief_hosts_service:
            raise ValueError(
                "--job_name=ps contradicts --ps_tasks=0 (chief hosts the "
                "service); launch without the PS task or drop --ps_tasks=0"
            )
        from ..parallel.membership import coordinator_addrs as _coord_addrs

        reshard_spec = getattr(FLAGS, "ps_reshard_to", "") or ""
        if reshard_spec:
            # Live-reshard JOINER (r15): this task serves shard
            # --task_index of the TARGET topology named by
            # --ps_reshard_to, assembling its slice from the OLD topology
            # (--ps_hosts / --ps_shards / --ps_layout_version) before it
            # carries data.  See RUNBOOK "Live resharding".
            from ..utils.flags import parse_reshard_to

            new_version, new_entries = parse_reshard_to(reshard_spec)
            if new_version <= layout_version:
                raise ValueError(
                    f"--ps_reshard_to epoch {new_version} must exceed the "
                    f"old --ps_layout_version {layout_version}"
                )
            tid = FLAGS.task_index
            if tid >= len(new_entries):
                raise ValueError(
                    f"--task_index={tid} exceeds the {len(new_entries)}-"
                    "entry --ps_reshard_to topology"
                )
            my_host, my_port = new_entries[tid]
            listen_all = _resolve_listen_all(
                FLAGS, my_host, "--ps_reshard_to"
            )
            rc = _supervised_reexec(FLAGS, child_env_flag="DTX_PS_SUPERVISED")
            if rc is not None:
                if rc != 0:
                    raise SystemExit(rc)
                return None
            bound = async_ps.host_ps_task(
                int(my_port), loopback_only=not listen_all,
                shard_id=tid, shard_count=len(new_entries),
                layout_version=new_version,
                coordinator_addrs=[new_entries[0]],
                lease_ttl_s=float(getattr(FLAGS, "lease_ttl_s", 10.0) or 10.0),
                reshard_from={
                    "addrs": shard_addrs,
                    "shards": n_shards,
                    "replicas": n_replicas,
                    "version": layout_version,
                    "new_addrs": new_entries,
                },
            )
            print(f"PS_DONE port={bound}")
            return None
        tid = min(FLAGS.task_index, len(entries) - 1)
        my_host, my_port = entries[tid]
        listen_all = _resolve_listen_all(FLAGS, my_host)
        # Host in a supervised CHILD (--ps_restarts): a PS crash (injected
        # or organic) is healed by a fresh incarnation on the same port,
        # which the chief/worker clients reconnect into — partial recovery
        # instead of whole-job crash-restart.  With sharding, ONE shard's
        # crash is healed this way while the other shards serve on.
        rc = _supervised_reexec(FLAGS, child_env_flag="DTX_PS_SUPERVISED")
        if rc is not None:
            if rc != 0:
                raise SystemExit(rc)
            return None
        if tid >= n_shards * n_replicas:
            # Launch-script parity: extra PS tasks beyond the shard/replica
            # grid are accepted but own no slice — host an
            # unsharded-identity service nothing will dial.
            log.warning(
                "PS task %d exceeds --ps_shards=%d x --ps_replicas=%d: no "
                "shard assigned (idle; shrink --ps_hosts or raise "
                "--ps_shards)", tid, n_shards, n_replicas,
            )
            bound = async_ps.host_ps_task(
                int(my_port), loopback_only=not listen_all
            )
        else:
            # Task i serves shard i % shards, replica i // shards — the
            # inverse of ps_shard.replica_major's addrs[r*shards + s]
            # grouping (the ONE replica-major definition).  Its PEER is
            # the other replica of the same shard; a restart catches up
            # from it (REPL_SYNC) before serving — the primary waits only
            # briefly (its peer may be waiting on US at a cold start),
            # the backup generously (its primary is booting too).
            s_id, r_id = tid % n_shards, tid // n_shards
            peer = None
            peer_role = ""
            sync_wait_s = 0.0
            if n_replicas == 2:
                from ..parallel.ps_shard import replica_major

                pair = replica_major(
                    list(range(n_shards * n_replicas)), n_shards, n_replicas
                )[s_id]
                peer_tid = pair[(r_id + 1) % 2]
                peer = entries[peer_tid]
                peer_role = f"ps{peer_tid}"
                sync_wait_s = 2.0 if r_id == 0 else 45.0
            bound = async_ps.host_ps_task(
                int(my_port), loopback_only=not listen_all,
                shard_id=s_id, shard_count=n_shards,
                layout_version=layout_version, peer=peer,
                peer_role=peer_role, sync_wait_s=sync_wait_s,
                # The coordinator's registry backs the idle-pair self-exit
                # (RUNBOOK 4e) and the drain/epoch reads.
                coordinator_addrs=_coord_addrs(
                    entries, n_shards, n_replicas
                ),
            )
        print(f"PS_DONE port={bound}")
        return None

    if job == "chief":
        faults.arm_process_faults()
        params = init_fn(jax.random.key(FLAGS.seed))
        if isinstance(params, tuple):
            params, model_state = params
        if not chief_hosts_service:
            for sh, sp in shard_addrs:
                if not _probe_ps(sh, sp, 120.0):
                    raise ConnectionError(
                        f"no PS task answered at {sh}:{sp} after 120 s "
                        "(launch every --job_name=ps shard process first, "
                        "or pass --ps_tasks=0 to host the service in the "
                        "chief)"
                    )
        log.info(
            "PS cluster chief: mode=%s %d workers, %d shard(s) x %d "
            "replica(s) at %s (%s)",
            mode, n_workers, n_shards, n_replicas,
            ",".join(f"{h}:{p}" for h, p in shard_addrs),
            "hosted in-process" if chief_hosts_service else "external PS tasks",
        )
        # Scrapable platform record: tools/ps_tpu_smoke.py asserts the chief
        # genuinely ran the accelerator plugin (not a silent CPU fallback).
        print(f"CHIEF_PLATFORM={jax.devices()[0].platform}", flush=True)
        trainer = async_ps.RemotePSChief(
            acfg, loss_fn, optimizer, params,
            model_state=model_state,
            rng=jax.random.key(FLAGS.seed),
            ps_replicas=n_replicas,
            layout_version=layout_version,
            **(
                # Chief-hosted service (one in-process server per shard
                # replica): same explicit-exposure contract as the
                # dedicated PS task (code-review r5), checked per host.
                {
                    "ports": [p for _, p in shard_addrs],
                    "listen_all": any(
                        _resolve_listen_all(FLAGS, h) for h, _ in shard_addrs
                    ),
                }
                if chief_hosts_service
                else {"ps_addrs": shard_addrs}
            ),
        )
        t0 = time.perf_counter()
        final_params = trainer.run_chief()
        dt = time.perf_counter() - t0
        metrics = eval_fn(final_params) if eval_fn is not None else {}
        # Same examples_per_sec_per_chip DEFINITION as the thread-emulation
        # path: divide by the chief's device count (ADVICE r4 — one scrapable
        # field name must not carry two definitions across the PS modes), and
        # count all replicas_to_aggregate worker batches per sync step
        # (ADVICE r5).
        sps = trainer.global_step / dt if dt > 0 else 0.0
        r2a = (
            (acfg.replicas_to_aggregate or n_workers)
            if mode == "sync_replicas"
            else 1
        )
        _print_final(
            step=trainer.global_step, dt=dt, local_bs=local_bs,
            mode=f"{mode}_cluster", metrics=metrics,
            eps_per_chip=sps * local_bs * r2a / max(1, len(jax.devices())),
            extra={"workers": n_workers, "stale_dropped": trainer.total_dropped},
        )
        return final_params

    # job == "worker"
    faults.arm_process_faults()
    wid = FLAGS.task_index
    for sh, sp in shard_addrs:
        if not _probe_ps(sh, sp, 120.0):
            raise ConnectionError(f"no PS service at {sh}:{sp} after 120 s")

    def struct_init(rng):
        p = init_fn(rng)
        return p[0] if isinstance(p, tuple) else p

    n = async_ps.remote_worker_loop(
        host, port, wid,
        cfg=acfg,
        loss_fn=loss_fn,
        init_fn=struct_init,
        batches=iter(batches_for_worker(wid, local_bs, n_workers)),
        model_state=model_state,
        rng=jax.random.key(FLAGS.seed),
        addrs=shard_addrs,
        ps_replicas=n_replicas,
        layout_version=layout_version,
        # Per-shard pull/push wall-time scalars (shard-imbalance signal).
        metrics_dir=(
            os.path.join(FLAGS.log_dir, f"worker{wid}") if FLAGS.log_dir else None
        ),
        metrics_every=max(1, getattr(FLAGS, "log_every_steps", 20) or 20),
    )
    print(f"WORKER_DONE task={wid} contributed={n}")
    return None


def array_eval_fn(apply_logits: Callable, test: dict[str, np.ndarray], batch_size: int):
    """Standard accuracy eval over array test splits for the FINAL line."""
    import jax

    from ..models import layers

    @jax.jit
    def _acc(p, b):
        return layers.accuracy(apply_logits(p, b), b["label"])

    def eval_fn(params):
        n = len(test["label"])
        ebs = min(batch_size, n)
        accs = [
            float(_acc(params, {k: v[i : i + ebs] for k, v in test.items()}))
            for i in range(0, (n // ebs) * ebs, ebs)
        ]
        return {"test_accuracy": float(np.mean(accs))}

    return eval_fn
