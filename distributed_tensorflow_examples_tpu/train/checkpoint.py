"""Sharding-aware checkpoint/auto-resume (SURVEY.md T3, section 5.4).

Reference stack: ``tf.train.Saver`` sharded V2 checkpoints, where each PS task
writes the variables it owns, ``CheckpointSaverHook`` triggers saves, and
``MonitoredTrainingSession`` restores the newest checkpoint on start.  Here
Orbax provides the same properties natively on a mesh: every host writes only
its local shards (OCDBT), saves are asynchronous (training continues during
the write — the reference's saver blocks the session), and restore re-shards
to whatever mesh layout the restoring job uses (``restore_latest`` takes the
target state/shardings as the template).
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from .state import TrainState

log = logging.getLogger("dtx.checkpoint")


class CheckpointManager:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    - ``save(step, state)``: async, deduped, honors max_to_keep.
    - ``restore_latest(template)``: returns restored state with the
      *template's* shardings (elastic re-shard on restore), or None.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 5,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(os.path.abspath(directory), options=opts)

    def save(self, step: int, state: TrainState, *, force: bool = False) -> bool:
        step = int(step)
        if self._mgr.latest_step() == step:
            return False  # already saved this step (periodic + final overlap)
        return self._mgr.save(step, args=ocp.args.StandardSave(state), force=force)

    def restore_latest(self, template: TrainState) -> TrainState | None:
        step = self._mgr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        log.info("restored checkpoint at step %d", step)
        return restored

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
