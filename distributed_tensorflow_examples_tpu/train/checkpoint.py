"""Sharding-aware checkpoint/auto-resume (SURVEY.md T3, section 5.4).

Reference stack: ``tf.train.Saver`` sharded V2 checkpoints, where each PS task
writes the variables it owns, ``CheckpointSaverHook`` triggers saves, and
``MonitoredTrainingSession`` restores the newest checkpoint on start.  Here
Orbax provides the same properties natively on a mesh: every host writes only
its local shards (OCDBT), saves are asynchronous (training continues during
the write — the reference's saver blocks the session), and restore re-shards
to whatever mesh layout the restoring job uses (``restore_latest`` takes the
target state/shardings as the template).
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from .state import TrainState

log = logging.getLogger("dtx.checkpoint")


def _is_key(x: Any) -> bool:
    """True for typed PRNG key arrays (``jax.random.key``), which Orbax
    cannot serialize directly (their extended dtype has no numpy form)."""
    try:
        return jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key)
    except (AttributeError, TypeError):
        return False


def keys_to_data(state: Any) -> Any:
    """The storable form of a pytree: every typed PRNG key leaf replaced by
    its raw counter data (``jax.random.key_data``).  Non-key leaves pass
    through untouched."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, state
    )


def data_to_keys(restored: Any, template: Any) -> Any:
    """Inverse of :func:`keys_to_data`: leaves that are typed keys in
    ``template`` are re-wrapped (``jax.random.wrap_key_data``) with the
    template leaf's RNG impl, so the restored state round-trips to the
    exact key type the trainer folds per step."""
    return jax.tree.map(
        lambda r, t: (
            jax.random.wrap_key_data(r, impl=jax.random.key_impl(t))
            if _is_key(t)
            else r
        ),
        restored,
        template,
    )


def flat_params_of(state_or_params: Any):
    """The flat f32 parameter vector of a params pytree (or a TrainState —
    its ``params`` half), in the shared ``ps_shard.flat_param_spec`` leaf
    order — the bridge from a restored checkpoint to the serve plane's
    flat-vector substrate (the model registry publishes exactly this
    shape, and a serving replica's ``unflatten`` inverts it)."""
    import numpy as np

    params = getattr(state_or_params, "params", state_or_params)
    leaves = jax.tree.leaves(params)
    if not leaves:
        raise ValueError("no parameter leaves to flatten")
    return np.concatenate(
        [np.asarray(jax.device_get(l), np.float32).reshape(-1) for l in leaves]
    )


class CheckpointManager:
    """Thin policy wrapper over ``ocp.CheckpointManager``.

    - ``save(step, state)``: async, deduped, honors max_to_keep.
    - ``restore_latest(template)``: returns restored state with the
      *template's* shardings (elastic re-shard on restore), or None.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_to_keep: int = 5,
        save_interval_steps: int = 1,
        async_save: bool = True,
    ):
        opts = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(os.path.abspath(directory), options=opts)

    def save(self, step: int, state: TrainState, *, force: bool = False) -> bool:
        step = int(step)
        if self._mgr.latest_step() == step:
            return False  # already saved this step (periodic + final overlap)
        # Typed PRNG keys are stored as their raw key data (JAX's extended
        # key dtype has no numpy/tensorstore form); restore re-wraps them.
        return self._mgr.save(
            step, args=ocp.args.StandardSave(keys_to_data(state)), force=force
        )

    def restore_latest(self, template: TrainState) -> TrainState | None:
        step = self._mgr.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            ocp.utils.to_shape_dtype_struct, keys_to_data(template)
        )
        restored = self._mgr.restore(step, args=ocp.args.StandardRestore(abstract))
        log.info("restored checkpoint at step %d", step)
        return data_to_keys(restored, template)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
