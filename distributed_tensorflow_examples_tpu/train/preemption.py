"""Preemption-aware checkpointing (SURVEY.md section 5.3).

Reference-era recovery is crash-restart: non-chief workers block in
``SessionManager.wait_for_session`` while the chief restores the newest
checkpoint (``session_manager.py:259,419``); modern TF adds
``PreemptionCheckpointHandler`` (``failure_handling.py:337``) which listens
for the platform's preemption signal and saves one final checkpoint before
the instance disappears.

TPU-native shape: Cloud TPU preemptions deliver SIGTERM; this hook installs a
signal handler that flips a flag, and the training loop (which owns the only
safe point to act — between compiled steps) saves a checkpoint and requests a
clean stop.  Resume is the ordinary auto-restore path of ``TrainSession``.
"""

from __future__ import annotations

import logging
import signal
import threading

from .hooks import Hook

log = logging.getLogger("dtx.preemption")


class PreemptionCheckpointHook(Hook):
    """Save-and-stop on SIGTERM/SIGINT (the PreemptionCheckpointHandler
    analog).  Installed while the session runs; restores the previous signal
    handlers at end."""

    def __init__(self, manager, signals=(signal.SIGTERM,)):
        self.mgr = manager
        self.signals = signals
        self._flag = threading.Event()
        self._prev: dict = {}

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def _handler(self, signum, frame):
        log.warning("received signal %d: will checkpoint and stop", signum)
        self._flag.set()

    def begin(self, loop):
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._handler)
            except ValueError:
                # Not the main thread (e.g. tests driving the loop from a
                # worker thread): fall back to manual .trigger().
                log.info("cannot install handler for signal %d here", s)

    def trigger(self) -> None:
        """Manual preemption signal (tests / external watchers)."""
        self._flag.set()

    def after_step(self, loop, metrics):
        if self._flag.is_set() and not loop.should_stop():
            self.mgr.save(loop.step, loop.state, force=True)
            self.mgr.wait()
            loop.request_stop(f"preempted at step {loop.step} (checkpoint saved)")

    def end(self, loop):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except ValueError:
                pass
