"""Experiment runner: flags -> mesh -> sharded state -> session, shared by
every example CLI (SURVEY.md section 7: "one small framework, five thin
example CLIs on top" — inverting the reference's copy-per-script structure).

Wraps the full L0-L3 wiring that each reference script re-implements by hand:
mesh build, distributed bootstrap, sharded-state init, jitted step build,
hook stack (stop/steps-per-sec/logging/summary/checkpoint/profiler), infeed,
and the managed run loop.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Callable, Iterable

import jax
import optax
from jax.sharding import Mesh, PartitionSpec

from ..data import pipeline as pipeline_lib
from ..parallel import MeshSpec, build_mesh, dist
from ..utils.metrics import MetricsWriter
from . import hooks as hooks_lib
from .checkpoint import CheckpointManager
from .loop import TrainSession
from .state import create_sharded_state
from .step import build_eval_step, build_train_step

log = logging.getLogger("dtx.runner")


class Experiment:
    """One configured training run.

    Args mirror what every reference script assembles around its model:
    ``init_fn(rng) -> params | (params, model_state)``, the framework-standard
    ``loss_fn``, an optax optimizer, and sharding rules.
    """

    def __init__(
        self,
        *,
        init_fn: Callable,
        loss_fn: Callable | None,
        optimizer: optax.GradientTransformation,
        rules=(),
        flags,
        mesh: Mesh | None = None,
        extra_hooks: Iterable[hooks_lib.Hook] = (),
        loss_fn_factory: Callable | None = None,
        batch_spec: PartitionSpec | None = None,
    ):
        self.flags = flags
        if getattr(flags, "deterministic", False):
            from ..utils import determinism

            determinism.enable()
        cluster = dist.initialize()
        if cluster.is_ps_task:
            # TF_CONFIG launchers may still start ps/evaluator processes;
            # they hold no SPMD seat — exiting here prevents a duplicate
            # training job from corrupting the real workers' log_dir.
            print(
                f"TF_CONFIG task type {cluster.task_type!r}: parameter "
                "servers are not needed on TPU; exiting 0."
            )
            raise SystemExit(0)
        if getattr(flags, "watchdog", True):
            # Multi-process fail-fast (no-op single-process): a dead peer
            # must crash the job promptly so the per-task supervisor can
            # restart it — see utils.supervisor for the recovery story.
            dist.start_watchdog(grace_s=getattr(flags, "watchdog_grace_secs", 10.0))
        self.mesh = mesh if mesh is not None else build_mesh(MeshSpec.parse(flags.mesh))
        log.info("mesh: %s over %d devices", dict(self.mesh.shape), self.mesh.size)
        if loss_fn is None:
            # Mesh-dependent losses (ring attention needs the mesh object).
            if loss_fn_factory is None:
                raise ValueError("pass loss_fn or loss_fn_factory")
            loss_fn = loss_fn_factory(self.mesh)
        self.batch_spec = batch_spec
        self.optimizer = optimizer
        self.state, self.shardings = create_sharded_state(
            init_fn,
            optimizer,
            jax.random.key(flags.seed),
            mesh=self.mesh,
            rules=rules,
            zero_opt_sharding=getattr(flags, "zero_opt", False),
        )
        self.step_fn = build_train_step(
            loss_fn,
            optimizer,
            mesh=self.mesh,
            state_shardings=self.shardings,
            unroll=flags.unroll,
            batch_spec=batch_spec,
            grad_accum=getattr(flags, "grad_accum", 1),
        )
        self._loss_fn = loss_fn
        self.log_dir = flags.log_dir or None
        self.writer = MetricsWriter(self.log_dir if dist.is_chief() else None)
        self.ckpt = None
        if self.log_dir:
            self.ckpt = CheckpointManager(
                os.path.join(self.log_dir, "ckpt"), save_interval_steps=1
            )
        self.hooks = [
            hooks_lib.StopAtStepHook(flags.train_steps),
            hooks_lib.StepCounterHook(
                every_steps=flags.log_every_steps, batch_size=flags.batch_size
            ),
            hooks_lib.LoggingHook(every_steps=flags.log_every_steps),
            hooks_lib.SummaryHook(self.writer, every_steps=flags.log_every_steps),
        ]
        if self.ckpt is not None:
            self.hooks.append(
                hooks_lib.CheckpointHook(
                    self.ckpt, every_steps=flags.checkpoint_every_steps
                )
            )
            # Preemption (SIGTERM) -> final checkpoint + clean stop; resume
            # is the ordinary auto-restore (SURVEY.md section 5.3).
            from .preemption import PreemptionCheckpointHook

            self.hooks.append(PreemptionCheckpointHook(self.ckpt))
        if getattr(flags, "profile", False) and self.log_dir:
            self.hooks.append(hooks_lib.ProfilerHook(self.log_dir))
        self.hooks.extend(extra_hooks)
        self.session = TrainSession(
            self.step_fn,
            self.state,
            hooks=self.hooks,
            checkpoint_manager=self.ckpt,
            steps_per_call=flags.unroll,
        )

    def batches(self, local_iter, *, unrolled: bool = True):
        """Wrap a per-host local-batch iterator into prefetched global device
        batches (stacking for unroll when configured)."""
        spec = self.batch_spec
        it = local_iter if hasattr(local_iter, "__next__") else iter(local_iter)
        if unrolled and self.flags.unroll > 1:
            it = pipeline_lib.stack_for_unroll(it, self.flags.unroll)
            base = spec if spec is not None else PartitionSpec("data")
            spec = PartitionSpec(None, *base)
        return pipeline_lib.prefetch_to_mesh(it, self.mesh, spec=spec)

    def run(self, local_iter) -> Any:
        """Managed run over the given local-batch iterator; returns final state."""
        final = self.session.run(self.batches(local_iter))
        self.state = final
        return final

    def evaluate(
        self,
        arrays: dict,
        *,
        eval_fn: Callable | None = None,
        batch_size: int | None = None,
    ) -> dict[str, float]:
        """Sharded full-split eval; averages metrics over complete batches."""
        if eval_fn is None:
            _loss = self._loss_fn

            def eval_fn(params, mstate, batch):
                return _loss(params, mstate, batch, jax.random.key(0))[1][1]

        step = build_eval_step(
            eval_fn,
            mesh=self.mesh,
            state_shardings=self.shardings,
            batch_spec=self.batch_spec,
        )
        n = len(next(iter(arrays.values())))
        dp = self.mesh.shape.get("data", 1)
        ebs = min(batch_size or self.flags.batch_size, n // dp * dp)
        # Round down to a multiple of the data-axis size: a --batch_size not
        # divisible by dp (e.g. 100 on an 8-way mesh) must not crash eval
        # after training completed.
        ebs = (ebs // dp) * dp
        if ebs <= 0:
            return {}
        sums: dict[str, float] = {}
        count = 0
        for i in range(0, (n // ebs) * ebs, ebs):
            b = {k: v[i : i + ebs] for k, v in arrays.items()}
            m = step(
                self.state,
                pipeline_lib.as_global(b, self.mesh, spec=self.batch_spec),
            )
            for k, v in m.items():
                sums[k] = sums.get(k, 0.0) + float(v)
            count += 1
        return {k: v / count for k, v in sums.items()}

    def finish(self, **final_metrics) -> None:
        """Print the FINAL line (the contract tests/bench scrape) and close."""
        parts = [f"FINAL step={self.session.step}"]
        # Always present (0.0 when the run was shorter than the counter
        # cadence) — scrapers key on these fields.
        sps = self.session.records.get("steps_per_sec") or 0.0
        parts.append(f"steps_per_sec={sps:.1f}")
        eps = self.session.records.get("examples_per_sec_per_chip") or 0.0
        parts.append(f"examples_per_sec_per_chip={eps:.0f}")
        for k, v in final_metrics.items():
            parts.append(f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}")
        print(" ".join(parts))
        self.writer.close()
        if self.ckpt is not None:
            self.ckpt.close()
        # Announce clean departure: peers' watchdogs must not read this
        # process's end-of-job silence as a crash (finish-time skew between
        # workers can exceed the heartbeat grace).
        dist.stop_watchdog()
