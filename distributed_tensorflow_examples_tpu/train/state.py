"""TrainState: the framework's unit of trainable state.

Replaces the reference's scattered graph state — global_step variable,
model variables placed by ``replica_device_setter``, optimizer slot variables
on PS tasks (SURVEY.md sections 2b D3, 3.1) — with one pytree whose layout is
governed by sharding rules and which checkpointing/restoring treats atomically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel.sharding import ShardingRules, sharding_tree


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array  # int32 scalar — the global_step analog
    params: Any
    opt_state: Any
    model_state: Any  # mutable non-trainable state (e.g. batchnorm stats)
    rng: jax.Array  # per-step randomness source, folded with step


def create_state(init_params_fn: Callable, optimizer, rng: jax.Array) -> TrainState:
    """Host-side (unsharded) state init; for tests and single-chip runs."""
    params, model_state = _split_init(init_params_fn, rng)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        model_state=model_state,
        rng=rng,
    )


def _split_init(init_params_fn, rng):
    out = init_params_fn(rng)
    if isinstance(out, tuple):
        params, model_state = out
    else:
        params, model_state = out, {}
    return params, model_state


#: Lazily probed, cached PER MESH SHAPE: whether this jax/XLA generates
#: the same random bits under a sharded ``out_shardings`` jit as eagerly.
#: Some pinned jaxlibs partition the threefry computation non-invariantly
#: (different counter slices per shard -> different draws, even with
#: ``jax_threefry_partitionable``) — and whether it manifests depends on
#: the MESH (observed: single-axis whole-device meshes stay invariant,
#: multi-axis meshes do not) — which silently breaks every "born-sharded
#: init == eager init" parity contract the tests (and the PS workers'
#: ``init_fn`` template convention) rely on.
_PARTITIONED_RNG_INVARIANT: dict[tuple, bool] = {}


def _partitioned_rng_invariant(mesh: Mesh) -> bool:
    axis = next((a for a, n in mesh.shape.items() if n > 1), None)
    if axis is None:
        return True  # trivial mesh: nothing partitions
    key = tuple(sorted(mesh.shape.items()))
    cached = _PARTITIONED_RNG_INVARIANT.get(key)
    if cached is not None:
        return cached
    # Probe the INIT-SHAPED pattern per non-trivial axis: a stack of
    # per-key draws with its leading dim sharded over that axis — the
    # layer-stacked kernel shape the rule tables produce — at a
    # representative block size (the observed drift is size-dependent:
    # tiny draws partition invariantly while kernel-sized ones do not).
    ok = True
    for axis, n in mesh.shape.items():
        if n <= 1:
            continue

        def mk(r, n=n):
            ks = jax.random.split(r, n)
            return jnp.stack(
                [jax.random.uniform(k, (32, 96)) for k in ks]
            )

        eager = mk(jax.random.key(7))
        sharded = jax.jit(
            mk,
            out_shardings=jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(axis)
            ),
        )(jax.random.key(7))
        if not bool(jnp.all(eager == sharded)):
            ok = False
            break
    _PARTITIONED_RNG_INVARIANT[key] = ok
    if not ok:
        import logging

        logging.getLogger("dtx.state").warning(
            "this jax partitions RNG non-invariantly under sharded "
            "out_shardings on mesh %s; create_sharded_state falls back "
            "to init-then-place (params materialise replicated on the "
            "host first)", dict(mesh.shape),
        )
    return ok


def create_sharded_state(
    init_params_fn: Callable,
    optimizer,
    rng: jax.Array,
    *,
    mesh: Mesh,
    rules: ShardingRules = (),
    auto_shard_min_bytes: int | None = None,
    zero_opt_sharding: bool = False,
    zero_min_elements: int = 65536,
) -> tuple[TrainState, Any]:
    """Initialise the state *directly sharded*: the init function is jitted
    with ``out_shardings`` from the rule table, so large sharded parameters
    (e.g. W4's embedding table) are born distributed in mesh HBM and never
    materialise on one host — the analog of each PS task initialising only its
    own variables.

    ``auto_shard_min_bytes`` opts into the D4 heuristic partitioner
    (``parallel.partitioner.min_max_variable_partitioner``): any leaf NO rule
    matches whose per-model-shard slice would still be at least this many
    bytes gets its leading dim sharded over the ``model`` axis; smaller
    leaves stay replicated.  Explicit rules always win.

    ``zero_opt_sharding`` (ZeRO-1, the T5X/praxis mechanism): every
    still-replicated optimizer-state leaf of >= ``zero_min_elements`` gets
    a dim sharded over the data-parallel axes — ``('slice','data')``
    jointly on multi-slice meshes (HBM divides by the FULL dp degree; the
    implied param all-gather then crosses DCN once per step, same as the
    gradient reduction), falling back to a single axis for dims the joint
    degree doesn't divide.  Params stay replicated — GSPMD then emits
    reduce-scatter(grads) -> sharded optimizer update -> all-gather
    (params), with identical numerics.  The reference has no analog (its
    PS *hosted* slot variables off-device; this is the mesh-era version of
    not paying for optimizer state per replica).

    Returns ``(state, state_shardings)``; the shardings tree is reused as the
    train step's in/out shardings and the checkpoint restore layout.
    """

    def _init(rng):
        params, model_state = _split_init(init_params_fn, rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            model_state=model_state,
            rng=rng,
        )

    default_fn = None
    if auto_shard_min_bytes is not None and mesh.shape.get("model", 1) > 1:
        from ..parallel.partitioner import min_max_variable_partitioner

        decide = min_max_variable_partitioner(auto_shard_min_bytes)
        model_size = mesh.shape["model"]

        def default_fn(path, leaf):
            return decide(
                getattr(leaf, "shape", ()),
                getattr(getattr(leaf, "dtype", None), "itemsize", 4),
                model_size,
            )

    abstract = jax.eval_shape(_init, rng)
    shardings = sharding_tree(abstract, mesh, rules, default_spec_fn=default_fn)
    if zero_opt_sharding:
        # _zero_shard_opt is a no-op when no data-parallel axis exceeds 1.
        shardings.opt_state = _zero_shard_opt(
            shardings.opt_state, abstract.opt_state, mesh, zero_min_elements
        )
    if _partitioned_rng_invariant(mesh):
        state = jax.jit(_init, out_shardings=shardings)(rng)
    else:
        # Value-correct fallback for jaxlibs whose SPMD partitioner draws
        # DIFFERENT random bits under sharded generation (see the probe
        # above): init unsharded — bitwise the eager values — then place
        # onto the rule shardings.  Costs one replicated materialisation
        # of the state on the host; the born-distributed memory property
        # returns automatically on a jax whose partitioned RNG is
        # invariant.
        state = jax.device_put(jax.jit(_init)(rng), shardings)
    return state, shardings


def _zero_shard_opt(opt_shardings, abstract_opt, mesh: Mesh, min_elements: int):
    """Shard replicated optimizer-state leaves over the data axes (ZeRO-1).
    On multi-slice meshes (an explicit 'slice' axis, r4) the slice axis
    joins in — optimizer HBM then divides by the FULL data-parallel degree,
    not just the within-slice part."""
    import math

    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = tuple(
        a for a in ("slice", "data") if mesh.shape.get(a, 1) > 1
    )
    if not axes:
        return opt_shardings
    # Preference order: the full joint degree first, then each single axis
    # — a leaf whose dims don't divide slice*data still gets the partial
    # sharding the single-axis layout allows (no silent replication
    # regression on awkward shapes).
    candidates = [axes] + ([(a,) for a in axes] if len(axes) > 1 else [])

    def one(sh, leaf):
        shape = getattr(leaf, "shape", ())
        if not shape or math.prod(shape) < min_elements:
            return sh
        if any(e is not None for e in sh.spec):
            return sh  # already sharded by a rule (e.g. Megatron TP mirror)
        for cand in candidates:
            dsize = math.prod(mesh.shape[a] for a in cand)
            for d, s in enumerate(shape):
                if s % dsize == 0:
                    spec = [None] * len(shape)
                    spec[d] = cand if len(cand) > 1 else cand[0]
                    return NamedSharding(mesh, P(*spec))
        return sh

    return jax.tree.map(one, opt_shardings, abstract_opt)
