"""TrainState: the framework's unit of trainable state.

Replaces the reference's scattered graph state — global_step variable,
model variables placed by ``replica_device_setter``, optimizer slot variables
on PS tasks (SURVEY.md sections 2b D3, 3.1) — with one pytree whose layout is
governed by sharding rules and which checkpointing/restoring treats atomically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel.sharding import ShardingRules, sharding_tree


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array  # int32 scalar — the global_step analog
    params: Any
    opt_state: Any
    model_state: Any  # mutable non-trainable state (e.g. batchnorm stats)
    rng: jax.Array  # per-step randomness source, folded with step


def create_state(init_params_fn: Callable, optimizer, rng: jax.Array) -> TrainState:
    """Host-side (unsharded) state init; for tests and single-chip runs."""
    params, model_state = _split_init(init_params_fn, rng)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        model_state=model_state,
        rng=rng,
    )


def _split_init(init_params_fn, rng):
    out = init_params_fn(rng)
    if isinstance(out, tuple):
        params, model_state = out
    else:
        params, model_state = out, {}
    return params, model_state


def create_sharded_state(
    init_params_fn: Callable,
    optimizer,
    rng: jax.Array,
    *,
    mesh: Mesh,
    rules: ShardingRules = (),
    auto_shard_min_bytes: int | None = None,
    zero_opt_sharding: bool = False,
    zero_min_elements: int = 65536,
) -> tuple[TrainState, Any]:
    """Initialise the state *directly sharded*: the init function is jitted
    with ``out_shardings`` from the rule table, so large sharded parameters
    (e.g. W4's embedding table) are born distributed in mesh HBM and never
    materialise on one host — the analog of each PS task initialising only its
    own variables.

    ``auto_shard_min_bytes`` opts into the D4 heuristic partitioner
    (``parallel.partitioner.min_max_variable_partitioner``): any leaf NO rule
    matches whose per-model-shard slice would still be at least this many
    bytes gets its leading dim sharded over the ``model`` axis; smaller
    leaves stay replicated.  Explicit rules always win.

    ``zero_opt_sharding`` (ZeRO-1, the T5X/praxis mechanism): every
    still-replicated optimizer-state leaf of >= ``zero_min_elements`` gets
    a dim sharded over the data-parallel axes — ``('slice','data')``
    jointly on multi-slice meshes (HBM divides by the FULL dp degree; the
    implied param all-gather then crosses DCN once per step, same as the
    gradient reduction), falling back to a single axis for dims the joint
    degree doesn't divide.  Params stay replicated — GSPMD then emits
    reduce-scatter(grads) -> sharded optimizer update -> all-gather
    (params), with identical numerics.  The reference has no analog (its
    PS *hosted* slot variables off-device; this is the mesh-era version of
    not paying for optimizer state per replica).

    Returns ``(state, state_shardings)``; the shardings tree is reused as the
    train step's in/out shardings and the checkpoint restore layout.
    """

    def _init(rng):
        params, model_state = _split_init(init_params_fn, rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            model_state=model_state,
            rng=rng,
        )

    default_fn = None
    if auto_shard_min_bytes is not None and mesh.shape.get("model", 1) > 1:
        from ..parallel.partitioner import min_max_variable_partitioner

        decide = min_max_variable_partitioner(auto_shard_min_bytes)
        model_size = mesh.shape["model"]

        def default_fn(path, leaf):
            return decide(
                getattr(leaf, "shape", ()),
                getattr(getattr(leaf, "dtype", None), "itemsize", 4),
                model_size,
            )

    abstract = jax.eval_shape(_init, rng)
    shardings = sharding_tree(abstract, mesh, rules, default_spec_fn=default_fn)
    if zero_opt_sharding:
        # _zero_shard_opt is a no-op when no data-parallel axis exceeds 1.
        shardings.opt_state = _zero_shard_opt(
            shardings.opt_state, abstract.opt_state, mesh, zero_min_elements
        )
    state = jax.jit(_init, out_shardings=shardings)(rng)
    return state, shardings


def _zero_shard_opt(opt_shardings, abstract_opt, mesh: Mesh, min_elements: int):
    """Shard replicated optimizer-state leaves over the data axes (ZeRO-1).
    On multi-slice meshes (an explicit 'slice' axis, r4) the slice axis
    joins in — optimizer HBM then divides by the FULL data-parallel degree,
    not just the within-slice part."""
    import math

    from jax.sharding import NamedSharding, PartitionSpec as P

    axes = tuple(
        a for a in ("slice", "data") if mesh.shape.get(a, 1) > 1
    )
    if not axes:
        return opt_shardings
    # Preference order: the full joint degree first, then each single axis
    # — a leaf whose dims don't divide slice*data still gets the partial
    # sharding the single-axis layout allows (no silent replication
    # regression on awkward shapes).
    candidates = [axes] + ([(a,) for a in axes] if len(axes) > 1 else [])

    def one(sh, leaf):
        shape = getattr(leaf, "shape", ())
        if not shape or math.prod(shape) < min_elements:
            return sh
        if any(e is not None for e in sh.spec):
            return sh  # already sharded by a rule (e.g. Megatron TP mirror)
        for cand in candidates:
            dsize = math.prod(mesh.shape[a] for a in cand)
            for d, s in enumerate(shape):
                if s % dsize == 0:
                    spec = [None] * len(shape)
                    spec[d] = cand if len(cand) > 1 else cand[0]
                    return NamedSharding(mesh, P(*spec))
        return sh

    return jax.tree.map(one, opt_shardings, abstract_opt)
