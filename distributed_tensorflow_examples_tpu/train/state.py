"""TrainState: the framework's unit of trainable state.

Replaces the reference's scattered graph state — global_step variable,
model variables placed by ``replica_device_setter``, optimizer slot variables
on PS tasks (SURVEY.md sections 2b D3, 3.1) — with one pytree whose layout is
governed by sharding rules and which checkpointing/restoring treats atomically.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..parallel.sharding import ShardingRules, sharding_tree


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array  # int32 scalar — the global_step analog
    params: Any
    opt_state: Any
    model_state: Any  # mutable non-trainable state (e.g. batchnorm stats)
    rng: jax.Array  # per-step randomness source, folded with step


def create_state(init_params_fn: Callable, optimizer, rng: jax.Array) -> TrainState:
    """Host-side (unsharded) state init; for tests and single-chip runs."""
    params, model_state = _split_init(init_params_fn, rng)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        model_state=model_state,
        rng=rng,
    )


def _split_init(init_params_fn, rng):
    out = init_params_fn(rng)
    if isinstance(out, tuple):
        params, model_state = out
    else:
        params, model_state = out, {}
    return params, model_state


def create_sharded_state(
    init_params_fn: Callable,
    optimizer,
    rng: jax.Array,
    *,
    mesh: Mesh,
    rules: ShardingRules = (),
    auto_shard_min_bytes: int | None = None,
) -> tuple[TrainState, Any]:
    """Initialise the state *directly sharded*: the init function is jitted
    with ``out_shardings`` from the rule table, so large sharded parameters
    (e.g. W4's embedding table) are born distributed in mesh HBM and never
    materialise on one host — the analog of each PS task initialising only its
    own variables.

    ``auto_shard_min_bytes`` opts into the D4 heuristic partitioner
    (``parallel.partitioner.min_max_variable_partitioner``): any leaf NO rule
    matches whose per-model-shard slice would still be at least this many
    bytes gets its leading dim sharded over the ``model`` axis; smaller
    leaves stay replicated.  Explicit rules always win.

    Returns ``(state, state_shardings)``; the shardings tree is reused as the
    train step's in/out shardings and the checkpoint restore layout.
    """

    def _init(rng):
        params, model_state = _split_init(init_params_fn, rng)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            model_state=model_state,
            rng=rng,
        )

    default_fn = None
    if auto_shard_min_bytes is not None and mesh.shape.get("model", 1) > 1:
        from ..parallel.partitioner import min_max_variable_partitioner

        decide = min_max_variable_partitioner(auto_shard_min_bytes)
        model_size = mesh.shape["model"]

        def default_fn(path, leaf):
            return decide(
                getattr(leaf, "shape", ()),
                getattr(getattr(leaf, "dtype", None), "itemsize", 4),
                model_size,
            )

    abstract = jax.eval_shape(_init, rng)
    shardings = sharding_tree(abstract, mesh, rules, default_spec_fn=default_fn)
    state = jax.jit(_init, out_shardings=shardings)(rng)
    return state, shardings
