"""Hook system: the reference's session-run-hook stack (SURVEY.md T2,
``basic_session_run_hooks.py``) rebuilt for an SPMD loop.

Hooks observe the host-side loop (they never enter the compiled step):

- ``StopAtStepHook``     (ref ``:393``) — stop at a global step.
- ``StepCounterHook``    (ref ``:674``) — steps/sec and examples/sec/chip,
                          the benchmark instrument.
- ``LoggingHook``        (ref ``:169``) — periodic metric logging.
- ``CheckpointHook``     (ref ``:524``) — periodic save via train.checkpoint.
- ``SummaryHook``        (ref ``:793``) — metric series to the metrics writer.
- ``ProfilerHook``       — jax.profiler trace for a step window (SURVEY.md
                          section 5.1).

Citations are to the TF files the reference relies on, per SURVEY.md; the
reference tree itself is an empty mount (SURVEY.md section 0).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable

import jax

log = logging.getLogger("dtx.hooks")


class Hook:
    """Lifecycle: begin(loop) -> [before_step / after_step]* -> end(loop)."""

    def begin(self, loop) -> None: ...

    def before_step(self, loop) -> None: ...

    def after_step(self, loop, metrics: dict[str, Any]) -> None: ...

    def end(self, loop) -> None: ...


class StopAtStepHook(Hook):
    def __init__(self, last_step: int):
        self.last_step = last_step

    def begin(self, loop):
        # An auto-resumed session may already be at/past the target; stopping
        # here prevents re-running a finished job from training extra steps
        # and overwriting its final checkpoint.
        if loop.step >= self.last_step:
            loop.request_stop(f"already at step {loop.step} >= {self.last_step}")

    def after_step(self, loop, metrics):
        if loop.step >= self.last_step:
            loop.request_stop(f"reached step {self.last_step}")


class StepCounterHook(Hook):
    """steps/sec + examples/sec(/chip) counter — the instrument behind the
    headline images/sec/chip metric (BASELINE.md)."""

    def __init__(self, every_steps: int = 100, batch_size: int | None = None):
        self.every = every_steps
        self.batch_size = batch_size
        self._t0 = None
        self._s0 = 0
        self.last_steps_per_sec: float | None = None
        self.last_examples_per_sec_per_chip: float | None = None

    def begin(self, loop):
        # Timing starts at the FIRST after_step, not here: the first step
        # pays XLA compilation (tens of seconds), which would bias every
        # short run's reported steps/sec down (round-1 review).
        self._t0 = None

    def after_step(self, loop, metrics):
        if self._t0 is None:
            self._t0 = time.perf_counter()
            self._s0 = loop.step
            return
        if loop.step - self._s0 < self.every:
            return
        now = time.perf_counter()
        dt = now - self._t0
        steps = loop.step - self._s0
        self.last_steps_per_sec = steps / dt
        msg = f"step {loop.step}: {self.last_steps_per_sec:.1f} steps/sec"
        if self.batch_size:
            eps = self.last_steps_per_sec * self.batch_size
            n_chips = max(1, len(jax.devices()))
            self.last_examples_per_sec_per_chip = eps / n_chips
            msg += (
                f", {eps:.0f} examples/sec"
                f" ({self.last_examples_per_sec_per_chip:.0f}/chip)"
            )
        log.info(msg)
        loop.record(
            steps_per_sec=self.last_steps_per_sec,
            examples_per_sec_per_chip=self.last_examples_per_sec_per_chip,
        )
        self._t0, self._s0 = now, loop.step


class LoggingHook(Hook):
    """Every N steps, fetch the (device) metrics and log them.  The fetch is
    the only host sync in the loop, so its cadence bounds dispatch overlap —
    keep N modest (ref LoggingTensorHook's every_n_iter).  Cadence is
    delta-based so it holds under unroll>1 (step advances by k per call)."""

    def __init__(self, every_steps: int = 100, formatter: Callable | None = None):
        self.every = every_steps
        self.formatter = formatter
        self._last = 0

    def begin(self, loop):
        self._last = loop.step

    def after_step(self, loop, metrics):
        if loop.step - self._last < self.every:
            return
        self._last = loop.step
        host = {k: float(v) for k, v in metrics.items() if _is_scalar(v)}
        if self.formatter:
            log.info(self.formatter(loop.step, host))
        else:
            parts = ", ".join(f"{k}={v:.4f}" for k, v in sorted(host.items()))
            log.info("step %d: %s", loop.step, parts)


class CheckpointHook(Hook):
    """Periodic + final save through a ``checkpoint.CheckpointManager``."""

    def __init__(self, manager, every_steps: int = 1000, every_secs: float | None = None):
        self.mgr = manager
        self.every_steps = every_steps
        self.every_secs = every_secs
        self._last_t = time.monotonic()
        self._last_s = 0

    def begin(self, loop):
        self._last_s = loop.step

    def after_step(self, loop, metrics):
        due = loop.step - self._last_s >= self.every_steps
        if self.every_secs is not None:
            due = due or (time.monotonic() - self._last_t) >= self.every_secs
        if due:
            self.mgr.save(loop.step, loop.state)
            self._last_t = time.monotonic()
            self._last_s = loop.step

    def end(self, loop):
        self.mgr.save(loop.step, loop.state, force=True)
        self.mgr.wait()


class SummaryHook(Hook):
    """Writes scalar metrics to a ``utils.metrics.MetricsWriter`` every N
    steps (ref SummarySaverHook -> event files)."""

    def __init__(self, writer, every_steps: int = 100):
        self.writer = writer
        self.every = every_steps
        self._last = 0

    def begin(self, loop):
        self._last = loop.step

    def after_step(self, loop, metrics):
        if loop.step - self._last < self.every:
            return
        self._last = loop.step
        self.writer.scalars(
            loop.step, {k: float(v) for k, v in metrics.items() if _is_scalar(v)}
        )

    def end(self, loop):
        self.writer.flush()


class ProfilerHook(Hook):
    """Captures a jax.profiler trace for steps [start, start+count)."""

    def __init__(self, log_dir: str, start_step: int = 10, num_steps: int = 5):
        self.log_dir = log_dir
        self.start = start_step
        self.stop = start_step + num_steps
        self._active = False

    def before_step(self, loop):
        # Straddle check: under unroll>1 the observed step advances by
        # steps_per_call and may jump over [start, stop) entirely; activate
        # whenever the upcoming call overlaps the window.
        upcoming_end = loop.step + getattr(loop, "steps_per_call", 1)
        if not self._active and loop.step < self.stop and upcoming_end > self.start:
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def after_step(self, loop, metrics):
        if self._active and loop.step >= self.stop:
            jax.profiler.stop_trace()
            self._active = False

    def end(self, loop):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False


def _is_scalar(v) -> bool:
    try:
        return getattr(v, "ndim", None) == 0 or isinstance(v, (int, float))
    except Exception:
        return False
