"""TrainSession: the ``MonitoredTrainingSession`` analog (SURVEY.md T1).

The reference's session (``monitored_session.py:428``) provides: chief-led
init, worker wait-for-chief, hook dispatch around every ``sess.run``, stop
signalling, and crash-recovery restore from the latest checkpoint.  On a
single-controller SPMD runtime there is no chief/worker split to coordinate —
init happens once, identically, on every process (same seeds => same values;
sharded init via ``create_sharded_state``).  What remains, and lives here:

- hook dispatch around each compiled step (``should_stop`` protocol),
- auto-resume from the newest checkpoint before the first step,
- async-dispatch-aware metric handling (metrics stay on device; hooks decide
  when to block on them).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Iterable, Sequence

from .hooks import Hook
from .state import TrainState

log = logging.getLogger("dtx.loop")


class TrainSession:
    """Runs ``state, metrics = step_fn(state, batch)`` until a hook requests
    stop.

    Usage (mirrors the reference loop shape, SURVEY.md section 3.1)::

        session = TrainSession(step_fn, state, hooks=[StopAtStepHook(1000)])
        session.run(batches)          # or: step-at-a-time via run_step
    """

    def __init__(
        self,
        step_fn: Callable,
        state: TrainState,
        *,
        hooks: Sequence[Hook] = (),
        checkpoint_manager=None,
        steps_per_call: int = 1,
    ):
        self.step_fn = step_fn
        self.state = state
        self.hooks = list(hooks)
        self.ckpt = checkpoint_manager
        self.steps_per_call = steps_per_call
        self._stop_reason: str | None = None
        self.records: dict[str, Any] = {}
        self.last_metrics: dict[str, Any] = {}
        # Host-side step mirror: reading state.step would block on the
        # freshly-dispatched device computation every step, serialising the
        # pipeline.  Synced from the device only at begin/restore.
        self._host_step = int(state.step)

    # -- MonitoredSession-compatible surface ---------------------------------

    def should_stop(self) -> bool:
        return self._stop_reason is not None

    def request_stop(self, reason: str = "") -> None:
        if self._stop_reason is None:
            self._stop_reason = reason or "requested"

    @property
    def step(self) -> int:
        """Host-side mirror of the global step (no device sync)."""
        return self._host_step

    def record(self, **kv) -> None:
        """Hooks publish summary values here (e.g. steps/sec) for callers."""
        self.records.update({k: v for k, v in kv.items() if v is not None})

    # -- lifecycle -----------------------------------------------------------

    def _begin(self):
        if self.ckpt is not None:
            restored = self.ckpt.restore_latest(self.state)
            if restored is not None:
                self.state = restored
                self._host_step = int(restored.step)
                self.record(resumed_at=self._host_step)
                log.info("auto-resumed at step %d", self.step)
        for h in self.hooks:
            h.begin(self)

    def _end(self):
        for h in self.hooks:
            h.end(self)

    def run_step(self, batch) -> dict[str, Any]:
        for h in self.hooks:
            h.before_step(self)
        self.state, metrics = self.step_fn(self.state, batch)
        self._host_step += self.steps_per_call
        self.last_metrics = metrics
        for h in self.hooks:
            h.after_step(self, metrics)
        return metrics

    def run(self, batches: Iterable) -> TrainState:
        """Full managed run: begin (restore + hooks), loop, end (final save)."""
        self._begin()
        try:
            if not self.should_stop():
                for batch in batches:
                    self.run_step(batch)
                    if self.should_stop():
                        break
                else:
                    self.request_stop("data exhausted")
        finally:
            self._end()
        log.info("training stopped: %s", self._stop_reason)
        return self.state
