"""Online inference plane (r10): param-tracking model replicas with
dynamic micro-batching over the PS wire.

The first consumer of the parameter-store plane that is not a training
worker — the TensorFlow architecture paper's "servers hand versioned
params to any consumer" substrate, applied to serving:

- ``model_server`` — :class:`ModelReplicaServer` hot-tracks training by
  polling the (sharded) PS with versioned pulls, micro-batches predict
  requests into one jitted apply, stamps responses with the served
  ``model_step``, and sheds load with an explicit OVERLOAD status; hosted
  as the supervised ``--job_name=serve`` cluster role.
- ``batcher`` — the model-agnostic dynamic micro-batcher + admission
  control.
- ``client`` — :class:`ServeClient` (deadlines / backoff reconnect /
  ``<role>_sv`` fault injection) and :class:`ServePool` (round-robin over
  N replicas with unhealthy-replica ejection; ``set_addrs`` reconciles an
  elastic membership list).
- ``autoscale`` (r14) — :class:`ServeAutoscaler` grows/shrinks an
  in-process replica set against measured queue depth / p99, and
  :class:`LeaseServeDiscovery` follows the membership lease registry so
  pools track an elastic replica set with no static flags.
- ``registry`` (r19) — :class:`ModelRegistry`: immutable ``(name,
  version)`` flat-param snapshots with fsync'd atomic manifests and
  lease-style pins; replicas PIN a version instead of hot-tracking, and
  GC can never reclaim a version a live replica serves.
- ``deploy`` (r19) — :class:`RollingDeploy`: canary/promote/rollback
  version flips over a live pool with zero failed predicts
  (start-then-stop surge + lease-release-before-stop), and
  :func:`canary_verdict`, the promote-or-rollback policy over the pool's
  per-version accounting.
"""

from .autoscale import (  # noqa: F401
    LeaseServeDiscovery,
    ServeAutoscaler,
    make_replica_factory,
)
from .batcher import DynamicBatcher, Overloaded, SlotBatcher  # noqa: F401
from .client import (  # noqa: F401
    ServeClient,
    ServeDeadlineError,
    ServeError,
    ServeOverloadError,
    ServePool,
    ServeRejectedError,
    ServeSessionError,
    ServeUnavailableError,
)
from .deploy import (  # noqa: F401
    RollingDeploy,
    canary_verdict,
    make_pinned_factory,
)
from .model_server import (  # noqa: F401
    ModelReplicaServer,
    host_serve_task,
)
from .registry import ModelRegistry, RegistryError  # noqa: F401
