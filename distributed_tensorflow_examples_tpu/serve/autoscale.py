"""Autoscaling serve pool: replicas grow/shrink against measured load (r14).

The serve plane so far is FIXED-SIZE: ``--serve_hosts`` pins the replica
set at launch.  This module closes the elasticity loop the membership
leases (``parallel/membership.py``) enable:

- :class:`ServeAutoscaler` owns a set of in-process
  :class:`~serve.model_server.ModelReplicaServer` replicas and sizes it
  against MEASURED load — the batcher's in-system depth and the served
  p99 from the r13 telemetry instruments each replica already exports.
  Scale-up adds a replica (which announces itself in the lease registry
  and starts hot-tracking the PS with zero coordination); scale-down
  stops the newest replica AFTER dropping it from discovery, so clients
  rotate off it first — and even a predict caught in-flight on a
  stopping replica just retries on a peer (:class:`serve.ServePool`'s
  ejection/rotation; predict is pure), which is what makes scale-down
  zero-failed-requests by construction.
- :class:`LeaseServeDiscovery` is the client half: it polls the lease
  registry for ``kind="serve"`` members and reconciles a ``ServePool``
  onto the live set (``ServePool.set_addrs``), so an elastic pool is
  followed by its clients with no static flag anywhere.

Decisions are damped (``settle_polls`` consecutive over/under-load polls
before acting) so one bursty batch can't flap the pool.
"""

from __future__ import annotations

import logging
import threading
import time

from ..utils import faults, telemetry
from . import model_server as msrv_lib

log = logging.getLogger("dtx.autoscale")

_OBS_UP = telemetry.REGISTRY.counter("autoscale/scale_ups")
_OBS_DOWN = telemetry.REGISTRY.counter("autoscale/scale_downs")


class ServeAutoscaler:
    """Grow/shrink an in-process replica set against queue depth and p99.

    ``make_server(index) -> ModelReplicaServer``   replica factory (the
        caller closes over init_fn/predict_fn/ps_addrs and any knobs);
        the autoscaler owns the returned servers' lifecycles.
    ``min_replicas`` / ``max_replicas``            pool bounds.
    ``queue_high``      mean in-system requests per replica above which
                        the pool is overloaded (scale up).
    ``queue_low``       mean depth below which the pool is idle (scale
                        down, never under ``min_replicas``).
    ``p99_high_ms``     optional latency SLO: a measured p99 above it
                        counts as overload even at low queue depth.
    ``settle_polls``    consecutive polls a condition must hold before
                        acting (damping).
    """

    def __init__(
        self,
        make_server,
        *,
        min_replicas: int = 1,
        max_replicas: int = 4,
        queue_high: float = 8.0,
        queue_low: float = 1.0,
        p99_high_ms: float | None = None,
        settle_polls: int = 3,
        poll_s: float = 1.0,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"bad replica bounds [{min_replicas}, {max_replicas}]"
            )
        self._make = make_server
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.p99_high_ms = p99_high_ms
        self.settle_polls = max(1, int(settle_polls))
        self.poll_s = float(poll_s)
        self.scale_ups = 0
        self.scale_downs = 0
        self._hot_polls = 0
        self._cold_polls = 0
        self._lock = threading.Lock()
        self._servers: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for _ in range(self.min_replicas):
            self._grow_locked()

    # -- pool surface --------------------------------------------------------

    def addrs(self) -> list[tuple[str, int]]:
        with self._lock:
            return [("127.0.0.1", s.port) for s in self._servers]

    @property
    def num_replicas(self) -> int:
        with self._lock:
            return len(self._servers)

    # -- the control loop ----------------------------------------------------

    def _measurements(self) -> tuple[float, float]:
        """(mean in-system depth per replica, max p99 ms) across the
        pool, read from the replicas' own instruments — no scrape round
        trip for an in-process pool."""
        with self._lock:
            servers = list(self._servers)
        if not servers:
            return 0.0, 0.0
        depth = sum(s._batcher.stats()["inflight"] for s in servers)
        p99 = max(
            s.latency.percentile_scalars("serve").get(
                "serve/latency_p99_ms", 0.0
            )
            for s in servers
        )
        return depth / len(servers), p99

    def poll_once(self) -> str:
        """One control decision: 'up', 'down' or 'hold' (tests drive this
        directly for determinism; the background loop just paces it)."""
        depth, p99 = self._measurements()
        hot = depth > self.queue_high or (
            self.p99_high_ms is not None and p99 > self.p99_high_ms
        )
        cold = depth < self.queue_low
        self._hot_polls = self._hot_polls + 1 if hot else 0
        self._cold_polls = self._cold_polls + 1 if cold else 0
        with self._lock:
            n = len(self._servers)
        if self._hot_polls >= self.settle_polls and n < self.max_replicas:
            self._hot_polls = 0
            self.scale_up(depth=depth, p99=p99)
            return "up"
        if self._cold_polls >= self.settle_polls and n > self.min_replicas:
            self._cold_polls = 0
            self.scale_down(depth=depth)
            return "down"
        return "hold"

    def _grow_locked(self) -> None:
        self._servers.append(self._make(len(self._servers)))

    def scale_up(self, **why) -> tuple[str, int]:
        """Add one replica; returns its address.  The new replica leases
        itself into the registry and hot-tracks the PS — discovery (and
        dtxtop) sees it within one heartbeat, with zero coordination."""
        with self._lock:
            self._grow_locked()
            addr = ("127.0.0.1", self._servers[-1].port)
        self.scale_ups += 1
        _OBS_UP.inc()
        faults.log_event(
            "autoscale_up", replicas=self.num_replicas,
            **{k: round(float(v), 3) for k, v in why.items()},
        )
        return addr

    def scale_down(self, **why) -> tuple[str, int] | None:
        """Retire the newest replica: release its lease FIRST (discovery
        drops it from the rotation), then stop it.  A request caught
        in-flight retries on a peer — the pool's ejection/rotation makes
        the drain invisible to callers."""
        with self._lock:
            if len(self._servers) <= self.min_replicas:
                return None
            server = self._servers.pop()
        addr = ("127.0.0.1", server.port)
        server.stop()  # stop() releases the lease before closing conns
        self.scale_downs += 1
        _OBS_DOWN.inc()
        faults.log_event(
            "autoscale_down", replicas=self.num_replicas,
            **{k: round(float(v), 3) for k, v in why.items()},
        )
        return addr

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Run the control loop in the background (``poll_s`` cadence)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dtx-autoscale"
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — sizing must never crash serving
                log.exception("autoscaler poll failed")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        with self._lock:
            servers, self._servers = list(self._servers), []
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass


def make_replica_factory(init_fn, predict_fn, ps_addrs, **server_kw):
    """The standard ``make_server`` for :class:`ServeAutoscaler`: each
    replica binds an ephemeral port, leases itself as ``<role>-es<i>``
    (elastic-serve) and inherits the caller's batcher/refresh knobs.

    Registry pinning (r19) composes: pass ``registry_dir=`` +
    ``model_version=`` through ``server_kw`` and every autoscaled
    replica pins the SAME immutable version — demand-driven scale-up
    cannot drift a versioned pool (version flips are
    :class:`serve.deploy.RollingDeploy`'s job, not the autoscaler's)."""
    base_role = faults.current_role() or "serve"

    def make(i: int) -> msrv_lib.ModelReplicaServer:
        return msrv_lib.ModelReplicaServer(
            init_fn, predict_fn, list(ps_addrs), port=0,
            role=f"{base_role}-es{i}", **server_kw,
        )

    return make


class LeaseServeDiscovery:
    """Follows the lease registry's ``kind="serve"`` members and
    reconciles a :class:`serve.ServePool` onto the live set — the client
    half of the elastic pool.  Keeps the LAST non-empty set when the
    registry momentarily answers empty mid-failover (an empty rotation
    would fail requests a degraded-but-alive pool could still serve)."""

    def __init__(
        self, ps_addrs, pool, *, poll_s: float = 1.0,
        role: str | None = None, follow_epoch: bool = True,
        layout_version: int = 0,
    ):
        from ..parallel import membership

        self.pool = pool
        self.updates = 0

        def _reconcile(_m=None) -> None:
            watcher = getattr(self, "_watcher", None)
            if watcher is None:  # first poll racing the ctor's assignment
                return
            live = sorted(
                m["addr"] for m in watcher.members() if m.get("addr")
            )
            addrs = [
                a
                for a in (membership.unpack_addr(x) for x in live)
                if a is not None
            ]
            if addrs:
                self.pool.set_addrs(addrs)
                self.updates += 1

        # follow_epoch (r15): the registry moves with a live PS reshard;
        # chasing the committed epoch keeps replica discovery alive
        # across an N→M transition (a pre-r15 coordinator answers the
        # poll -2 and nothing changes).
        self._watcher = membership.LeaseWatcher(
            list(ps_addrs), kind="serve", poll_s=poll_s,
            on_join=_reconcile, on_leave=_reconcile, role=role,
            follow_epoch=follow_epoch, layout_version=layout_version,
        )

    def poll_once(self) -> None:
        self._watcher.poll_once()

    def close(self) -> None:
        self._watcher.close()
