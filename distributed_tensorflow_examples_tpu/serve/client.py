"""Resilient serving clients: per-replica transport + replica pool (r10).

:class:`ServeClient` is the PR 1 discipline applied to the serving wire —
per-op deadlines, exponential-backoff reconnect bounded by
``reconnect_deadline_s``, ``DTX_FAULT_PLAN`` injection under the client
role ``<role>_sv`` — over the shared ``parallel/wire.py`` framing with the
``msrv`` HELLO service identity (a wrong-service dial fails loudly naming
both ends).  Predict is PURE (same inputs, same published params, same
outputs), so replaying it after a reconnect is always safe — the simplest
replay story of the three wires.

:class:`ServePool` is the load-balancing layer: round-robin over N
replicas, with unhealthy-replica EJECTION (a transport failure benches the
replica for ``eject_s`` and the request retries on a peer immediately) and
explicit backoff on OVERLOAD / NO_MODEL answers (admission control means
the replica is alive but shedding — rotate, don't hammer).  Under a
replica kill + supervised restart, the pool absorbs the gap: requests keep
succeeding on the surviving replicas, and the healed replica rejoins the
rotation when its ejection expires — the "zero failed client requests"
contract the fault tests pin.

r18 (graceful degradation): both layers run the shared retry discipline
(``parallel/retry.py``).  A replica's RETRY_LATER shed answer carries its
own backoff hint in the status; the pool HONORS it — the shedding replica
benches for the hinted window and, once a rotation sweep has seen only
sheds (pool-WIDE overload), the next attempt waits a jittered hint first
instead of re-hammering the rotation at line rate (rotation must not
amplify an overload).  Transport replays and shed retries spend a
token-bucket retry budget; per-address circuit breakers fail dead peers
fast; every backoff is jittered so recovering clients decorrelate.
"""

from __future__ import annotations

import json
import socket
import threading
import time

import numpy as np

from ..parallel import retry, tenancy, wire
from ..utils import faults, telemetry
from ..utils.metrics import LatencyRecorder
from .model_server import (
    BAD_SESSION, ERR, NO_DECODER, NO_MODEL, OVERLOAD, SRV_DECODE_CLOSE,
    SRV_DECODE_NEXT, SRV_DECODE_OPEN, SRV_PREDICT, SRV_SHUTDOWN, SRV_STATS,
)


class ServeError(RuntimeError):
    """A serving op failed terminally (transport unrecoverable or the
    replica rejected the request)."""


class ServeDeadlineError(ServeError):
    """Reconnect/retry budget exhausted: no replica answered in time."""


class ServeOverloadError(ServeError):
    """The replica's admission control refused the request (queue full):
    back off or try another replica.  ``retry_after_s`` is the backoff
    hint the shed answer carried (r18: the RETRY_LATER band packs it into
    the status; the legacy OVERLOAD code point carries none → 0.0)."""

    def __init__(self, msg: str, retry_after_s: float = 0.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ServeUnavailableError(ServeError):
    """The replica is up but has not pulled a published snapshot yet
    (warming after a restart, or the chief has not published)."""


class ServeRejectedError(ServeError):
    """The replica ANSWERED and rejected the request itself (malformed
    inputs, apply error) — the transport is fine and every peer would
    answer the same, so pools must surface this to the caller instead of
    ejecting the healthy replica and replaying the bad request."""


class ServeSessionError(ServeError):
    """A decode session id the replica no longer knows (expired by the
    idle sweep, lost to a replica restart, or never existed) — the caller
    re-opens a session rather than retrying the poll."""


class ServeClient:
    """One TCP connection to a model replica (requests serialized on it).

    Fault-plan role: ``<process role>_sv`` by default, so ``DTX_FAULT_PLAN``
    specs can target serving connections specifically (``role=client0_sv``)
    while broad globs still match every transport of a process.
    """

    def __init__(
        self, host: str, port: int, *, op_timeout_s: float | None = 30.0,
        reconnect_deadline_s: float = 60.0, backoff_s: float = 0.25,
        role: str | None = None, tenant: str = tenancy.DEFAULT_TENANT,
    ):
        self._host, self._port = host, port
        # The tenant every request of this client is tagged with (r20):
        # the default tenant tags nothing — byte-identical frames against
        # any pre-tenant replica.
        self.tenant = (
            tenant if tenant == tenancy.DEFAULT_TENANT
            else tenancy.check_tenant(tenant)
        )
        self._op_timeout = op_timeout_s
        self._reconnect_deadline = reconnect_deadline_s
        self._backoff = backoff_s
        self.role = role if role is not None else (
            (faults.current_role() or "client") + "_sv"
        )
        self._injector = faults.client_injector(self.role)
        # Shared retry discipline (r18): transport replays spend this
        # token-bucket budget; exhaustion surfaces as ServeDeadlineError
        # plus a flight-recorder event (parallel/retry.py).
        self._budget = retry.RetryBudget()
        self._lock = threading.RLock()
        self._sock: socket.socket | None = None
        self._hdr = bytearray(wire.RESP_HDR.size)
        # The served registry version (r19): learned from the msrv HELLO
        # version word at connect (0 = hot-tracking / pre-r19 replica),
        # refreshed per response via the SRV_VERSION_FIELD stamp — pools
        # read both for canary routing and per-version accounting.
        self.server_model_version = 0
        self.last_model_version = -1
        try:
            self._connect()
        except OSError:
            if self._reconnect_deadline <= 0:
                raise
            self._recover(time.monotonic() + self._reconnect_deadline)

    # -- transport -----------------------------------------------------------

    def _connect(self) -> None:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._op_timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        status, tag = self._attempt(
            wire.HELLO_OP, a=wire.WIRE_VERSION,
            b=wire.pack_hello_b(wire.WIRE_DTYPES["f32"], service="msrv"),
        )
        err = wire.hello_failure(
            status, tag, service="msrv", host=self._host, port=self._port
        )
        if err is not None:
            self._sever()
            raise ServeError(err)
        _tag4, self.server_model_version = wire.unpack_hello_tag(tag)

    def _sever(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._reconnect_deadline = 0.0
        self._sever()

    def _attempt(
        self, op: int, name: str = "", a: int = 0, b: int = 0, *,
        payload_bufs: list | None = None, batch: bool = False,
    ):
        """One send/recv round trip; severs the socket on ANY transport
        failure.  ``payload_bufs``: a pre-encoded batch buffer list (wire
        codec) sent zero-copy via scatter/gather ``sendmsg``."""
        if self._sock is None:
            raise ConnectionError("not connected")
        # The ONE client-side tagging point (r20): every data-plane op of
        # a non-default tenant carries its tenant in the name operand —
        # never HELLO, the version-discovery frame (same reasoning as the
        # deadline stamp below).
        if self.tenant != tenancy.DEFAULT_TENANT and op != wire.HELLO_OP:
            name = tenancy.tag_name(name, self.tenant)
        try:
            self._sock.settimeout(self._op_timeout)
            nbytes = wire.encoded_nbytes(payload_bufs) if payload_bufs else 0
            # Deadline propagation (r18): the remaining per-op budget
            # rides in the frame header, so the replica sheds a predict
            # this client has already abandoned instead of batching it.
            # Safe unconditionally: every ServeClient connection HELLOs
            # (v4 confirmed) before any other op — except HELLO itself.
            hdr = wire.pack_request(
                op, name, a, b, nbytes,
                deadline_ms=(
                    0 if self._op_timeout is None or op == wire.HELLO_OP
                    else max(1, int(self._op_timeout * 1000))
                ),
            )
            wire.send_frames(self._sock, [hdr] + (payload_bufs or []))
            head = memoryview(self._hdr)
            wire.recv_exact(self._sock, head)
            status, rbytes = wire.RESP_HDR.unpack(self._hdr)
            if not rbytes:
                return status, None
            if batch:
                return status, wire.read_batch(self._sock, rbytes)
            buf = bytearray(rbytes)
            wire.recv_exact(self._sock, memoryview(buf))
            return status, bytes(buf)
        except OSError:
            self._sever()
            raise

    def _recover(self, t_end: float) -> None:
        attempt = 0
        immediate = False
        while True:
            if attempt and not immediate:
                # Jittered backoff (r18): recovering peers decorrelate
                # their re-dials instead of re-arriving in lockstep.
                delay = retry.jittered(self._backoff, attempt - 1, cap_s=2.0)
                time.sleep(min(delay, max(0.0, t_end - time.monotonic())))
            immediate = False
            if time.monotonic() >= t_end:
                faults.log_event(
                    "reconnect_gave_up", role=self.role, host=self._host,
                    port=self._port, attempts=attempt,
                )
                telemetry.dump_flight_recorder("reconnect_gave_up")
                raise ServeDeadlineError(
                    f"model replica at {self._host}:{self._port} unreachable "
                    f"for {self._reconnect_deadline:.0f}s ({attempt} attempts)"
                )
            attempt += 1
            # Per-address circuit breaker (r18, process-wide): a freshly-
            # proven-dead replica fails fast for its open window instead
            # of burning another connect timeout.
            breaker = retry.breaker_for((self._host, self._port))
            if not breaker.allow():
                breaker.wait_for_probe(t_end)
                immediate = True  # the wait was this attempt's pacing
                continue
            try:
                self._connect()
            except OSError:
                breaker.on_failure()
                self._sever()
                continue
            breaker.on_success()
            faults.log_event("reconnected", role=self.role, attempts=attempt)
            return

    def call(
        self, op: int, name: str = "", a: int = 0, b: int = 0, *,
        payload_bufs: list | None = None, batch: bool = False,
    ):
        """One request/response; recovers + replays on transport failure
        (every SRV op is pure/idempotent, so replay is always safe).  A
        replay spends the shared retry budget (r18): a storm of failing
        ops cannot replay unboundedly."""
        with self._lock:
            if self._injector is not None and self._injector.before_op(op):
                self._sever()  # injected drop_conn
            t_end = None
            while True:
                if self._sock is not None:
                    try:
                        got = self._attempt(
                            op, name, a, b, payload_bufs=payload_bufs,
                            batch=batch,
                        )
                    except OSError as e:
                        if self._reconnect_deadline <= 0:
                            raise ServeError(
                                f"serve op {op} failed: {e!r}"
                            ) from e
                        faults.log_event(
                            "conn_lost", role=self.role, op_code=op,
                            error=type(e).__name__,
                        )
                    else:
                        self._budget.on_success()
                        return got
                elif self._reconnect_deadline <= 0:
                    raise ServeError(f"serve op {op} failed: not connected")
                if t_end is None:
                    t_end = time.monotonic() + self._reconnect_deadline
                if not self._budget.try_spend():
                    raise ServeDeadlineError(
                        f"replica at {self._host}:{self._port} retry budget "
                        f"exhausted replaying op {op}"
                    )
                self._recover(t_end)

    # -- ops -----------------------------------------------------------------

    def predict(self, inputs: dict) -> tuple[int, dict[str, np.ndarray]]:
        """One predict round trip: ``(model_step, outputs)``.  The step is
        the published update the replica served this answer from.  Raises
        :class:`ServeOverloadError` / :class:`ServeUnavailableError` on the
        explicit shed statuses (callers/pools back off or rotate)."""
        bufs = wire.encode_batch(inputs)
        status, out = self.call(SRV_PREDICT, payload_bufs=bufs, batch=True)
        hint_ms = wire.retry_after_ms(status)
        if hint_ms is not None:
            # r18: the replica SHED this predict (admission control —
            # batcher queue full, dispatch bound, or queue-deadline
            # expiry) and the status carries its own backoff hint.
            raise ServeOverloadError(
                f"replica {self._host}:{self._port} overloaded "
                f"(retry after {hint_ms}ms)",
                retry_after_s=hint_ms / 1e3,
            )
        if status == OVERLOAD:
            # Legacy code point (pre-r18 replicas): no hint.
            raise ServeOverloadError(
                f"replica {self._host}:{self._port} overloaded"
            )
        if status == NO_MODEL:
            raise ServeUnavailableError(
                f"replica {self._host}:{self._port} has no model yet"
            )
        if status == ERR:
            # The server core's loud handler-failure band (r17): the
            # replica answered — an apply/handler exception server-side,
            # not a transport fault — so the typed rejection names where
            # the traceback lives instead of reading as "bad status -2".
            raise ServeRejectedError(
                "predict failed server-side (ERR: apply/handler error — "
                "see the replica's log)"
            )
        if status < 0 or out is None:
            raise ServeRejectedError(f"predict rejected: {status}")
        return status, self._strip_version(out)

    def _strip_version(self, out: dict) -> dict:
        """Pop the per-response version stamp (r19) into
        ``last_model_version`` — user code sees only its own fields."""
        ver = out.pop(wire.SRV_VERSION_FIELD, None)
        if ver is not None:
            self.last_model_version = int(np.asarray(ver).reshape(()))
        return out

    def _decode_status_check(self, status: int) -> None:
        """The shared decode-wire error mapping (every status a replica
        can answer on the DECODE ops gets its typed client error)."""
        hint_ms = wire.retry_after_ms(status)
        if hint_ms is not None:
            raise ServeOverloadError(
                f"replica {self._host}:{self._port} shed the decode op "
                f"(retry after {hint_ms}ms)", retry_after_s=hint_ms / 1e3,
            )
        if status == NO_MODEL:
            raise ServeUnavailableError(
                f"replica {self._host}:{self._port} has no model yet"
            )
        if status == NO_DECODER:
            raise ServeRejectedError(
                f"replica {self._host}:{self._port} serves no decode path "
                "(predict-only model)"
            )
        if status == BAD_SESSION:
            raise ServeSessionError(
                f"replica {self._host}:{self._port} does not know this "
                "decode session (expired, or lost to a restart) — re-open"
            )
        if status < 0:
            raise ServeRejectedError(f"decode op rejected: {status}")

    def decode_open(self, prompt, max_new_tokens: int) -> int:
        """Open one stepped-decode session (greedy continuation of
        ``prompt``, a 1-D int32 token array); returns the session id.
        A transport replay can orphan a server-side session — the
        replica's idle sweep reclaims it, so replay stays safe."""
        bufs = wire.encode_batch({"prompt": np.asarray(prompt, np.int32)})
        status, _ = self.call(
            SRV_DECODE_OPEN, a=int(max_new_tokens), payload_bufs=bufs,
        )
        self._decode_status_check(status)
        return status

    def decode_next(self, session: int, cursor: int = 0):
        """Poll a session's token stream from ``cursor`` (tokens already
        received): ``(tokens, done, model_step)``.  Cursor-addressed, so
        replaying the poll after a reconnect re-reads instead of
        double-draining."""
        status, out = self.call(
            SRV_DECODE_NEXT, a=int(session), b=int(cursor), batch=True,
        )
        self._decode_status_check(status)
        out = self._strip_version(out)
        return (
            np.asarray(out["tokens"], np.int32).reshape(-1),
            bool(np.asarray(out["done"]).reshape(-1)[0]),
            status,
        )

    def decode_close(self, session: int) -> None:
        """Release a session server-side (idempotent)."""
        self.call(SRV_DECODE_CLOSE, a=int(session))

    def generate(
        self, prompt, max_new_tokens: int, *, poll_s: float = 0.005,
        deadline_s: float = 120.0,
    ) -> np.ndarray:
        """Convenience client for the whole stream: open, poll the token
        stream to completion, close; returns the generated int32 tokens
        (the continuation only — the prompt is not echoed)."""
        sid = self.decode_open(prompt, max_new_tokens)
        tokens: list[int] = []
        try:
            t_end = time.monotonic() + deadline_s
            while True:
                got, done, _step = self.decode_next(sid, cursor=len(tokens))
                tokens.extend(int(t) for t in got)
                if done:
                    return np.asarray(tokens, np.int32)
                if time.monotonic() >= t_end:
                    raise ServeDeadlineError(
                        f"decode session {sid} incomplete after "
                        f"{deadline_s:.0f}s ({len(tokens)} tokens)"
                    )
                time.sleep(poll_s)
        finally:
            try:
                self.decode_close(sid)
            except ServeError:
                pass  # best-effort release; the idle sweep is the backstop

    def stats(self) -> dict:
        status, raw = self.call(SRV_STATS)
        if status == ERR:
            raise ServeRejectedError(
                "stats failed server-side (ERR: handler error — see the "
                "replica's log)"
            )
        if status != 0 or raw is None:
            raise ServeRejectedError(f"stats rejected: {status}")
        return json.loads(raw)

    def shutdown_server(self) -> None:
        self.call(SRV_SHUTDOWN)


class ServePool:
    """Round-robin load balancer over N replicas with unhealthy-replica
    ejection.  Per-replica clients run FAIL-FAST (no per-client reconnect
    budget): the pool itself is the recovery layer — a failed attempt
    benches that replica for ``eject_s`` and immediately retries on a peer,
    which converts a replica kill into added latency on one request rather
    than an error.  ``deadline_s`` bounds one logical predict across every
    retry; it should comfortably cover a supervised replica restart."""

    def __init__(
        self, addrs: list[tuple[str, int]], *, role: str | None = None,
        op_timeout_s: float | None = 10.0, eject_s: float = 1.0,
        deadline_s: float = 60.0, backoff_s: float = 0.05,
        tenant: str = tenancy.DEFAULT_TENANT,
    ):
        if not addrs:
            raise ValueError("need at least one replica address")
        # The pool's tenant (r20): forwarded to every per-replica client,
        # so each predict is tagged and the replicas' admission control /
        # accounting attribute this pool's traffic to it.
        self.tenant = (
            tenant if tenant == tenancy.DEFAULT_TENANT
            else tenancy.check_tenant(tenant)
        )
        self.addrs = list(addrs)
        self.role = role if role is not None else (
            (faults.current_role() or "client") + "_sv"
        )
        self._op_timeout = op_timeout_s
        self._eject_s = eject_s
        self._deadline = deadline_s
        self._backoff = backoff_s
        n = len(self.addrs)
        self._clients: list[ServeClient | None] = [None] * n
        self._eject_until = [0.0] * n
        # Per-replica served registry version (r19): learned from the
        # HELLO version word at dial and refreshed per response; None =
        # not yet dialed.  The canary lane keys off it.
        self._ver: list[int | None] = [None] * n
        self._rr = 0
        self._lock = threading.Lock()
        # Canary routing (r19): (version, weight) — that fraction of
        # picks routes to replicas serving ``version``, the rest to the
        # stable lane.  None = plain round-robin.
        self._canary: tuple[int, float] | None = None
        self._canary_acc = 0.0
        # Per-version accounting (r19): ok/error counts + a latency ring
        # per served version — the promote-or-rollback evidence
        # (serve.deploy.canary_verdict consumes version_stats()).
        self._vstats: dict[int, dict] = {}
        # Shared retry discipline (r18): every cross-replica retry spends
        # this budget — a pool cannot convert one overload into an
        # unbounded rotation storm.
        self._budget = retry.RetryBudget()
        self.retries = 0
        self.ejections = 0
        self.overload_backoffs = 0
        self.last_replica = -1
        self.last_version = -1

    def set_canary(self, version: int, weight: float) -> None:
        """Route ``weight`` (0..1) of picks to replicas serving registry
        ``version`` (the canary lane), the rest to everything else (the
        stable lane).  A lane with no live replica falls back to plain
        rotation — a canary that dies degrades to stable service, it
        never blackholes the weighted fraction."""
        if not 0.0 <= weight <= 1.0:
            raise ValueError(f"canary weight must be in [0, 1], got {weight}")
        with self._lock:
            self._canary = (int(version), float(weight))
            self._canary_acc = 0.0
        faults.log_event(
            "serve_canary_set", role=self.role, version=int(version),
            weight=round(float(weight), 3),
        )

    def clear_canary(self) -> None:
        with self._lock:
            self._canary = None

    def _rr_pick_locked(self, now: float, lane=None) -> int | None:
        """Round-robin over un-ejected replicas (optionally restricted to
        a lane of indices); caller holds the lock."""
        for k in range(len(self.addrs)):
            i = (self._rr + k) % len(self.addrs)
            if now >= self._eject_until[i] and (lane is None or i in lane):
                self._rr = i + 1
                return i
        return None

    def _pick(self) -> int | None:
        with self._lock:
            now = time.monotonic()
            if self._canary is not None:
                cver, weight = self._canary
                live = [
                    i for i in range(len(self.addrs))
                    if now >= self._eject_until[i]
                ]
                c_lane = {i for i in live if self._ver[i] == cver}
                s_lane = {i for i in live if self._ver[i] != cver}
                if c_lane and s_lane:
                    # Deterministic weighted split: the accumulator hands
                    # exactly ``weight`` of picks to the canary lane over
                    # any window (no RNG to decorrelate in tests).
                    self._canary_acc += weight
                    if self._canary_acc >= 1.0:
                        self._canary_acc -= 1.0
                        lane = c_lane
                    else:
                        lane = s_lane
                    got = self._rr_pick_locked(now, lane)
                    if got is not None:
                        return got
            return self._rr_pick_locked(now)  # plain rotation / fallback

    def _eject(self, i: int, for_s: float) -> None:
        with self._lock:
            if i >= len(self.addrs):
                return  # set_addrs shrank the pool under this request
            self._eject_until[i] = time.monotonic() + for_s
            self.ejections += 1
            c, self._clients[i] = self._clients[i], None
        if c is not None:
            c.close()

    def _client(self, i: int) -> ServeClient:
        with self._lock:
            c = self._clients[i]
        if c is not None:
            return c
        host, port = self.addrs[i]
        c = ServeClient(
            host, port, op_timeout_s=self._op_timeout,
            reconnect_deadline_s=0.0,  # the POOL is the recovery layer
            role=self.role, tenant=self.tenant,
        )
        with self._lock:
            # Two threads can race past the None check and both dial;
            # first one in wins, the loser closes its socket (no leak)
            # and shares the winner's client.
            if self._clients[i] is None:
                self._clients[i] = c
                if i < len(self._ver):
                    self._ver[i] = c.server_model_version
                return c
            winner = self._clients[i]
        c.close()
        return winner

    # -- per-version accounting (r19) ----------------------------------------

    def _record_version(
        self, i: int, version: int | None, ok: bool, dt_s: float = 0.0,
    ) -> None:
        with self._lock:
            if version is None:
                # An errored attempt: charge the replica's last-known
                # version (-1 when it was never learned).
                known = self._ver[i] if 0 <= i < len(self._ver) else None
                ver = -1 if known is None else int(known)
            else:
                ver = int(version)
                if 0 <= i < len(self._ver):
                    self._ver[i] = ver
            st = self._vstats.get(ver)
            if st is None:
                st = self._vstats[ver] = {
                    "ok": 0, "err": 0, "lat": LatencyRecorder(),
                }
            if ok:
                st["ok"] += 1
                st["lat"].record(dt_s)
            else:
                st["err"] += 1
        if ok and version is not None:
            self.last_version = ver

    def version_stats(self) -> dict[int, dict]:
        """Per served-version accounting: ``{version: {ok, err,
        latency percentiles/qps}}`` (version -1 = attempts whose replica's
        version was never learned) — the canary-vs-stable evidence a
        promote-or-rollback decision reads (serve.deploy.canary_verdict)."""
        with self._lock:
            items = list(self._vstats.items())
        out: dict[int, dict] = {}
        for ver, st in items:
            row = {"ok": st["ok"], "err": st["err"]}
            for k, v in st["lat"].percentile_scalars("v").items():
                row[k.split("/", 1)[1]] = v
            out[ver] = row
        return out

    def known_versions(self) -> dict[str, int | None]:
        """Last-known served version per replica address (None = never
        dialed)."""
        with self._lock:
            return {
                f"{h}:{p}": v for (h, p), v in zip(self.addrs, self._ver)
            }

    def predict(
        self, inputs: dict, *, deadline_s: float | None = None,
    ) -> tuple[int, dict[str, np.ndarray]]:
        """One logical predict, retried across the rotation until it
        succeeds or the deadline passes.  Safe to retry without markers:
        predict is pure, so a response lost mid-failover at worst costs a
        recomputation, never a duplicated side effect."""
        t_end = time.monotonic() + (
            deadline_s if deadline_s is not None else self._deadline
        )
        last_err: BaseException | None = None
        first = True
        sheds_in_row = 0  # consecutive RETRY_LATER answers this request
        while time.monotonic() < t_end:
            i = self._pick()
            if i is None:
                # Everything benched: sleep to the earliest un-ejection
                # (bounded by the backoff floor) and try again.  Waiting
                # is free — no request is issued, so no retry token is
                # spent (the budget prices re-ISSUES, not patience).
                with self._lock:
                    wake = min(self._eject_until)
                time.sleep(
                    min(max(self._backoff, wake - time.monotonic()), 1.0)
                )
                continue
            if not first:
                with self._lock:
                    self.retries += 1
                # Every re-issued request consults the shared budget
                # (r18): refused means the pool is already storming —
                # surface the typed deadline error instead of feeding it.
                if not self._budget.try_spend():
                    raise ServeDeadlineError(
                        "serve pool retry budget exhausted "
                        f"(last error: {last_err!r})"
                    )
            first = False
            try:
                c = self._client(i)
                t0 = time.perf_counter()
                got = c.predict(inputs)
                self.last_replica = i
                # The response's version stamp (r19) — fall back to the
                # HELLO word against a pre-stamp replica.
                ver = (
                    c.last_model_version
                    if c.last_model_version >= 0
                    else c.server_model_version
                )
                self._record_version(
                    i, ver, ok=True, dt_s=time.perf_counter() - t0
                )
                self._budget.on_success()
                return got
            except ServeRejectedError:
                # The replica ANSWERED: the request itself is bad (or the
                # apply failed deterministically).  Every peer would reject
                # it the same way — surface it instead of benching healthy
                # replicas and replaying for the whole deadline.
                raise
            except (ServeOverloadError, ServeUnavailableError) as e:
                # Alive but shedding: rotate — but HONOR the retry-after
                # hint the shed carried (r18).  The shedding replica
                # benches for the hinted window (it told us how long its
                # queue needs to drain), and once a whole rotation sweep
                # has answered only sheds — pool-WIDE overload — the next
                # attempt waits a jittered hint first: rotating at line
                # rate across N overloaded replicas is amplification, not
                # load balancing.
                last_err = e
                self._record_version(i, None, ok=False)
                hint_s = getattr(e, "retry_after_s", 0.0)
                self._eject(i, max(min(self._eject_s, 0.25), hint_s))
                # Only a genuine SHED answer counts toward the pool-wide-
                # overload detection — a warming replica (Unavailable, no
                # hint) is not overload evidence, and must not push the
                # pool into the backoff sleep.
                if isinstance(e, ServeOverloadError):
                    sheds_in_row += 1
                if hint_s > 0 and sheds_in_row >= len(self.addrs):
                    with self._lock:
                        self.overload_backoffs += 1
                    time.sleep(min(
                        retry.jittered(hint_s, cap_s=2.0),
                        max(0.0, t_end - time.monotonic()),
                    ))
            except IndexError:
                # set_addrs() shrank the pool between _pick and use (an
                # elastic scale-down racing this request): the index is
                # simply stale — re-pick against the new rotation, never
                # fail the logical predict.
                continue
            except (ServeError, OSError, ConnectionError) as e:
                last_err = e
                sheds_in_row = 0  # a transport fault, not a shed answer
                self._record_version(i, None, ok=False)
                self._eject(i, self._eject_s)
                faults.log_event(
                    "serve_replica_ejected", role=self.role, replica=i,
                    error=type(e).__name__,
                )
        raise ServeDeadlineError(
            f"no replica answered within {self._deadline:.0f}s "
            f"(last error: {last_err!r})"
        )

    def set_addrs(self, addrs: list[tuple[str, int]]) -> None:
        """Reconcile the replica set against an ELASTIC membership list
        (r14): addresses that remain keep their client and ejection
        state; removed replicas' clients close (an in-flight predict on
        one fails its attempt and retries on a peer — predict is pure, so
        a scale-down never fails a logical request); new replicas join
        the rotation un-ejected.  No-op when nothing changed."""
        addrs = list(addrs)
        if not addrs:
            raise ValueError("need at least one replica address")
        stale: list[ServeClient] = []
        with self._lock:
            if addrs == self.addrs:
                return
            keep_clients = dict(zip(self.addrs, self._clients))
            keep_eject = dict(zip(self.addrs, self._eject_until))
            keep_ver = dict(zip(self.addrs, self._ver))
            stale = [
                c
                for a, c in keep_clients.items()
                if c is not None and a not in addrs
            ]
            self.addrs = addrs
            self._clients = [keep_clients.get(a) for a in addrs]
            self._eject_until = [keep_eject.get(a, 0.0) for a in addrs]
            self._ver = [keep_ver.get(a) for a in addrs]
            self._rr %= len(addrs)
        for c in stale:
            try:
                c.close()
            except Exception:
                pass
        faults.log_event(
            "serve_pool_resized", role=self.role, replicas=len(addrs),
        )

    def stats(self, i: int) -> dict:
        """Replica ``i``'s stats (dialing it directly, even if benched)."""
        return self._client(i).stats()

    def close(self) -> None:
        for k, c in enumerate(self._clients):
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass
            self._clients[k] = None
