"""Rolling deploys & canary decisions over version-pinned replicas (r19).

The deploy story the model registry unlocks: a serve pool flips from
registry version A to version B with ZERO failed predicts, through the
same ordering the autoscaler proved for scale-down (lease-release-before-
stop + pool ejection/rotation + predict purity):

- :class:`RollingDeploy` owns a set of in-process version-PINNED
  :class:`~serve.model_server.ModelReplicaServer` replicas.  ``canary``
  starts ONE replica at the new version (it loads, pins, leases and only
  then joins routing); ``promote`` replaces the remaining old-version
  replicas one at a time, START-THEN-STOP (surge): the replacement is
  model-loaded and routable BEFORE its predecessor releases its lease and
  drains — capacity never dips below the pool size, and a predict caught
  on a stopping replica retries on a peer.  ``rollback`` stops the new
  version's replicas the same way (guarded: it refuses to stop the last
  replica standing).
- :func:`canary_verdict` is the promote-or-rollback policy over
  :meth:`serve.ServePool.version_stats` — canary error rate and p99
  against the stable lane's, with a minimum-evidence floor so one lucky
  (or unlucky) request cannot decide a deploy.

The controller is deliberately in-process (the autoscaler's shape): the
multi-process flavor is an orchestration concern (``tools/loadsim.py
--scenario=canary`` drives it over the product CLI), while every ordering
invariant lives — and is tested — here.
"""

from __future__ import annotations

import logging
import threading

from ..utils import faults

log = logging.getLogger("dtx.deploy")


def canary_verdict(
    stable: dict | None, canary: dict | None, *, min_requests: int = 20,
    max_err_ratio: float = 0.02, p99_factor: float = 3.0,
) -> str:
    """``"promote"`` / ``"rollback"`` / ``"hold"`` from two
    ``version_stats()`` rows.  Policy: below ``min_requests`` canary
    answers the evidence is insufficient (hold); a canary error RATIO
    above ``max_err_ratio`` — or a canary p99 beyond ``p99_factor`` x the
    stable p99 — rolls back; otherwise promote."""
    if not canary:
        return "hold"
    total = canary.get("ok", 0) + canary.get("err", 0)
    if total < min_requests:
        return "hold"
    if canary.get("err", 0) > max_err_ratio * total:
        return "rollback"
    c_p99 = canary.get("latency_p99_ms", 0.0)
    s_p99 = (stable or {}).get("latency_p99_ms", 0.0)
    if s_p99 > 0 and c_p99 > p99_factor * s_p99:
        return "rollback"
    return "promote"


class RollingDeploy:
    """Drive version flips over a live in-process replica set.

    ``make_server(index, version)`` builds one version-PINNED replica
    (closing over init_fn/predict_fn/registry_dir and any knobs); the
    controller owns the returned servers' lifecycles.  ``on_change`` (if
    given) is called with the current address list after EVERY topology
    change — wire it to ``ServePool.set_addrs`` for a static pool;
    lease-following pools (``LeaseServeDiscovery``) need nothing.

    Zero-failed-flip ordering, per replacement:

    1. construct the replacement (it loads + PINS its version — a replica
       that cannot load fails construction, aborting the flip with the
       old set intact);
    2. ``wait_for_model`` (paranoia: pin mode loads synchronously);
    3. announce the grown set (``on_change``; the lease the replica
       acquired in its constructor covers discovery-based pools);
    4. stop the predecessor — ``ModelReplicaServer.stop`` releases its
       membership lease FIRST, then drains the core, so routing drops it
       before its port goes dark and an in-flight predict just retries
       on a peer;
    5. announce the shrunk set.
    """

    def __init__(
        self, make_server, *, replicas: int = 3, version: int,
        on_change=None, model_ready_s: float = 60.0,
    ):
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self._make = make_server
        self._on_change = on_change
        self._ready_s = float(model_ready_s)
        self._lock = threading.Lock()
        self._next_index = 0
        self._servers: list = []  # [(server, version)]
        self.flips = 0
        self.rollbacks = 0
        for _ in range(replicas):
            self._start_one(int(version))
        self._announce()

    # -- surface -------------------------------------------------------------

    def addrs(self) -> list[tuple[str, int]]:
        with self._lock:
            return [("127.0.0.1", s.port) for s, _v in self._servers]

    def versions(self) -> dict[str, int]:
        """``{addr: pinned version}`` of the live set."""
        with self._lock:
            return {f"127.0.0.1:{s.port}": v for s, v in self._servers}

    def _announce(self) -> None:
        if self._on_change is not None:
            self._on_change(self.addrs())

    def _start_one(self, version: int):
        with self._lock:
            index = self._next_index
            self._next_index += 1
        server = self._make(index, int(version))
        if not server.wait_for_model(self._ready_s):
            server.stop()
            raise TimeoutError(
                f"replacement replica (v{version}) never loaded its model"
            )
        with self._lock:
            self._servers.append((server, int(version)))
        return server

    # -- the deploy verbs ----------------------------------------------------

    def canary(self, version: int) -> tuple[str, int]:
        """Start ONE replica pinned at ``version`` alongside the current
        set; returns its address.  Pair with
        ``ServePool.set_canary(version, weight)`` to route a weighted
        fraction at it, and :func:`canary_verdict` to decide."""
        server = self._start_one(version)
        self._announce()
        faults.log_event(
            "deploy_canary_up", version=int(version), port=server.port,
        )
        return ("127.0.0.1", server.port)

    def promote(self, version: int) -> int:
        """Roll every replica NOT already at ``version`` onto it,
        one surge replacement at a time; returns how many were replaced.
        On any failure the flip stops with the set still fully serving
        (old and already-flipped replicas intact)."""
        replaced = 0
        while True:
            with self._lock:
                old = next(
                    ((s, v) for s, v in self._servers if v != int(version)),
                    None,
                )
            if old is None:
                break
            old_server, old_version = old
            self._start_one(version)  # surge: grow BEFORE shrinking
            self._announce()
            with self._lock:
                self._servers = [
                    (s, v) for s, v in self._servers if s is not old_server
                ]
            old_server.stop()  # lease-release-before-stop lives in stop()
            self._announce()
            replaced += 1
            faults.log_event(
                "deploy_replica_flipped", from_version=int(old_version),
                to_version=int(version),
            )
        if replaced:
            self.flips += 1
            faults.log_event(
                "deploy_promoted", version=int(version), replaced=replaced,
            )
        return replaced

    def rollback(self, version: int) -> int:
        """Stop every replica pinned at ``version`` (the failed canary /
        half-promoted set); returns how many stopped.  Refuses to stop
        the last replica standing — a rollback must degrade to the stable
        set, never to an empty pool."""
        stopped = 0
        while True:
            with self._lock:
                victim = next(
                    (s for s, v in self._servers if v == int(version)),
                    None,
                )
                if victim is None or len(self._servers) <= 1:
                    break
                self._servers = [
                    (s, v) for s, v in self._servers if s is not victim
                ]
            victim.stop()
            self._announce()
            stopped += 1
        if stopped:
            self.rollbacks += 1
            faults.log_event(
                "deploy_rolled_back", version=int(version), stopped=stopped,
            )
        return stopped

    def close(self) -> None:
        with self._lock:
            servers, self._servers = list(self._servers), []
        for s, _v in servers:
            try:
                s.stop()
            except Exception:  # noqa: BLE001 — teardown stops the rest
                log.exception("deploy close: replica stop failed")


def make_pinned_factory(
    init_fn, predict_fn, ps_addrs, *, registry_dir: str,
    model_name: str = "default", **server_kw,
):
    """The standard ``make_server`` for :class:`RollingDeploy`: each
    replica binds an ephemeral port, pins ``(model_name, version)`` from
    ``registry_dir`` and (when ``ps_addrs`` is non-empty) leases itself
    into the membership registry as ``<role>-rd<i>``."""
    from ..utils import faults as faults_lib
    from . import model_server as msrv_lib

    base_role = faults_lib.current_role() or "serve"

    def make(i: int, version: int) -> msrv_lib.ModelReplicaServer:
        return msrv_lib.ModelReplicaServer(
            init_fn, predict_fn, list(ps_addrs), port=0,
            role=f"{base_role}-rd{i}", registry_dir=registry_dir,
            model_name=model_name, model_version=int(version), **server_kw,
        )

    return make
