"""Model registry: immutable versioned snapshots the serve plane pins (r19).

Until now the serve plane could only HOT-TRACK the single live training
run — every replica follows the PS head, so there was no way to stage,
pin, or roll back a model.  This module is the missing versioned layer
(the TensorFlow paper's checkpointed-session capability, rebuilt for the
flat-param serving substrate):

- :class:`ModelRegistry` — a directory of immutable ``(model_name,
  version)`` snapshots.  ``publish`` writes the flat parameter vector
  plus a MANIFEST (flat-param spec, training step, dtype, source run);
  the manifest is written ATOMICALLY (tmp file, flush+fsync, rename,
  directory fsync) and LAST, so a version either exists completely or
  not at all — a crash mid-publish leaves no half-readable version, and
  a reader that sees the manifest sees everything it names.
- **Pins** — a replica serving a version PINS it (lease-style: an owner
  file with a TTL, renewed on the replica's refresh cadence), and
  :meth:`gc` NEVER deletes a pinned version no matter what
  ``keep_last_n`` says — retention can shrink history, it cannot yank a
  model out from under a live replica.
- ``publish_from_checkpoint`` bridges ``train/checkpoint.py``: the
  newest Orbax checkpoint restores against the caller's template and
  publishes as a registry version, so any training run's checkpoints
  become deployable artifacts with one call.

Version ids are immutable: re-publishing an existing version is refused
loudly (a deploy pipeline must mint a NEW version to change bytes — that
is what makes "replica X serves v3" a meaningful statement).  Everything
is plain files under one root, shareable by every process on a host (or
a shared filesystem) with no extra service.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time

import numpy as np

from ..parallel import tenancy

log = logging.getLogger("dtx.registry")

#: Manifest schema version (tests pin it).
MANIFEST_SCHEMA_VERSION = 1

_NAME_RE = re.compile(r"^[A-Za-z0-9._-]{1,128}$")
_VERSION_DIR_RE = re.compile(r"^v(\d{6})$")


class RegistryError(RuntimeError):
    """A registry operation failed (unknown version, immutability
    violation, malformed manifest)."""


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a rename into it is durable — the half of
    atomic-publish a bare ``os.replace`` does not give you."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_manifest(path: str, manifest: dict) -> None:
    """The ONE manifest writer: tmp file, flush+fsync, atomic rename,
    directory fsync — on EVERY exit path the tmp handle is closed, and
    the destination is either the complete old content or the complete
    new content, durably.  Every registry publish path must route through
    here (pinned by dtxlint's ``registry-manifest`` lifecycle check)."""
    tmp = path + ".tmp"
    f = open(tmp, "w")
    try:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    finally:
        f.close()
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


class ModelRegistry:
    """Filesystem-backed registry of immutable ``(name, version)`` model
    snapshots.  Layout::

        <root>/<name>/v000001/params.npy      the flat param vector
        <root>/<name>/v000001/manifest.json   written LAST, atomically
        <root>/<name>/v000001/pins/<owner>.json   lease-style pin files

    A version without a ``manifest.json`` is invisible (a crashed
    publish); a version with one is complete and immutable.
    """

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def _model_dir(self, name: str) -> str:
        if not _NAME_RE.match(name):
            raise RegistryError(
                f"model name {name!r} must match {_NAME_RE.pattern}"
            )
        return os.path.join(self.root, name)

    def _version_dir(self, name: str, version: int) -> str:
        if version < 1:
            raise RegistryError(f"version must be >= 1, got {version}")
        return os.path.join(self._model_dir(name), f"v{int(version):06d}")

    # -- read side -----------------------------------------------------------

    def models(self) -> list[str]:
        try:
            entries = sorted(os.listdir(self.root))
        except OSError:
            return []
        return [
            e for e in entries
            if _NAME_RE.match(e) and os.path.isdir(os.path.join(self.root, e))
        ]

    def versions(self, name: str) -> list[int]:
        """Published (manifest-complete) versions, ascending."""
        out = []
        try:
            entries = os.listdir(self._model_dir(name))
        except OSError:
            return []
        for e in sorted(entries):
            m = _VERSION_DIR_RE.match(e)
            if m and os.path.exists(
                os.path.join(self._model_dir(name), e, "manifest.json")
            ):
                out.append(int(m.group(1)))
        return out

    def latest(self, name: str) -> int | None:
        vs = self.versions(name)
        return vs[-1] if vs else None

    def manifest(self, name: str, version: int) -> dict:
        path = os.path.join(self._version_dir(name, version), "manifest.json")
        try:
            with open(path) as f:
                m = json.load(f)
        except OSError as e:
            raise RegistryError(
                f"no published version {name}/v{version} under {self.root}"
            ) from e
        except ValueError as e:
            raise RegistryError(
                f"manifest for {name}/v{version} is not valid JSON"
            ) from e
        for key in ("name", "version", "step", "num_elems", "dtype"):
            if key not in m:
                raise RegistryError(
                    f"manifest for {name}/v{version} lacks {key!r}"
                )
        return m

    def load(self, name: str, version: int) -> tuple[int, np.ndarray, dict]:
        """``(step, flat_params, manifest)`` for a published version.  The
        flat vector is validated against the manifest's spec — a truncated
        or wrong-dtype blob fails HERE, not as garbage attention later."""
        m = self.manifest(name, version)
        path = os.path.join(
            self._version_dir(name, version), m.get("params_file", "params.npy")
        )
        flat = np.load(path)
        if flat.shape != (int(m["num_elems"]),) or str(flat.dtype) != m["dtype"]:
            raise RegistryError(
                f"{name}/v{version}: params blob is {flat.shape}/{flat.dtype}, "
                f"manifest says ({m['num_elems']},)/{m['dtype']}"
            )
        return int(m["step"]), flat, m

    # -- publish -------------------------------------------------------------

    def publish(
        self, name: str, flat, *, step: int, version: int | None = None,
        source: str = "", extra: dict | None = None,
    ) -> int:
        """Publish one immutable snapshot; returns the version id.
        ``version=None`` mints ``latest + 1``.  Re-publishing an existing
        version is refused (immutability is the whole point).  The params
        blob lands first (fsync'd), the manifest last (atomic + fsync'd),
        so a reader never sees a manifest whose blob is missing or
        partial."""
        flat = np.ascontiguousarray(np.asarray(flat).reshape(-1))
        if version is None:
            version = (self.latest(name) or 0) + 1
        vdir = self._version_dir(name, int(version))
        manifest_path = os.path.join(vdir, "manifest.json")
        if os.path.exists(manifest_path):
            raise RegistryError(
                f"{name}/v{version} is already published — registry versions "
                "are immutable; publish a new version instead"
            )
        os.makedirs(vdir, exist_ok=True)
        params_tmp = os.path.join(vdir, "params.npy.tmp")
        f = open(params_tmp, "wb")
        try:
            np.save(f, flat)
            f.flush()
            os.fsync(f.fileno())
        finally:
            f.close()
        os.replace(params_tmp, os.path.join(vdir, "params.npy"))
        _fsync_dir(vdir)
        manifest = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "name": name,
            "version": int(version),
            "step": int(step),
            "num_elems": int(flat.size),
            "dtype": str(flat.dtype),
            "params_file": "params.npy",
            "source": source,
            "created_unix": time.time(),
        }
        if extra:
            manifest["extra"] = dict(extra)
        write_manifest(manifest_path, manifest)
        log.info(
            "registry: published %s/v%d (step %d, %d elems) under %s",
            name, version, step, flat.size, self.root,
        )
        return int(version)

    def publish_from_checkpoint(
        self, manager, template, name: str, *, version: int | None = None,
        source: str = "checkpoint",
    ) -> int:
        """Publish the NEWEST checkpoint a ``train.checkpoint.
        CheckpointManager`` holds: restore against ``template`` (a params
        pytree or TrainState), flatten the params half with the shared
        ``ps_shard`` convention, publish.  Raises when the manager holds
        no checkpoint."""
        from ..train.checkpoint import flat_params_of

        restored = manager.restore_latest(template)
        if restored is None:
            raise RegistryError(
                f"checkpoint manager holds no step to publish as {name!r}"
            )
        step = manager.latest_step()
        flat = flat_params_of(restored)
        return self.publish(
            name, flat, step=int(step or 0), version=version, source=source,
        )

    # -- pins (lease-style refcount) ----------------------------------------

    def _pins_dir(self, name: str, version: int) -> str:
        return os.path.join(self._version_dir(name, version), "pins")

    def pin(
        self, name: str, version: int, owner: str, *, ttl_s: float = 60.0,
        tenant: str = tenancy.DEFAULT_TENANT,
    ) -> None:
        """Pin a version on behalf of ``owner`` (a serving replica's
        role): refresh on the replica's poll cadence — an expired pin no
        longer protects, so a crashed replica cannot block GC forever
        (the same self-healing posture as membership leases).

        The pin file is keyed by the TENANT-QUALIFIED owner (r20): two
        tenants' replicas sharing both a snapshot and a role name (e.g.
        both pinning the shared base model as ``serve0``) hold two
        distinct pins — one tenant's unpin/GC sweep can never unprotect
        the version out from under the other tenant's live replica."""
        if not _NAME_RE.match(owner):
            raise RegistryError(
                f"pin owner {owner!r} must match {_NAME_RE.pattern}"
            )
        owner = tenancy.qualify(tenant, owner)
        self.manifest(name, version)  # pinning an unpublished version is a bug
        pins = self._pins_dir(name, version)
        os.makedirs(pins, exist_ok=True)
        write_manifest(
            os.path.join(pins, f"{owner}.json"),
            {"owner": owner, "expires_unix": time.time() + float(ttl_s)},
        )

    def unpin(
        self, name: str, version: int, owner: str, *,
        tenant: str = tenancy.DEFAULT_TENANT,
    ) -> None:
        owner = tenancy.qualify(tenant, owner)
        try:
            os.unlink(os.path.join(self._pins_dir(name, version), f"{owner}.json"))
        except OSError:
            pass  # idempotent

    def pinned_by(self, name: str, version: int) -> list[str]:
        """Owners holding an UNEXPIRED pin on this version (expired pin
        files are pruned as they are seen)."""
        pins = self._pins_dir(name, version)
        out = []
        try:
            entries = sorted(os.listdir(pins))
        except OSError:
            return []
        now = time.time()
        for e in entries:
            if not e.endswith(".json") or e.endswith(".tmp"):
                continue
            path = os.path.join(pins, e)
            try:
                with open(path) as f:
                    p = json.load(f)
                if float(p.get("expires_unix", 0)) > now:
                    out.append(p.get("owner", e[: -len(".json")]))
                else:
                    os.unlink(path)
            except (OSError, ValueError):
                continue
        return out

    # -- retention -----------------------------------------------------------

    def gc(self, name: str, *, keep_last_n: int) -> list[int]:
        """Delete all but the newest ``keep_last_n`` versions — EXCEPT any
        version a live (unexpired) pin protects.  Returns the versions
        deleted.  The manifest is unlinked FIRST, so a concurrent reader
        racing the delete sees 'not published' (the same state as
        pre-publish), never a manifest whose blob is gone."""
        if keep_last_n < 1:
            raise RegistryError(f"keep_last_n must be >= 1, got {keep_last_n}")
        versions = self.versions(name)
        deleted = []
        for v in versions[:-keep_last_n]:
            owners = self.pinned_by(name, v)
            if owners:
                log.info(
                    "registry gc: keeping %s/v%d past keep_last_n=%d — "
                    "pinned by %s", name, v, keep_last_n, owners,
                )
                continue
            vdir = self._version_dir(name, v)
            try:
                os.unlink(os.path.join(vdir, "manifest.json"))
            except OSError:
                continue  # raced another gc
            for sub, _dirs, files in os.walk(vdir, topdown=False):
                for fn in files:
                    try:
                        os.unlink(os.path.join(sub, fn))
                    except OSError:
                        pass
                try:
                    os.rmdir(sub)
                except OSError:
                    pass
            deleted.append(v)
        if deleted:
            log.info("registry gc: deleted %s versions %s", name, deleted)
        return deleted
