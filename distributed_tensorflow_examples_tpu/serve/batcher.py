"""Dynamic micro-batcher: coalesce queued predict requests into one apply.

Online inference arrives one small request at a time, but the accelerator's
throughput comes from batched applies — the same tension the reference
stack resolved for *training* with global batches.  This module is the
serving-side resolution (r10 tentpole): requests queue as they arrive, a
single batch thread coalesces them — up to ``max_batch`` rows, or whatever
accumulated within ``max_wait_ms`` of the first request — and runs ONE
jitted apply, then scatters the per-request output slices back to each
waiting connection handler.

Admission control: the number of in-system requests (queued + being
batched + computing) is bounded by ``queue_depth``.  Past it, ``submit``
raises :class:`Overloaded` IMMEDIATELY — the server answers an explicit
OVERLOAD status so resilient clients back off / rotate to another replica,
instead of piling requests onto a replica that can only grow its latency
tail (the load-shedding half of the serving SLO).

The batcher is model-agnostic: ``run_batch(items) -> results`` is the only
coupling, so the unit tests drive it with plain functions and the model
server plugs in the padded jitted apply.

Sequence-slot batching (r19): :class:`SlotBatcher` is the second mode —
for STATEFUL, VARIABLE-LENGTH work the row-wise padding model cannot
express (autoregressive decode: a session lives for many steps, holds a
KV cache, and ends at its own time).  Sessions occupy SLOTS of a
fixed-width batch; one step thread advances every active slot together
(``run_step(slots)`` — one jitted apply over the whole slot array), each
session streams its emissions through a :class:`StreamTicket`, and a
finished session frees its slot for the next queued one mid-flight.  The
schema-keyed row batcher and the slot batcher coexist in one replica:
stateless predicts coalesce rows, decode sessions occupy slots.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque

from ..utils import telemetry

log = logging.getLogger("dtx.serve")


class Overloaded(RuntimeError):
    """Admission control refused the request: the replica's queue is full.
    Clients should back off or try another replica."""


class Ticket:
    """One submitted request's future: ``result()`` blocks until the batch
    containing it was applied, then returns this request's slice (or
    re-raises the batch's error on the submitting side)."""

    __slots__ = (
        "rows", "key", "_event", "_value", "_error", "_callback",
        "_cb_lock", "_resolved",
    )

    def __init__(self, rows: int, key=None):
        self.rows = rows
        self.key = key
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None
        self._callback = None
        self._cb_lock = threading.Lock()
        self._resolved = False

    def _resolve(self, value=None, error: BaseException | None = None) -> None:
        """First resolution wins; later calls are no-ops — that
        idempotence is what makes an external timeout sweep (the model
        server's wedged-apply backstop) safe against the genuine
        resolution racing in late."""
        with self._cb_lock:
            if self._resolved:
                return
            self._resolved = True
            self._value, self._error = value, error
            cb, self._callback = self._callback, None
        self._event.set()
        if cb is not None:
            self._run_callback(cb)

    def _run_callback(self, cb) -> None:
        """A consumer callback must never kill the RESOLVING thread — an
        exception out of it would take down the batch thread (every
        later predict hangs) or, on the synchronous register path, make
        the core's worker send a SECOND error frame after the callback
        already replied.  Contain it here, loudly."""
        try:
            cb(self._value, self._error)
        except Exception:
            log.exception("ticket on_resolve callback failed")

    def on_resolve(self, fn) -> None:
        """Register ``fn(value, error)`` to run when the batch containing
        this ticket resolves (on the resolving thread) — the async-reply
        hook the server core's bounded worker pool uses instead of
        parking a thread in :meth:`result`.  A ticket that already
        resolved calls ``fn`` immediately.  The register/resolve handoff
        is lock-guarded so ``fn`` runs EXACTLY once no matter how the
        two threads interleave (a double invocation would queue two
        response frames for one request and desynchronize the
        connection)."""
        with self._cb_lock:
            if not self._resolved:
                self._callback = fn
                return
        self._run_callback(fn)

    def result(self, timeout_s: float | None = None):
        if not self._event.wait(timeout_s):
            raise TimeoutError("batched apply did not complete in time")
        if self._error is not None:
            raise self._error
        return self._value


class DynamicBatcher:
    """The coalescing loop.  ``run_batch(items: list) -> list`` runs on the
    single batch thread and must return one result per item (in order);
    an exception fails every request of that batch (each submitter sees
    it), never the batcher itself.

    ``max_batch``    row budget per apply; a request's ``rows`` that would
                     overflow the current batch is carried into the next
                     one (never split).  A single request larger than
                     ``max_batch`` runs as its own batch.
    ``max_wait_ms``  how long a non-full batch waits for more requests
                     after its FIRST one arrived — the latency the first
                     request pays to buy coalescing.
    ``queue_depth``  max in-system requests before ``submit`` answers
                     :class:`Overloaded`.
    """

    def __init__(
        self, run_batch, *, max_batch: int = 32, max_wait_ms: float = 5.0,
        queue_depth: int = 128, name: str = "serve",
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._run = run_batch
        self.max_batch = int(max_batch)
        self.max_wait_s = max_wait_ms / 1e3
        self.queue_depth = int(queue_depth)
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._carry: Ticket | None = None  # would-overflow head of next batch
        self._items: dict[Ticket, object] = {}
        self._lock = threading.Lock()
        self._inflight = 0
        self._stopped = False
        # Counters (read via stats(); writes under _lock or batch-thread-only).
        self.requests = 0
        self.overloads = 0
        self.batches = 0
        self.rows_batched = 0
        self.flush_full = 0
        self.flush_timeout = 0
        self.last_batch_rows = 0
        # Observability histograms (r13 dtxobs): in-system depth sampled at
        # every admit, and rows per flushed batch — the coalescing-quality
        # signals ``stats()`` flattens next to the counters (and the serve
        # STATS scrape ships to dtxtop).  Instance-owned, not registry
        # entries: two batchers in one process must not share a ring.
        self.queue_depth_hist = telemetry.Histogram(f"{name}/queue_depth")
        self.batch_rows_hist = telemetry.Histogram(f"{name}/batch_rows")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"dtx-{name}-batcher"
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, item, rows: int = 1, key=None) -> Ticket:
        """Enqueue one request (``rows`` = its leading-dim size, the unit
        ``max_batch`` budgets).  Only requests with EQUAL ``key`` coalesce
        into one apply (the model server keys by field schema, so one
        malformed request can never poison a well-formed neighbour's
        batch; a mismatched arrival ends the current batch and heads the
        next one).  Raises :class:`Overloaded` when the in-system request
        count is at ``queue_depth`` — the caller answers the explicit
        OVERLOAD status instead of queuing unboundedly."""
        t = Ticket(rows, key)
        with self._lock:
            if self._stopped:
                raise RuntimeError("batcher is stopped")
            if self._inflight >= self.queue_depth:
                self.overloads += 1
                raise Overloaded(
                    f"{self._inflight} requests in flight (depth "
                    f"{self.queue_depth})"
                )
            self._inflight += 1
            self.requests += 1
            self.queue_depth_hist.observe(self._inflight)
            # Enqueue under the SAME lock that stop() takes to set
            # _stopped: a ticket that passed the check above is therefore
            # queued before the stop sentinel, so the drain loop always
            # sees it and no submitter is left blocking on an unresolved
            # ticket.
            self._items[t] = item
            self._q.put(t)
        return t

    def stats(self) -> dict:
        with self._lock:
            out = {
                "requests": self.requests,
                "overloads": self.overloads,
                "batches": self.batches,
                "rows_batched": self.rows_batched,
                "flush_full": self.flush_full,
                "flush_timeout": self.flush_timeout,
                "last_batch_rows": self.last_batch_rows,
                "inflight": self._inflight,
                "max_batch": self.max_batch,
                "queue_depth": self.queue_depth,
            }
        for k, v in self.queue_depth_hist.snapshot().items():
            out[f"queue_depth_{k}"] = v
        for k, v in self.batch_rows_hist.snapshot().items():
            out[f"batch_rows_{k}"] = v
        return out

    def stop(self) -> None:
        """Stop the batch thread; pending submitters see RuntimeError."""
        with self._lock:
            self._stopped = True
        self._q.put(None)  # wake the collector
        self._thread.join(timeout=10.0)

    # -- the batch thread ----------------------------------------------------

    def _next_ticket(self, timeout_s: float | None):
        try:
            return self._q.get(timeout=timeout_s)
        except queue.Empty:
            return None

    def _collect(self) -> tuple[list[Ticket], bool] | None:
        """Block for the first request, then coalesce until the row budget
        fills or ``max_wait_ms`` passes.  Returns ``(batch, filled)`` or
        None when stopping."""
        if self._carry is not None:
            first, self._carry = self._carry, None
        else:
            while True:
                if self._stopped:
                    return None
                # The stop() wake sentinel arrives as a literal None — the
                # same shape as a get() timeout, and handled the same way:
                # loop around and observe _stopped.
                first = self._next_ticket(0.2)
                if first is not None:
                    break
        batch, rows = [first], first.rows
        deadline = time.monotonic() + self.max_wait_s
        while rows < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            t = self._next_ticket(remaining)
            if t is None:
                break  # window expired (or the stop sentinel: flush now)
            if t.key != first.key:
                self._carry = t  # different schema: never co-batched
                break
            if rows + t.rows > self.max_batch:
                self._carry = t  # head of the NEXT batch — never split
                rows = self.max_batch
                break
            batch.append(t)
            rows += t.rows
        return batch, rows >= self.max_batch

    def _loop(self) -> None:
        while True:
            got = self._collect()
            if got is None:
                break
            batch, filled = got
            items = [self._items.pop(t) for t in batch]
            try:
                results = self._run(items)
                if len(results) != len(batch):
                    raise RuntimeError(
                        f"run_batch returned {len(results)} results for "
                        f"{len(batch)} requests"
                    )
            except BaseException as e:  # noqa: BLE001 — re-raised per ticket
                for t in batch:
                    t._resolve(error=e)
            else:
                for t, r in zip(batch, results):
                    t._resolve(value=r)
            nrows = sum(t.rows for t in batch)
            self.batch_rows_hist.observe(nrows)
            with self._lock:
                self._inflight -= len(batch)
                self.batches += 1
                self.rows_batched += nrows
                self.last_batch_rows = nrows
                if filled:
                    self.flush_full += 1
                else:
                    self.flush_timeout += 1
        # Drain: anything still queued (or carried) fails loudly on its
        # submitter's side rather than hanging it.
        err = RuntimeError("batcher stopped")
        pending = [self._carry] if self._carry is not None else []
        self._carry = None
        while True:
            try:
                t = self._q.get_nowait()
            except queue.Empty:
                break
            if isinstance(t, Ticket):  # skip the stop() wake sentinel
                pending.append(t)
        with self._lock:
            self._inflight -= len(pending)
        for t in pending:
            self._items.pop(t, None)
            t._resolve(error=err)


# ----------------------------------------------------------------------------
# Sequence-slot batching (r19): stateful variable-length sessions
# ----------------------------------------------------------------------------


class StreamTicket:
    """One decode session's stream: the step thread APPENDS emissions,
    consumers read them by CURSOR (``snapshot(cursor)`` returns everything
    from ``cursor`` on), so a replayed poll after a reconnect re-reads
    instead of double-draining.  Terminal states: ``done`` (the session
    produced its full budget) or an error (the step function raised — the
    whole active batch fails, like the row batcher's contract)."""

    __slots__ = ("state", "_emits", "_done", "_error", "_cancelled",
                 "_lock", "_event")

    def __init__(self, state):
        self.state = state
        self._emits: list = []
        self._done = False
        self._error: BaseException | None = None
        self._cancelled = False
        self._lock = threading.Lock()
        self._event = threading.Event()

    # -- step-thread side --
    def _emit(self, items) -> None:
        with self._lock:
            self._emits.extend(items)
        self._event.set()

    def _finish(self, error: BaseException | None = None) -> None:
        with self._lock:
            if self._done:
                return
            self._done = True
            self._error = error
        self._event.set()

    # -- consumer side --
    def cancel(self) -> None:
        """Ask the step thread to drop this session at its next step (or
        before it ever takes a slot).  Idempotent."""
        self._cancelled = True
        self._finish(error=None)

    @property
    def done(self) -> bool:
        return self._done

    @property
    def error(self) -> BaseException | None:
        return self._error

    def snapshot(self, cursor: int = 0) -> tuple[list, bool]:
        """``(emissions[cursor:], done)`` — non-blocking, replay-safe (the
        full emission list is retained for the session's lifetime; decode
        budgets bound it).  Raises the session's error if it failed."""
        with self._lock:
            if self._error is not None:
                raise self._error
            return list(self._emits[max(0, int(cursor)):]), self._done

    def wait(self, timeout_s: float | None = None) -> bool:
        """Block until at least one emission (or a terminal state) since
        the last ``wait``; True unless the timeout passed."""
        ok = self._event.wait(timeout_s)
        self._event.clear()
        return ok


class SlotBatcher:
    """The sequence-slot step loop.  ``run_step(slots)`` runs on the one
    step thread with ``slots`` a fixed-length list — ``StreamTicket`` for
    an occupied slot, None for a free one — and returns a same-length
    list whose occupied entries are ``(emits, done)``; a free slot's
    entry is ignored.  The step function owns all cross-step state (KV
    caches, positions) keyed by SLOT INDEX; the batcher owns occupancy,
    admission and streaming.

    ``slots``         fixed batch width of one step (the jit shape).
    ``max_sessions``  admission bound on in-system sessions (active +
                      queued); past it ``open`` raises :class:`Overloaded`
                      (the same explicit-shed contract as ``submit``).
    ``idle_wait_s``   how long the step thread parks when no slot is
                      active.

    An exception out of ``run_step`` fails every ACTIVE session (each
    waiter sees it) and frees their slots — queued sessions then take
    slots and run; the batcher itself never dies.
    """

    def __init__(
        self, run_step, *, slots: int = 4, max_sessions: int = 64,
        idle_wait_s: float = 0.2, name: str = "decode",
    ):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self._run = run_step
        self.slots = int(slots)
        self.max_sessions = max(self.slots, int(max_sessions))
        self._idle_wait_s = float(idle_wait_s)
        self._slots: list[StreamTicket | None] = [None] * self.slots
        self._queue: deque = deque()
        self._fresh: set = set()  # tickets not yet seen by the step thread
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stopped = False
        # Counters (stats(); mutate under _lock or on the step thread).
        self.sessions = 0
        self.overloads = 0
        self.steps = 0
        self.emitted = 0
        self.step_errors = 0
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"dtx-{name}-slots"
        )
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def open(self, state) -> StreamTicket:
        """Admit one session (its ``state`` is whatever the step function
        needs to seed a slot).  Raises :class:`Overloaded` past
        ``max_sessions`` in-system."""
        t = StreamTicket(state)
        with self._lock:
            if self._stopped:
                raise RuntimeError("slot batcher is stopped")
            active = sum(1 for s in self._slots if s is not None)
            if active + len(self._queue) >= self.max_sessions:
                self.overloads += 1
                raise Overloaded(
                    f"{active} active + {len(self._queue)} queued decode "
                    f"sessions (bound {self.max_sessions})"
                )
            self.sessions += 1
            self._queue.append(t)
        self._work.set()
        return t

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "slots_active": sum(1 for s in self._slots if s is not None),
                "sessions_queued": len(self._queue),
                "sessions": self.sessions,
                "overloads": self.overloads,
                "steps": self.steps,
                "emitted": self.emitted,
                "step_errors": self.step_errors,
            }

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
        self._work.set()
        self._thread.join(timeout=10.0)

    # -- the step thread -----------------------------------------------------

    def _fill_slots(self) -> tuple[list, bool]:
        """Seat queued sessions in free slots, drop cancelled ones;
        returns ``(slots snapshot, any_active)``."""
        with self._lock:
            for i in range(self.slots):
                t = self._slots[i]
                if t is not None and (t._cancelled or t.done):
                    self._slots[i] = None
            while self._queue and any(s is None for s in self._slots):
                t = self._queue.popleft()
                if t._cancelled:
                    continue
                i = next(
                    k for k, s in enumerate(self._slots) if s is None
                )
                self._slots[i] = t
                self._fresh.add(t)
            snapshot = list(self._slots)
        return snapshot, any(s is not None for s in snapshot)

    def _loop(self) -> None:
        while True:
            if self._stopped:
                break
            slots, active = self._fill_slots()
            if not active:
                self._work.wait(self._idle_wait_s)
                self._work.clear()
                continue
            try:
                results = self._run(slots)
            except BaseException as e:  # noqa: BLE001 — re-raised per session
                self.step_errors += 1
                for t in slots:
                    if t is not None:
                        t._finish(error=e)
                continue
            self.steps += 1
            for i, t in enumerate(slots):
                if t is None:
                    continue
                self._fresh.discard(t)
                emits, done = results[i]
                if emits:
                    self.emitted += len(emits)
                    t._emit(emits)
                if done:
                    t._finish()
        # Drain: every active and queued session fails loudly instead of
        # hanging its poller.
        err = RuntimeError("slot batcher stopped")
        with self._lock:
            pending = [s for s in self._slots if s is not None]
            pending += [t for t in self._queue]
            self._queue.clear()
            self._slots = [None] * self.slots
        for t in pending:
            t._finish(error=err)
