"""Param-tracking model replica server: the online inference plane (r10).

After r9 the repo trains behind a resilient sharded parameter store but has
no process that answers a predict request.  The TensorFlow architecture
paper frames the PS pattern as the shared substrate for training AND
serving — parameter servers hand versioned params to any consumer — and
the tf.data-service PR (r8) showed the payoff of disaggregating a plane
onto the shared wire.  This module applies the same move to inference:

- :class:`ModelReplicaServer` — a replica speaking the shared
  ``parallel/wire.py`` framing under the ``msrv`` service tag.  It
  HOT-TRACKS training: a background refresher thread polls the (sharded)
  parameter store with ``PSTORE_GET_IF_NEWER`` (via
  ``ps_shard.ShardedParamStore`` / ``ps_service.RemoteParamStore``), so an
  unchanged model costs one O(header) round trip per shard and a changed
  one lands in a FRESH buffer the store never reuses — an in-flight batch
  holds its own ``(step, params)`` snapshot and can never tear.  Every
  predict response is stamped with the served ``model_step`` (the response
  status), so consumers can observe exactly which published update they
  were answered from.
- Dynamic micro-batching — requests from all connections coalesce through
  :class:`serve.batcher.DynamicBatcher` into one padded jitted apply
  (padding keeps the jit cache at ONE shape; row-independent models make
  the pad rows inert, so batched and unbatched outputs are byte-identical).
  A bounded queue answers an explicit OVERLOAD status past ``queue_depth``
  — admission control, so resilient clients back off instead of piling on.
- Fault posture — the replica process carries a fault role (``serve<i>``),
  ``die:after_reqs`` arms off the server's request counter, and the
  ``--job_name=serve`` task runs under the shared supervised-restart path
  (``train/ps_experiment._supervised_reexec``): a killed replica restarts,
  re-pulls the CURRENT params from the PS (zero coordination — the store
  is the rendezvous), and rejoins the client rotation.

Wire notes: frame layout / HELLO / zero-copy paths shared via
``parallel/wire.py``; payload lengths count BYTES (predict inputs/outputs
are mixed-dtype field dicts moved with the shared batch codec).  Op codes
are disjoint from both the PS range (1..27) and the data service's
(64..71), so a frame reaching the wrong service is refused, never
misinterpreted; the HELLO service identity makes even the refusal loud.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time

import numpy as np

from ..parallel import ps_shard, wire
from ..utils import faults, telemetry
from ..utils.metrics import LatencyRecorder, MetricsWriter
from . import batcher as batcher_lib

log = logging.getLogger("dtx.serve")

#: This wire's service identity (parallel/wire.py registry).
SERVICE = "msrv"
SERVICE_TAG = wire.SERVICE_TAGS[SERVICE]

# Op codes (SRV_*) — aliases into the ONE registry (wire.SRV_OPS), disjoint
# from the PS server's 1..27 and DSVC's 64..71 (dtxlint-enforced).
SRV_HELLO = wire.SRV_OPS["HELLO"]
SRV_PREDICT = wire.SRV_OPS["PREDICT"]
SRV_STATS = wire.SRV_OPS["STATS"]
SRV_SHUTDOWN = wire.SRV_OPS["SHUTDOWN"]

#: Ops excluded from the request counter — derived from the one
#: control-plane registry (wire.CONTROL_OPS; dtxlint pins this site).
_SRV_CONTROL_OPS = frozenset(
    wire.SRV_OPS[n] for n in wire.CONTROL_OPS["msrv"]
)

# Response statuses (wire.SRV_STATUS aliases).  PREDICT success answers the
# served model_step (>= 0) as the status — the per-response staleness stamp
# costs zero extra bytes.
ERR = wire.SRV_STATUS["ERR"]
OVERLOAD = wire.SRV_STATUS["OVERLOAD"]
NO_MODEL = wire.SRV_STATUS["NO_MODEL"]


def flat_param_spec(init_fn):
    """``(total_elems, unflatten)`` for the parameter STRUCTURE ``init_fn``
    builds — the shared ``ps_shard.flat_param_spec`` convention the
    training workers use (values always come from the param store; only
    shapes matter here)."""
    import jax

    template = init_fn(jax.random.key(0))
    if isinstance(template, tuple):  # init_fn returning (params, model_state)
        template = template[0]
    return ps_shard.flat_param_spec(template)


class ModelReplicaServer:
    """One serving replica: PS-tracking model + micro-batched predict.

    ``init_fn``       builds the parameter structure (shapes/treedef); the
                      VALUES are pulled from the parameter store.
    ``predict_fn``    ``predict_fn(params, inputs: dict) -> array | dict``;
                      must be row-wise in the leading dim (outputs row i
                      depend only on inputs row i) — that is what makes
                      padded batching exact and the scatter well-defined.
    ``ps_addrs``      the shard servers in shard order (``--ps_hosts``).
    ``max_batch`` / ``max_wait_ms`` / ``queue_depth``
                      the micro-batcher knobs (serve/batcher.py).
    ``refresh_ms``    param-poll cadence; each poll is O(header) per shard
                      while the published step is unchanged.
    """

    def __init__(
        self, init_fn, predict_fn, ps_addrs, *, port: int = 0,
        loopback_only: bool = True, max_batch: int = 32,
        max_wait_ms: float = 5.0, queue_depth: int = 128,
        refresh_ms: float = 50.0, op_timeout_s: float | None = 10.0,
        reconnect_deadline_s: float = 60.0, role: str | None = None,
        metrics_dir: str | None = None, metrics_every: int = 100,
        membership: bool = True, lease_ttl_s: float = 10.0,
        advertise_addr: str | None = None, ps_replicas: int = 1,
        layout_version: int = 0, follow_reshard: bool = True,
    ):
        import jax

        from ..parallel import reshard

        total, self._unflatten = flat_param_spec(init_fn)
        self._predict = jax.jit(predict_fn)
        self.role = role if role is not None else (
            faults.current_role() or "serve0"
        )
        self._op_timeout_s = op_timeout_s
        self._reconnect_deadline_s = reconnect_deadline_s
        self._group = ps_shard.ShardedPSClients(
            list(ps_addrs), role=self.role, op_timeout_s=op_timeout_s,
            reconnect_deadline_s=reconnect_deadline_s,
            replicas=ps_replicas, layout_version=layout_version,
        )
        self._layout = self._group.layout_for(total)
        self._pstore = ps_shard.ShardedParamStore(
            self._group, "params", self._layout
        )
        # Live resharding (r15): the refresher polls the coordinator for a
        # committed layout epoch (O(header) while unchanged) and swaps its
        # whole PS-side onto the new topology — a replica keeps
        # hot-tracking through an N→M reshard with zero restarts.
        self._reshards = 0
        self._follower = (
            reshard.EpochFollower(
                self._group.coordinator, layout_version,
                max(0.5, refresh_ms / 1e3),
            )
            if follow_reshard
            else None
        )
        self.max_batch = int(max_batch)
        self._refresh_s = max(refresh_ms, 1.0) / 1e3
        # The served model: an immutable (step, params) tuple swapped by
        # ONE reference assignment.  A changed pull lands in a fresh buffer
        # (the store's contract), so a batch holding the previous tuple is
        # never torn by the swap.
        self._model: tuple[int, object] | None = None
        self._incarnation = int.from_bytes(os.urandom(4), "little") | 1
        self._lock = threading.Lock()
        self._requests = 0
        self._predicts = 0
        self._refreshes = 0
        self._refresh_errors = 0
        self._overloads = 0
        self.latency = LatencyRecorder()
        self._writer = MetricsWriter(metrics_dir) if metrics_dir else None
        self._metrics_every = max(1, metrics_every)
        self._batcher = batcher_lib.DynamicBatcher(
            self._run_batch, max_batch=max_batch, max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
        )
        self._stop = threading.Event()
        self.shutdown_requested = threading.Event()
        self._conns: list[socket.socket] = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        bind_deadline = time.monotonic() + (5.0 if port else 0.0)
        while True:
            try:
                self._listener.bind(("127.0.0.1" if loopback_only else "", port))
                break
            except OSError:
                # A supervised restart rebinds the dead incarnation's FIXED
                # port; lingering sockets can hold it briefly — retry within
                # a short window instead of failing the healing restart.
                if time.monotonic() >= bind_deadline:
                    raise
                time.sleep(0.2)
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        # Membership (r14): announce this replica — WITH its dialable
        # address — in the coordinator's lease registry, so an elastic
        # serve pool (and dtxtop) discovers dynamically-started replicas
        # from the registry instead of a static --serve_hosts list.
        self._heartbeat = None
        if membership:
            from ..parallel import membership as membership_lib

            self._heartbeat = membership_lib.LeaseHeartbeat(
                self._group.replica_addrs[0], self.role, kind="serve",
                addr=advertise_addr or f"127.0.0.1:{self.port}",
                ttl_s=lease_ttl_s, role=self.role,
                op_timeout_s=op_timeout_s,
                reconnect_deadline_s=reconnect_deadline_s,
            )
        self._refresher = threading.Thread(
            target=self._refresh_loop, daemon=True, name="msrv-refresh"
        )
        self._refresher.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="msrv-accept"
        )
        self._accept_thread.start()
        log.info(
            "model replica %s serving on port %d (%d PS shard(s), "
            "max_batch=%d, incarnation %d)",
            self.role, self.port, self._group.num_shards, self.max_batch,
            self._incarnation,
        )

    # -- lifecycle -----------------------------------------------------------

    def request_count(self) -> int:
        """Requests handled so far — the ``die:after_reqs`` fault trigger
        for a serve task (same contract as the PS / data servers)."""
        return self._requests

    @property
    def model_step(self) -> int:
        m = self._model
        return -1 if m is None else m[0]

    def wait_for_model(self, timeout_s: float = 60.0) -> bool:
        """Block until the first published snapshot was pulled (True), or
        the timeout passes (False) — the warm-up gate hosting code may use
        before advertising the replica."""
        deadline = time.monotonic() + timeout_s
        while self._model is None:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def stop(self) -> None:
        # Release the membership lease FIRST: discovery must drop this
        # replica from every pool rotation before the listener goes dark,
        # so a scale-down/stop never routes predicts at a dead port for
        # the thread-join window below (the zero-failed-requests drain
        # ordering autoscale.scale_down documents).
        if self._heartbeat is not None:
            self._heartbeat.close()
            self._heartbeat = None
        self._stop.set()
        # shutdown() BEFORE close(): close alone does not free the port
        # while the accept thread is blocked in accept() (same reasoning as
        # DataServiceServer.stop).
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=5.0)
        self._refresher.join(timeout=5.0)
        with self._lock:
            conns, self._conns = self._conns[:], []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        self._batcher.stop()
        if self._writer is not None:
            self._writer.close()
        self._group.close()

    # -- the param refresher (hot-tracking thread) ---------------------------

    def _swap_epoch(self, rec: dict) -> None:
        """Rebuild the PS-side onto a committed reshard record (refresher
        thread only — the predict path reads ``self._model``, an immutable
        tuple this swap never touches).  A failed rebuild keeps the
        current epoch and retries on the next poll."""
        old_version = self._layout.version
        if rec["num_elems"] != self._layout.num_elems:
            log.error(
                "serve %s: reshard v%d names %d elems, this replica "
                "serves %d — ignoring the record", self.role,
                rec["version"], rec["num_elems"], self._layout.num_elems,
            )
            return
        group = None
        try:
            group = ps_shard.ShardedPSClients.for_record(
                rec, role=self.role, op_timeout_s=self._op_timeout_s,
                reconnect_deadline_s=self._reconnect_deadline_s,
            )
            layout = group.layout_for(self._layout.num_elems)
            pstore = ps_shard.ShardedParamStore(group, "params", layout)
        except Exception as e:  # noqa: BLE001 — keep old epoch, retry
            if group is not None:
                group.close()
            self._follower.version = old_version
            faults.log_event(
                "serve_epoch_swap_failed", role=self.role,
                version=rec["version"], error=type(e).__name__,
            )
            return
        old_group = self._group
        self._group, self._layout, self._pstore = group, layout, pstore
        self._follower.rebind(group.coordinator, rec["version"])
        self._reshards += 1
        if self._heartbeat is not None:
            self._heartbeat.retarget(group.coordinator_replica_addrs)
        old_group.close()
        faults.log_event(
            "serve_epoch_swapped", role=self.role, version=rec["version"],
            shards=layout.num_shards,
        )

    def _refresh_loop(self) -> None:
        from ..parallel import ps_service

        while not self._stop.is_set():
            if self._follower is not None:
                rec = self._follower.poll()
                if rec is not None:
                    self._swap_epoch(rec)
            try:
                step, flat = self._pstore.get()
            except (ps_service.PSError, OSError) as e:
                # A PS outage past the client's own reconnect budget: keep
                # serving the LAST pulled model (stale-but-available beats
                # down) and keep polling.
                self._refresh_errors += 1
                faults.log_event(
                    "serve_refresh_error", role=self.role,
                    error=type(e).__name__,
                )
                self._stop.wait(min(1.0, self._refresh_s * 4))
                continue
            cur = self._model
            if step >= 0 and (cur is None or int(step) != cur[0]):
                # A CHANGED pull landed in a fresh buffer (the store never
                # hands back the previously returned one), so the views the
                # unflatten takes can outlive any number of later swaps.
                # device_put HERE, once per publish: the same snapshot is
                # reused across every apply until the next change, so the
                # batches must not each re-pay the host->device transfer.
                import jax

                self._model = (
                    int(step), jax.device_put(self._unflatten(flat))
                )
                self._refreshes += 1
            self._stop.wait(self._refresh_s)

    # -- the batched apply ---------------------------------------------------

    def _run_batch(self, items: list[dict]):
        """One padded jitted apply for a coalesced request list; returns
        ``(step, outputs_slice)`` per request.  Runs on the batch thread."""
        model = self._model
        if model is None:
            raise _NoModel()
        step, params = model
        proto = items[0]
        rows = [len(next(iter(it.values()))) for it in items]
        total = sum(rows)
        # Pad to the fixed max_batch shape so the jit cache holds ONE entry
        # per field signature; a lone oversized request runs at its own
        # (padded-to-itself) shape.
        padded = self.max_batch if total <= self.max_batch else total
        batch = {
            k: np.zeros((padded,) + np.asarray(v).shape[1:], np.asarray(v).dtype)
            for k, v in proto.items()
        }
        off = 0
        for it, r in zip(items, rows):
            for k in batch:
                batch[k][off : off + r] = it[k]
            off += r
        out = self._predict(params, batch)
        if not isinstance(out, dict):
            out = {"output": out}
        out_np = {k: np.asarray(v) for k, v in out.items()}
        results = []
        off = 0
        for r in rows:
            results.append(
                (step, {k: v[off : off + r] for k, v in out_np.items()})
            )
            off += r
        with self._lock:
            self._predicts += total
        return results

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        b = self._batcher.stats()
        with self._lock:
            s = {
                "service": SERVICE,
                "role": self.role,
                "incarnation": self._incarnation,
                "model_step": self.model_step,
                "requests": self._requests,
                "predict_rows": self._predicts,
                "overloads": self._overloads,
                "refreshes": self._refreshes,
                "refresh_errors": self._refresh_errors,
                "ps_shards": self._group.num_shards,
                "layout_epoch": self._layout.version,
                "reshards_followed": self._reshards,
                "leased": bool(
                    self._heartbeat is not None and self._heartbeat.enabled
                ),
            }
        s.update({f"batcher_{k}": v for k, v in b.items()})
        s.update(self.latency.percentile_scalars("serve"))
        # The replica process's client-side instruments ride along (r13):
        # its PS legs' reconnect/failover counters are the externally
        # visible half of "this replica kept tracking through the fault".
        s["registry"] = telemetry.snapshot()
        s["flight_events"] = len(telemetry.RECORDER)
        return s

    # -- connection handling -------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="msrv-conn",
            ).start()

    def _reply(self, conn, status: int, bufs: list | None) -> None:
        bufs = bufs or []
        hdr = wire.RESP_HDR.pack(status, wire.encoded_nbytes(bufs))
        wire.send_frames(conn, [hdr] + bufs)

    def _serve_conn(self, conn: socket.socket) -> None:
        hdr2 = bytearray(2)
        try:
            while not self._stop.is_set():
                req = wire.read_request(conn, hdr2)
                if req is None:
                    return
                op, name, a, b, plen = req
                # Control-plane ops (wire.CONTROL_OPS) never count toward
                # ``request_count``.
                if op not in _SRV_CONTROL_OPS:
                    with self._lock:
                        self._requests += 1
                if op == SRV_PREDICT:
                    t0 = time.perf_counter()
                    # The payload must leave the socket even on the
                    # overload path — the framing survives the refusal.
                    inputs = wire.read_batch(conn, plen)
                    self._handle_predict(conn, inputs, t0)
                    continue
                if plen:  # no other SRV op carries a request payload
                    sink = bytearray(min(plen, 1 << 20))
                    left = plen
                    while left:
                        view = memoryview(sink)[: min(left, len(sink))]
                        wire.recv_exact(conn, view)
                        left -= len(view)
                if op == SRV_HELLO:
                    status, tag = wire.hello_answer(a, b, service=SERVICE)
                    self._reply(conn, status, [tag] if tag else None)
                elif op == SRV_STATS:
                    self._reply(conn, 0, [json.dumps(self.stats()).encode()])
                elif op == SRV_SHUTDOWN:
                    self.shutdown_requested.set()
                    self._reply(conn, 0, None)
                else:
                    self._reply(conn, ERR, None)
        except (OSError, ConnectionError):
            pass
        finally:
            with self._lock:
                try:
                    self._conns.remove(conn)
                except ValueError:
                    pass
            try:
                conn.close()
            except OSError:
                pass

    def _handle_predict(self, conn, inputs: dict, t0: float) -> None:
        if not inputs:
            self._reply(conn, ERR, None)
            return
        lens = {len(np.asarray(v)) if np.asarray(v).ndim else -1
                for v in inputs.values()}
        if len(lens) != 1 or -1 in lens:
            # Every field must share one leading dim — the row unit the
            # batcher budgets and the scatter slices by.
            self._reply(conn, ERR, None)
            return
        if self._model is None:
            self._reply(conn, NO_MODEL, None)
            return
        # Requests coalesce only with SCHEMA-IDENTICAL neighbours (same
        # field names, trailing shapes and dtypes): one client sending a
        # mismatched request must never poison a well-formed concurrent
        # request's batch (it fails alone, in its own apply).
        schema = tuple(sorted(
            (k, np.asarray(v).shape[1:], str(np.asarray(v).dtype))
            for k, v in inputs.items()
        ))
        try:
            ticket = self._batcher.submit(inputs, rows=lens.pop(), key=schema)
        except batcher_lib.Overloaded:
            with self._lock:
                self._overloads += 1
            self._reply(conn, OVERLOAD, None)
            return
        try:
            step, out = ticket.result(timeout_s=120.0)
        except _NoModel:
            self._reply(conn, NO_MODEL, None)
            return
        except Exception:
            # An apply bug — or the ticket's own TimeoutError on a stuck
            # batch thread (an OSError subclass, so no transport-error
            # carve-out here: the try block does no socket I/O) — must
            # surface as a LOUD per-op error on the client, not a silent
            # connection close (same posture as the data service's
            # handler guard).
            log.exception("batched predict failed server-side")
            self._reply(conn, ERR, None)
            return
        bufs = wire.encode_batch(out)
        hdr = wire.RESP_HDR.pack(step, wire.encoded_nbytes(bufs))
        wire.send_frames(conn, [hdr] + bufs)
        self.latency.record(time.perf_counter() - t0)
        if (
            self._writer is not None
            and self.latency.total % self._metrics_every == 0
        ):
            self._writer.scalars(
                self.model_step, self.latency.percentile_scalars("serve")
            )


class _NoModel(RuntimeError):
    """Raised inside a batch whose replica has no pulled snapshot yet —
    mapped to the NO_MODEL status per request (warming replicas shed load
    explicitly, like overload)."""


# ----------------------------------------------------------------------------
# Task-role hosting (the runner's `serve` job)
# ----------------------------------------------------------------------------


def host_serve_task(
    *, init_fn, predict_fn, ps_addrs, port: int, loopback_only: bool = True,
    max_batch: int = 32, max_wait_ms: float = 5.0, queue_depth: int = 128,
    refresh_ms: float = 50.0, op_timeout_s: float | None = 10.0,
    reconnect_deadline_s: float = 60.0, metrics_dir: str | None = None,
    membership: bool = True, lease_ttl_s: float = 10.0,
    advertise_addr: str | None = None, ps_replicas: int = 1,
    layout_version: int = 0,
) -> int:
    """Dedicated serve-task body (``--job_name=serve``): host one replica
    until a client signals SRV_SHUTDOWN (or the supervisor dies).  Arms
    ``die`` fault specs off the replica's request counter — the
    deterministic "kill replica i at request N" fault the serving recovery
    tests inject; a supervisor restart re-pulls the current params from the
    PS and rejoins the rotation with zero coordination."""
    server = ModelReplicaServer(
        init_fn, predict_fn, ps_addrs, port=port,
        loopback_only=loopback_only, max_batch=max_batch,
        max_wait_ms=max_wait_ms, queue_depth=queue_depth,
        refresh_ms=refresh_ms, op_timeout_s=op_timeout_s,
        reconnect_deadline_s=reconnect_deadline_s, metrics_dir=metrics_dir,
        membership=membership, lease_ttl_s=lease_ttl_s,
        advertise_addr=advertise_addr, ps_replicas=ps_replicas,
        layout_version=layout_version,
    )
    faults.arm_process_faults(
        request_count_fn=server.request_count,
        leave_fn=lambda: server.stop(),
    )
    if not server.wait_for_model(timeout_s=120.0):
        log.warning(
            "serve task: no published params after 120 s — serving NO_MODEL "
            "until the chief publishes"
        )
    log.info(
        "serve task on port %d (model_step=%d; blocking until shutdown)",
        server.port, server.model_step,
    )
    supervised = os.environ.get("DTX_SERVE_SUPERVISED") == "1"
    ppid0 = os.getppid()
    while not server.shutdown_requested.wait(timeout=2.0):
        if supervised and os.getppid() != ppid0:
            log.warning("serve task: supervisor died; exiting")
            break
    bound = server.port
    server.stop()
    return bound
