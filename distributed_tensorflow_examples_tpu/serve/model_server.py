"""Param-tracking model replica server: the online inference plane (r10).

After r9 the repo trains behind a resilient sharded parameter store but has
no process that answers a predict request.  The TensorFlow architecture
paper frames the PS pattern as the shared substrate for training AND
serving — parameter servers hand versioned params to any consumer — and
the tf.data-service PR (r8) showed the payoff of disaggregating a plane
onto the shared wire.  This module applies the same move to inference:

- :class:`ModelReplicaServer` — a replica speaking the shared
  ``parallel/wire.py`` framing under the ``msrv`` service tag.  It
  HOT-TRACKS training: a background refresher thread polls the (sharded)
  parameter store with ``PSTORE_GET_IF_NEWER`` (via
  ``ps_shard.ShardedParamStore`` / ``ps_service.RemoteParamStore``), so an
  unchanged model costs one O(header) round trip per shard and a changed
  one lands in a FRESH buffer the store never reuses — an in-flight batch
  holds its own ``(step, params)`` snapshot and can never tear.  Every
  predict response is stamped with the served ``model_step`` (the response
  status), so consumers can observe exactly which published update they
  were answered from.
- Dynamic micro-batching — requests from all connections coalesce through
  :class:`serve.batcher.DynamicBatcher` into one padded jitted apply
  (padding keeps the jit cache at ONE shape; row-independent models make
  the pad rows inert, so batched and unbatched outputs are byte-identical).
  A bounded queue answers an explicit OVERLOAD status past ``queue_depth``
  — admission control, so resilient clients back off instead of piling on.
- Fault posture — the replica process carries a fault role (``serve<i>``),
  ``die:after_reqs`` arms off the server's request counter, and the
  ``--job_name=serve`` task runs under the shared supervised-restart path
  (``train/ps_experiment._supervised_reexec``): a killed replica restarts,
  re-pulls the CURRENT params from the PS (zero coordination — the store
  is the rendezvous), and rejoins the client rotation.

Wire notes: frame layout / HELLO / zero-copy paths shared via
``parallel/wire.py``; payload lengths count BYTES (predict inputs/outputs
are mixed-dtype field dicts moved with the shared batch codec).  Op codes
are disjoint from both the PS range (1..27) and the data service's
(64..71), so a frame reaching the wrong service is refused, never
misinterpreted; the HELLO service identity makes even the refusal loud.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

import numpy as np

from ..parallel import ps_shard, server_core, tenancy, wire
from ..utils import faults, telemetry
from ..utils.metrics import LatencyRecorder, MetricsWriter
from . import batcher as batcher_lib

log = logging.getLogger("dtx.serve")

#: This wire's service identity (parallel/wire.py registry).
SERVICE = "msrv"
SERVICE_TAG = wire.SERVICE_TAGS[SERVICE]

# Op codes (SRV_*) — aliases into the ONE registry (wire.SRV_OPS), disjoint
# from the PS server's 1..27 and DSVC's 64..71 (dtxlint-enforced).
SRV_HELLO = wire.SRV_OPS["HELLO"]
SRV_PREDICT = wire.SRV_OPS["PREDICT"]
SRV_STATS = wire.SRV_OPS["STATS"]
SRV_SHUTDOWN = wire.SRV_OPS["SHUTDOWN"]
SRV_DECODE_OPEN = wire.SRV_OPS["DECODE_OPEN"]
SRV_DECODE_NEXT = wire.SRV_OPS["DECODE_NEXT"]
SRV_DECODE_CLOSE = wire.SRV_OPS["DECODE_CLOSE"]

#: Ops excluded from the request counter — derived from the one
#: control-plane registry (wire.CONTROL_OPS; dtxlint pins this site).
_SRV_CONTROL_OPS = frozenset(
    wire.SRV_OPS[n] for n in wire.CONTROL_OPS["msrv"]
)


def _tenant_of_request(op: int, name: str, a: int, b: int) -> str:
    """The server core's per-tenant admission attribution (r20): the
    tenant rides the ``name`` operand as a ``,t=<tenant>`` tag — absent
    (= the default tenant) on every untagged client's frames."""
    return tenancy.untag_name(name)[1]

# Response statuses (wire.SRV_STATUS aliases).  PREDICT success answers the
# served model_step (>= 0) as the status — the per-response staleness stamp
# costs zero extra bytes.
ERR = wire.SRV_STATUS["ERR"]
OVERLOAD = wire.SRV_STATUS["OVERLOAD"]
NO_MODEL = wire.SRV_STATUS["NO_MODEL"]
BAD_SESSION = wire.SRV_STATUS["BAD_SESSION"]
NO_DECODER = wire.SRV_STATUS["NO_DECODER"]


def flat_param_spec(init_fn):
    """``(total_elems, unflatten)`` for the parameter STRUCTURE ``init_fn``
    builds — the shared ``ps_shard.flat_param_spec`` convention the
    training workers use (values always come from the param store; only
    shapes matter here)."""
    import jax

    template = init_fn(jax.random.key(0))
    if isinstance(template, tuple):  # init_fn returning (params, model_state)
        template = template[0]
    return ps_shard.flat_param_spec(template)


class _DecodeEngine:
    """Stepped KV-cache decode behind the sequence-slot batcher (r19).

    Model-agnostic: the model supplies ``init_cache_fn(slots, max_len)``
    (a per-slot cache pytree) and ``step_fn(params, cache, tokens[S],
    pos[S]) -> (logits [S, V], cache)`` — one jitted apply advances EVERY
    active session one position.  The engine owns the host-side slot
    state (current token and position per slot), greedy next-token
    selection and prompt teacher-forcing, so batched decode is
    byte-identical to a session running alone: the slot array shape is
    FIXED (inactive slots compute inert rows, like the row batcher's pad
    rows), every row's math depends only on its own slot, and the
    attention mask confines each session to the cache positions it wrote
    itself — a freed slot needs no cache reset.
    """

    def __init__(
        self, model_getter, init_cache_fn, step_fn, *, slots: int,
        max_len: int, max_sessions: int,
    ):
        import jax

        self._get_model = model_getter  # () -> (step, params) | None
        self._cache = init_cache_fn(slots, max_len)
        self._step_jit = jax.jit(step_fn)
        self.slots = int(slots)
        self.max_len = int(max_len)
        self._tokens = np.zeros((self.slots,), np.int32)
        self._pos = np.zeros((self.slots,), np.int32)
        self.batcher = batcher_lib.SlotBatcher(
            self._run_step, slots=self.slots, max_sessions=max_sessions,
        )

    def open(self, prompt: np.ndarray, max_new_tokens: int):
        """Admit one greedy decode session; returns its StreamTicket.
        Raises ValueError on a prompt/budget the cache cannot hold, and
        ``batcher.Overloaded`` past the session bound."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = int(max_new_tokens)
        if prompt.size < 1:
            raise ValueError("decode needs a non-empty prompt")
        if n < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {n}")
        if prompt.size + n > self.max_len:
            raise ValueError(
                f"{prompt.size} prompt + {n} new tokens exceeds the "
                f"replica's decode_max_len={self.max_len}"
            )
        return self.batcher.open(
            {"prompt": prompt, "n": n, "emitted": 0, "seated": False}
        )

    def _run_step(self, slots):
        import jax.numpy as jnp

        model = self._get_model()
        if model is None:
            raise _NoModel()
        _step, params = model
        for i, t in enumerate(slots):
            if t is not None and not t.state["seated"]:
                # A freshly seated session starts its slot at position 0
                # feeding its first prompt token; the cache needs no
                # reset (see the class docstring).
                t.state["seated"] = True
                self._tokens[i] = t.state["prompt"][0]
                self._pos[i] = 0
        logits, self._cache = self._step_jit(
            params, self._cache,
            jnp.asarray(self._tokens), jnp.asarray(self._pos),
        )
        out = np.asarray(logits)
        results: list = [None] * len(slots)
        for i, t in enumerate(slots):
            if t is None:
                continue
            st = t.state
            p = int(self._pos[i])
            if p + 1 < len(st["prompt"]):
                nxt = int(st["prompt"][p + 1])  # teacher-force the prompt
                emits: list[int] = []
            else:
                nxt = int(np.argmax(out[i]))  # greedy continuation
                emits = [nxt]
                st["emitted"] += 1
            self._tokens[i] = nxt
            self._pos[i] = p + 1
            results[i] = (emits, st["emitted"] >= st["n"])
        return results

    def stats(self) -> dict:
        s = self.batcher.stats()
        s["max_len"] = self.max_len
        return s

    def stop(self) -> None:
        self.batcher.stop()


class ModelReplicaServer:
    """One serving replica: PS-tracking model + micro-batched predict.

    ``init_fn``       builds the parameter structure (shapes/treedef); the
                      VALUES are pulled from the parameter store.
    ``predict_fn``    ``predict_fn(params, inputs: dict) -> array | dict``;
                      must be row-wise in the leading dim (outputs row i
                      depend only on inputs row i) — that is what makes
                      padded batching exact and the scatter well-defined.
    ``ps_addrs``      the shard servers in shard order (``--ps_hosts``).
                      May be EMPTY in pin mode (a registry-only replica
                      needs no PS at all — membership then stays off).
    ``max_batch`` / ``max_wait_ms`` / ``queue_depth``
                      the micro-batcher knobs (serve/batcher.py).
    ``refresh_ms``    param-poll cadence; each poll is O(header) per shard
                      while the published step is unchanged.

    Registry pin mode (r19): with ``registry_dir`` + ``model_version``
    the replica serves an IMMUTABLE registry snapshot instead of
    hot-tracking the PS — the version loads once at construction, a
    lease-style PIN protects it from registry GC for the replica's
    lifetime (renewed by the refresher thread), and ``model_version``
    stamps the HELLO answer, every predict/decode response and STATS, so
    pools can route and account per version (canary vs stable).

    Decode serving (r19): ``decode_fns=(init_cache_fn, step_fn)`` adds
    the stepped KV-cache decode path — stateful sessions behind the
    sequence-slot batcher, streamed token responses over the
    DECODE_OPEN/NEXT/CLOSE wire (``serve.ServeClient.generate`` is the
    client side).
    """

    def __init__(
        self, init_fn, predict_fn, ps_addrs, *, port: int = 0,
        loopback_only: bool = True, max_batch: int = 32,
        max_wait_ms: float = 5.0, queue_depth: int = 128,
        refresh_ms: float = 50.0, op_timeout_s: float | None = 10.0,
        reconnect_deadline_s: float = 60.0, role: str | None = None,
        metrics_dir: str | None = None, metrics_every: int = 100,
        membership: bool = True, lease_ttl_s: float = 10.0,
        advertise_addr: str | None = None, ps_replicas: int = 1,
        layout_version: int = 0, follow_reshard: bool = True,
        handler_workers: int = 8, queue_deadline_ms: float = 0.0,
        registry_dir: str | None = None, model_name: str = "default",
        model_version: int | None = None, pin_ttl_s: float = 30.0,
        decode_fns: tuple | None = None, decode_slots: int = 4,
        decode_max_len: int = 512, decode_max_sessions: int = 64,
        session_idle_s: float = 60.0,
        tenant: str = tenancy.DEFAULT_TENANT,
        tenant_quotas: dict | None = None,
    ):
        import jax

        from ..parallel import reshard
        from . import registry as registry_lib

        total, self._unflatten = flat_param_spec(init_fn)
        self._predict = jax.jit(predict_fn)
        self.role = role if role is not None else (
            faults.current_role() or "serve0"
        )
        # The tenant this replica serves FOR (r20): scopes its PS param
        # namespace (hot-tracking pulls the tenant's own ``params``
        # object), its registry model namespace and pin identity, and its
        # membership lease.  The default tenant changes nothing.
        self.tenant = (
            tenant if tenant == tenancy.DEFAULT_TENANT
            else tenancy.check_tenant(tenant)
        )
        self._op_timeout_s = op_timeout_s
        self._reconnect_deadline_s = reconnect_deadline_s
        # Registry pin (r19): a pinned replica serves one immutable
        # version for its whole lifetime; version 0 means hot-tracking.
        # The registry namespace is tenant-qualified (r20): tenant
        # ``runa``'s model ``m`` is the registry entry ``t.runa.m`` — two
        # tenants' models can share a bare name without sharing bytes.
        self.model_version = int(model_version or 0)
        self.model_name = tenancy.qualify(self.tenant, model_name)
        self._registry = (
            registry_lib.ModelRegistry(registry_dir) if registry_dir else None
        )
        self._pinned = self._registry is not None and self.model_version > 0
        if self.model_version > 0 and self._registry is None:
            raise ValueError(
                f"model_version={self.model_version} needs a registry_dir "
                "to load it from"
            )
        self._pin_ttl_s = max(5.0, float(pin_ttl_s))
        self._next_pin_renew = 0.0
        ps_addrs = list(ps_addrs or [])
        if not ps_addrs and not self._pinned:
            raise ValueError(
                "a hot-tracking replica needs ps_addrs (only a registry-"
                "pinned replica can run PS-free)"
            )
        if ps_addrs:
            self._group = ps_shard.ShardedPSClients(
                ps_addrs, role=self.role, op_timeout_s=op_timeout_s,
                reconnect_deadline_s=reconnect_deadline_s,
                replicas=ps_replicas, layout_version=layout_version,
                tenant=self.tenant,
            )
            self._layout = self._group.layout_for(total)
            self._pstore = ps_shard.ShardedParamStore(
                self._group, "params", self._layout
            )
        else:
            self._group = self._layout = self._pstore = None
            membership = False
        # Live resharding (r15): the refresher polls the coordinator for a
        # committed layout epoch (O(header) while unchanged) and swaps its
        # whole PS-side onto the new topology — a replica keeps
        # hot-tracking through an N→M reshard with zero restarts.  A
        # PINNED replica never follows: its params come from the registry,
        # and its PS legs (when present) serve membership only.
        self._reshards = 0
        self._follower = (
            reshard.EpochFollower(
                self._group.coordinator, layout_version,
                max(0.5, refresh_ms / 1e3),
            )
            if follow_reshard and self._group is not None and not self._pinned
            else None
        )
        self.max_batch = int(max_batch)
        self._refresh_s = max(refresh_ms, 1.0) / 1e3
        # The served model: an immutable (step, params) tuple swapped by
        # ONE reference assignment.  A changed pull lands in a fresh buffer
        # (the store's contract), so a batch holding the previous tuple is
        # never torn by the swap.
        self._model: tuple[int, object] | None = None
        if self._pinned:
            # Pin mode: the version loads ONCE, here — a replica that
            # cannot load its pinned version must fail its construction
            # loudly (the deploy controller's signal to not route to it),
            # never come up serving NO_MODEL forever.
            step, flat, _manifest = self._registry.load(
                self.model_name, self.model_version
            )
            self._model = (int(step), jax.device_put(self._unflatten(flat)))
            self._registry.pin(
                self.model_name, self.model_version, self.role,
                ttl_s=self._pin_ttl_s, tenant=self.tenant,
            )
            self._next_pin_renew = time.monotonic() + self._pin_ttl_s / 3
        self._incarnation = int.from_bytes(os.urandom(4), "little") | 1
        self._lock = threading.Lock()
        # The wedged-apply backstop (the 120 s bound the old blocking
        # path got from ticket.result): in-flight predict tickets are
        # tracked with a deadline and the refresher thread sweeps
        # overdue ones, resolving them with TimeoutError — the resolve
        # callback then answers a loud ERR and frees the connection.
        # Ticket resolution is idempotent, so a genuine late resolve
        # racing the sweep is harmless.  No extra thread, no per-request
        # timer: bounded threads stay bounded.
        self._ticket_deadline_s = 120.0
        self._pending_tickets: dict = {}  # ticket -> deadline (monotonic)
        self._predicts = 0
        self._refreshes = 0
        self._refresh_errors = 0
        self._overloads = 0
        self.latency = LatencyRecorder()
        self._writer = MetricsWriter(metrics_dir) if metrics_dir else None
        self._metrics_every = max(1, metrics_every)
        self._batcher = batcher_lib.DynamicBatcher(
            self._run_batch, max_batch=max_batch, max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
        )
        # Decode serving (r19): stateful sessions behind the sequence-slot
        # batcher.  Session ids are handed to clients as the DECODE_OPEN
        # status; the table maps them to stream tickets, and the refresher
        # sweeps sessions nobody polled for ``session_idle_s``.
        self._engine = (
            _DecodeEngine(
                lambda: self._model, decode_fns[0], decode_fns[1],
                slots=decode_slots, max_len=decode_max_len,
                max_sessions=decode_max_sessions,
            )
            if decode_fns is not None
            else None
        )
        self._session_idle_s = float(session_idle_s)
        self._sessions: dict[int, list] = {}  # sid -> [ticket, last_poll]
        self._next_sid = 1
        self._decode_opens = 0
        self._stop = threading.Event()
        self.shutdown_requested = threading.Event()
        # The shared server runtime (r17): selector-driven I/O, bounded
        # handler pool, per-connection write buffering, HELLO routing and
        # the request counter live in parallel/server_core.py.  PREDICT
        # goes ASYNC through the batcher's resolve callback, so the pool
        # never parks a thread per in-flight predict — concurrency is
        # bounded by the batcher's admission control, not by threads.
        self._core = server_core.ServerCore(
            port=port, loopback_only=loopback_only, name="msrv",
            workers=handler_workers, tenant_quotas=tenant_quotas,
        )
        # Shed answers carry a backoff HINT (r18): roughly two batch
        # windows — the time a queue slot takes to free under load — so
        # pools back off for a meaningful beat instead of re-hammering.
        self._retry_after_ms = max(20, int(2 * max_wait_ms))
        self._core.add_service(server_core.Service(
            SERVICE, self._handle,
            control_ops=_SRV_CONTROL_OPS,
            tenant_of=_tenant_of_request,
            error_status=ERR,
            # PREDICT batches are the only request payloads; bound them
            # at the write-buffer bound rather than the frame ceiling.
            max_payload=256 << 20,
            # Admission policy (r18): a predict that sat in the dispatch
            # queue past this budget (or past the deadline its caller
            # stamped on the frame) is shed before a worker touches it.
            # 0 = client-stamped deadlines only.
            queue_deadline_s=(
                queue_deadline_ms / 1e3 if queue_deadline_ms else None
            ),
            retry_after_ms=self._retry_after_ms,
            # The msrv HELLO version word (r19): a dialing pool learns the
            # served registry version (0 = hot-tracking) at connect, before
            # routing a single predict — canary-weighted routing's
            # discovery half.
            hello_extra=lambda: wire.HELLO_VERSION_TAIL.pack(
                self.model_version
            ),
        ))
        self._core.start()
        self.port = self._core.port
        # Membership (r14): announce this replica — WITH its dialable
        # address — in the coordinator's lease registry, so an elastic
        # serve pool (and dtxtop) discovers dynamically-started replicas
        # from the registry instead of a static --serve_hosts list.
        self._heartbeat = None
        if membership:
            from ..parallel import membership as membership_lib

            self._heartbeat = membership_lib.LeaseHeartbeat(
                self._group.replica_addrs[0], self.role, kind="serve",
                addr=advertise_addr or f"127.0.0.1:{self.port}",
                ttl_s=lease_ttl_s, role=self.role,
                op_timeout_s=op_timeout_s,
                reconnect_deadline_s=reconnect_deadline_s,
                tenant=self.tenant,
            )
        self._refresher = threading.Thread(
            target=self._refresh_loop, daemon=True, name="msrv-refresh"
        )
        self._refresher.start()
        log.info(
            "model replica %s serving on port %d (%s, max_batch=%d, "
            "incarnation %d)",
            self.role, self.port,
            (
                f"pinned {self.model_name}/v{self.model_version}"
                if self._pinned
                else f"{self._group.num_shards} PS shard(s)"
            ),
            self.max_batch, self._incarnation,
        )

    # -- lifecycle -----------------------------------------------------------

    def request_count(self) -> int:
        """Requests handled so far — the ``die:after_reqs`` fault trigger
        for a serve task (same contract as the PS / data servers).  The
        counter lives in the server core, which excludes the control-plane
        ops (wire.CONTROL_OPS)."""
        return self._core.request_count()

    @property
    def model_step(self) -> int:
        m = self._model
        return -1 if m is None else m[0]

    def wait_for_model(self, timeout_s: float = 60.0) -> bool:
        """Block until the first published snapshot was pulled (True), or
        the timeout passes (False) — the warm-up gate hosting code may use
        before advertising the replica."""
        deadline = time.monotonic() + timeout_s
        while self._model is None:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    def stop(self) -> None:
        # Release the membership lease FIRST: discovery must drop this
        # replica from every pool rotation before the listener goes dark,
        # so a scale-down/stop never routes predicts at a dead port for
        # the thread-join window below (the zero-failed-requests drain
        # ordering autoscale.scale_down documents).
        if self._heartbeat is not None:
            self._heartbeat.close()
            self._heartbeat = None
        self._stop.set()
        # The core drains first (in-flight predicts resolve and their
        # buffered responses flush) and releases the port before
        # returning — the zero-dropped-requests half of a scale-down.
        self._core.stop()
        self._refresher.join(timeout=5.0)
        self._batcher.stop()
        if self._engine is not None:
            self._engine.stop()
        if self._pinned:
            # Release the registry pin LAST: GC must not reclaim the
            # served version while in-flight work could still touch it.
            try:
                self._registry.unpin(
                    self.model_name, self.model_version, self.role,
                    tenant=self.tenant,
                )
            except Exception:  # noqa: BLE001 — unpin is best-effort cleanup
                log.warning("registry unpin failed", exc_info=True)
        if self._writer is not None:
            self._writer.close()
        if self._group is not None:
            self._group.close()

    # -- the param refresher (hot-tracking thread) ---------------------------

    def _swap_epoch(self, rec: dict) -> None:
        """Rebuild the PS-side onto a committed reshard record (refresher
        thread only — the predict path reads ``self._model``, an immutable
        tuple this swap never touches).  A failed rebuild keeps the
        current epoch and retries on the next poll."""
        old_version = self._layout.version
        if rec["num_elems"] != self._layout.num_elems:
            log.error(
                "serve %s: reshard v%d names %d elems, this replica "
                "serves %d — ignoring the record", self.role,
                rec["version"], rec["num_elems"], self._layout.num_elems,
            )
            return
        group = None
        try:
            group = ps_shard.ShardedPSClients.for_record(
                rec, role=self.role, op_timeout_s=self._op_timeout_s,
                reconnect_deadline_s=self._reconnect_deadline_s,
                tenant=self.tenant,
            )
            layout = group.layout_for(self._layout.num_elems)
            pstore = ps_shard.ShardedParamStore(group, "params", layout)
        except Exception as e:  # noqa: BLE001 — keep old epoch, retry
            if group is not None:
                group.close()
            self._follower.version = old_version
            faults.log_event(
                "serve_epoch_swap_failed", role=self.role,
                version=rec["version"], error=type(e).__name__,
            )
            return
        old_group = self._group
        self._group, self._layout, self._pstore = group, layout, pstore
        self._follower.rebind(group.coordinator, rec["version"])
        self._reshards += 1
        if self._heartbeat is not None:
            self._heartbeat.retarget(group.coordinator_replica_addrs)
        old_group.close()
        faults.log_event(
            "serve_epoch_swapped", role=self.role, version=rec["version"],
            shards=layout.num_shards,
        )

    def _sweep_stuck_tickets(self) -> None:
        """Resolve predict tickets past their deadline with TimeoutError
        (idempotent — a genuine resolve racing in later is a no-op): a
        wedged batch thread must not pin connections in_flight forever,
        which would leak them AND make every drain()/stop() burn its
        full timeout."""
        now = time.monotonic()
        with self._lock:
            stuck = [t for t, dl in self._pending_tickets.items() if now > dl]
        for t in stuck:
            t._resolve(error=TimeoutError(
                "batched apply did not complete in "
                f"{self._ticket_deadline_s:.0f}s (batch thread wedged?)"
            ))

    def _sweep_idle_sessions(self) -> None:
        """Cancel decode sessions nobody polled for ``session_idle_s`` —
        an abandoned client (crash, lost interest) must not hold a slot
        or its emission buffer forever.  DECODE_CLOSE is the polite path;
        this is the backstop."""
        if self._engine is None:
            return
        now = time.monotonic()
        with self._lock:
            stale = [
                sid for sid, (_t, last) in self._sessions.items()
                if now - last > self._session_idle_s
            ]
            tickets = [self._sessions.pop(sid)[0] for sid in stale]
        for t in tickets:
            t.cancel()

    def _refresh_loop(self) -> None:
        from ..parallel import ps_service

        while not self._stop.is_set():
            self._sweep_stuck_tickets()
            self._sweep_idle_sessions()
            if self._pinned:
                # Pin mode: no PS polling — the refresher's job is the
                # lease-style pin renewal (plus the sweeps above), so
                # registry GC can never reclaim a version this live
                # replica serves.
                now = time.monotonic()
                if now >= self._next_pin_renew:
                    self._next_pin_renew = now + self._pin_ttl_s / 3
                    try:
                        self._registry.pin(
                            self.model_name, self.model_version, self.role,
                            ttl_s=self._pin_ttl_s, tenant=self.tenant,
                        )
                    except Exception:  # noqa: BLE001 — retried next renew
                        self._refresh_errors += 1
                        faults.log_event(
                            "serve_pin_renew_failed", role=self.role,
                            version=self.model_version,
                        )
                self._stop.wait(max(self._refresh_s, 0.25))
                continue
            if self._follower is not None:
                rec = self._follower.poll()
                if rec is not None:
                    self._swap_epoch(rec)
            try:
                step, flat = self._pstore.get()
            except (ps_service.PSError, OSError) as e:
                # A PS outage past the client's own reconnect budget: keep
                # serving the LAST pulled model (stale-but-available beats
                # down) and keep polling.
                self._refresh_errors += 1
                faults.log_event(
                    "serve_refresh_error", role=self.role,
                    error=type(e).__name__,
                )
                self._stop.wait(min(1.0, self._refresh_s * 4))
                continue
            cur = self._model
            if step >= 0 and (cur is None or int(step) != cur[0]):
                # A CHANGED pull landed in a fresh buffer (the store never
                # hands back the previously returned one), so the views the
                # unflatten takes can outlive any number of later swaps.
                # device_put HERE, once per publish: the same snapshot is
                # reused across every apply until the next change, so the
                # batches must not each re-pay the host->device transfer.
                import jax

                self._model = (
                    int(step), jax.device_put(self._unflatten(flat))
                )
                self._refreshes += 1
            self._stop.wait(self._refresh_s)

    # -- the batched apply ---------------------------------------------------

    def _run_batch(self, items: list[dict]):
        """One padded jitted apply for a coalesced request list; returns
        ``(step, outputs_slice)`` per request.  Runs on the batch thread."""
        model = self._model
        if model is None:
            raise _NoModel()
        step, params = model
        proto = items[0]
        rows = [len(next(iter(it.values()))) for it in items]
        total = sum(rows)
        # Pad to the fixed max_batch shape so the jit cache holds ONE entry
        # per field signature; a lone oversized request runs at its own
        # (padded-to-itself) shape.
        padded = self.max_batch if total <= self.max_batch else total
        batch = {
            k: np.zeros((padded,) + np.asarray(v).shape[1:], np.asarray(v).dtype)
            for k, v in proto.items()
        }
        off = 0
        for it, r in zip(items, rows):
            for k in batch:
                batch[k][off : off + r] = it[k]
            off += r
        out = self._predict(params, batch)
        if not isinstance(out, dict):
            out = {"output": out}
        out_np = {k: np.asarray(v) for k, v in out.items()}
        results = []
        off = 0
        for r in rows:
            results.append(
                (step, {k: v[off : off + r] for k, v in out_np.items()})
            )
            off += r
        with self._lock:
            self._predicts += total
        return results

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        b = self._batcher.stats()
        core = self._core.core_stats()
        with self._lock:
            s = {
                "service": SERVICE,
                "role": self.role,
                "incarnation": self._incarnation,
                "model_step": self.model_step,
                # The served registry version (r19): 0 = hot-tracking the
                # live run; > 0 = pinned to an immutable registry snapshot
                # (same stamp the HELLO word and every predict response
                # carry — dtxtop's per-version rollup keys off this).
                "model_version": self.model_version,
                "model_name": self.model_name,
                "tenant": self.tenant,
                "pinned": self._pinned,
                # The uniform runtime-accounting shape (r17): requests /
                # live_conns come from the shared server core, same
                # meaning on every service's STATS answer; the r18 shed
                # counters surface top-level with the same keys the
                # native PS exports.
                "requests": core["requests"],
                "live_conns": core["live_conns"],
                "shed_total": core["shed_total"],
                "queue_deadline_drops": core["queue_deadline_drops"],
                "core": core,
                # Per-tenant admission/accounting rows (r20) surface
                # top-level like the other two services', so dtxtop's
                # tenants section reads one shape everywhere.
                "tenants": core["tenants"],
                "predict_rows": self._predicts,
                "overloads": self._overloads,
                "refreshes": self._refreshes,
                "refresh_errors": self._refresh_errors,
                "ps_shards": (
                    self._group.num_shards if self._group is not None else 0
                ),
                "layout_epoch": (
                    self._layout.version if self._layout is not None else 0
                ),
                "reshards_followed": self._reshards,
                "decode_sessions_open": len(self._sessions),
                "decode_opens": self._decode_opens,
                "leased": bool(
                    self._heartbeat is not None and self._heartbeat.enabled
                ),
            }
        s.update({f"batcher_{k}": v for k, v in b.items()})
        if self._engine is not None:
            s.update({f"decode_{k}": v for k, v in self._engine.stats().items()})
        s.update(self.latency.percentile_scalars("serve"))
        # The replica process's client-side instruments ride along (r13):
        # its PS legs' reconnect/failover counters are the externally
        # visible half of "this replica kept tracking through the fault".
        s["registry"] = telemetry.snapshot()
        s["flight_events"] = len(telemetry.RECORDER)
        return s

    # -- the core handler ----------------------------------------------------
    # One registered handler on the shared server core (r17): the core
    # owns accept/read/write/HELLO/counting.  PREDICT is ASYNC — the
    # handler submits to the batcher and returns immediately; the
    # ticket's resolve callback (batch thread) queues the reply on the
    # connection, so a slow peer buffers bytes instead of wedging a
    # worker, and the bounded pool never caps the coalesced batch size.

    def _handle(self, conn, op: int, name: str, a: int, b: int, payload):
        if op == SRV_PREDICT:
            t0 = time.perf_counter()
            try:
                inputs = wire.decode_batch_bytes(payload)
            except (ValueError, TypeError, KeyError):
                return ERR, None
            return self._handle_predict(conn, inputs, t0)
        if op == SRV_DECODE_OPEN:
            return self._handle_decode_open(a, payload)
        if op == SRV_DECODE_NEXT:
            return self._handle_decode_next(a, b)
        if op == SRV_DECODE_CLOSE:
            return self._handle_decode_close(a)
        if op == SRV_STATS:
            return 0, [json.dumps(self.stats()).encode()]
        if op == SRV_SHUTDOWN:
            self.shutdown_requested.set()
            return 0, None
        return ERR, None

    # -- decode sessions (r19) ----------------------------------------------

    def _stamp(self, out: dict) -> dict:
        """Every predict/decode response batch carries the served registry
        version next to its model_step (the status) — the per-response
        half of version observability (wire.SRV_VERSION_FIELD; clients
        strip it before handing outputs to the caller)."""
        out = dict(out)
        out[wire.SRV_VERSION_FIELD] = np.int64(self.model_version)
        return out

    def _handle_decode_open(self, max_new_tokens: int, payload):
        if self._engine is None:
            return NO_DECODER, None
        if self._model is None:
            return NO_MODEL, None
        try:
            inputs = wire.decode_batch_bytes(payload)
            prompt = np.asarray(inputs["prompt"])
        except (ValueError, TypeError, KeyError):
            return ERR, None
        try:
            ticket = self._engine.open(prompt, max_new_tokens)
        except ValueError:
            return ERR, None
        except batcher_lib.Overloaded:
            with self._lock:
                self._overloads += 1
            return wire.retry_later_status(self._retry_after_ms), None
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            self._sessions[sid] = [ticket, time.monotonic()]
            self._decode_opens += 1
        return sid, None

    def _handle_decode_next(self, sid: int, cursor: int):
        with self._lock:
            entry = self._sessions.get(sid)
            if entry is not None:
                entry[1] = time.monotonic()
        if entry is None:
            return BAD_SESSION, None
        ticket = entry[0]
        try:
            tokens, done = ticket.snapshot(cursor)
        except _NoModel:
            return NO_MODEL, None
        except Exception:  # noqa: BLE001 — a failed step answers loudly
            log.error("decode session %d failed server-side", sid,
                      exc_info=True)
            with self._lock:
                self._sessions.pop(sid, None)
            return ERR, None
        out = self._stamp({
            "tokens": np.asarray(tokens, np.int32),
            "done": np.asarray([1 if done else 0], np.uint8),
        })
        return self.model_step, wire.encode_batch(out)

    def _handle_decode_close(self, sid: int):
        with self._lock:
            entry = self._sessions.pop(sid, None)
        if entry is not None:
            entry[0].cancel()
        return 0, None  # idempotent: closing an unknown session is a no-op

    def _handle_predict(self, conn, inputs: dict, t0: float):
        if not inputs:
            return ERR, None
        lens = {len(np.asarray(v)) if np.asarray(v).ndim else -1
                for v in inputs.values()}
        if len(lens) != 1 or -1 in lens:
            # Every field must share one leading dim — the row unit the
            # batcher budgets and the scatter slices by.
            return ERR, None
        if self._model is None:
            return NO_MODEL, None
        # Requests coalesce only with SCHEMA-IDENTICAL neighbours (same
        # field names, trailing shapes and dtypes): one client sending a
        # mismatched request must never poison a well-formed concurrent
        # request's batch (it fails alone, in its own apply).
        schema = tuple(sorted(
            (k, np.asarray(v).shape[1:], str(np.asarray(v).dtype))
            for k, v in inputs.items()
        ))
        try:
            ticket = self._batcher.submit(inputs, rows=lens.pop(), key=schema)
        except batcher_lib.Overloaded:
            # r18: the batcher's admission refusal answers the typed
            # RETRY_LATER band — the shed carries its backoff hint in the
            # status, so resilient clients back off for a meaningful beat
            # instead of re-hammering the rotation (the legacy OVERLOAD
            # code point stays recognized client-side).
            with self._lock:
                self._overloads += 1
            return wire.retry_later_status(self._retry_after_ms), None

        def _resolved(value, error) -> None:
            with self._lock:
                self._pending_tickets.pop(ticket, None)
            if error is not None:
                if isinstance(error, _NoModel):
                    conn.reply(NO_MODEL, None)
                    return
                # An apply bug (or the batcher's stop-drain error, or
                # the wedged-apply timeout sweep) must surface as a LOUD
                # per-op error on the client, not a silent connection
                # close — WITH the traceback, since the client's typed
                # error message points operators at this log.
                log.error(
                    "batched predict failed server-side", exc_info=error
                )
                conn.reply(ERR, None)
                return
            step, out = value
            try:
                # Same invariant the core's worker guards on the sync
                # path: an output the wire cannot encode must answer a
                # loud ERR — an escape here would be swallowed by the
                # ticket's callback container with NO reply sent,
                # wedging the connection in_flight forever.  reply()
                # normalizes its buffers before queuing anything, so
                # the ERR after a failed attempt is the first frame.
                conn.reply(step, wire.encode_batch(self._stamp(out)))
            except Exception:
                log.error(
                    "predict reply failed (unserializable output?)",
                    exc_info=True,
                )
                conn.reply(ERR, None)
                return
            self.latency.record(time.perf_counter() - t0)
            if (
                self._writer is not None
                and self.latency.total % self._metrics_every == 0
            ):
                self._writer.scalars(
                    self.model_step, self.latency.percentile_scalars("serve")
                )

        with self._lock:
            self._pending_tickets[ticket] = (
                time.monotonic() + self._ticket_deadline_s
            )
        ticket.on_resolve(_resolved)
        return server_core.ASYNC


class _NoModel(RuntimeError):
    """Raised inside a batch whose replica has no pulled snapshot yet —
    mapped to the NO_MODEL status per request (warming replicas shed load
    explicitly, like overload)."""


# ----------------------------------------------------------------------------
# Task-role hosting (the runner's `serve` job)
# ----------------------------------------------------------------------------


def host_serve_task(
    *, init_fn, predict_fn, ps_addrs, port: int, loopback_only: bool = True,
    max_batch: int = 32, max_wait_ms: float = 5.0, queue_depth: int = 128,
    refresh_ms: float = 50.0, op_timeout_s: float | None = 10.0,
    reconnect_deadline_s: float = 60.0, metrics_dir: str | None = None,
    membership: bool = True, lease_ttl_s: float = 10.0,
    advertise_addr: str | None = None, ps_replicas: int = 1,
    layout_version: int = 0, queue_deadline_ms: float = 0.0,
    registry_dir: str | None = None, model_name: str = "default",
    model_version: int | None = None, decode_fns: tuple | None = None,
    decode_slots: int = 4, decode_max_len: int = 512,
    tenant: str = tenancy.DEFAULT_TENANT, tenant_quotas: dict | None = None,
) -> int:
    """Dedicated serve-task body (``--job_name=serve``): host one replica
    until a client signals SRV_SHUTDOWN (or the supervisor dies).  Arms
    ``die`` fault specs off the replica's request counter — the
    deterministic "kill replica i at request N" fault the serving recovery
    tests inject; a supervisor restart re-pulls the current params from the
    PS and rejoins the rotation with zero coordination.  With
    ``registry_dir`` + ``model_version`` (``--registry_dir`` /
    ``--serve_model_version``) the replica PINS that registry version
    instead of hot-tracking — a supervised restart re-loads the SAME
    version, so a rolling deploy's replica set keeps its meaning through
    kills."""
    server = ModelReplicaServer(
        init_fn, predict_fn, ps_addrs, port=port,
        loopback_only=loopback_only, max_batch=max_batch,
        max_wait_ms=max_wait_ms, queue_depth=queue_depth,
        refresh_ms=refresh_ms, op_timeout_s=op_timeout_s,
        reconnect_deadline_s=reconnect_deadline_s, metrics_dir=metrics_dir,
        membership=membership, lease_ttl_s=lease_ttl_s,
        advertise_addr=advertise_addr, ps_replicas=ps_replicas,
        layout_version=layout_version, queue_deadline_ms=queue_deadline_ms,
        registry_dir=registry_dir, model_name=model_name,
        model_version=model_version, decode_fns=decode_fns,
        decode_slots=decode_slots, decode_max_len=decode_max_len,
        tenant=tenant, tenant_quotas=tenant_quotas,
    )
    faults.arm_process_faults(
        request_count_fn=server.request_count,
        leave_fn=lambda: server.stop(),
    )
    if not server.wait_for_model(timeout_s=120.0):
        log.warning(
            "serve task: no published params after 120 s — serving NO_MODEL "
            "until the chief publishes"
        )
    log.info(
        "serve task on port %d (model_step=%d; blocking until shutdown)",
        server.port, server.model_step,
    )
    supervised = os.environ.get("DTX_SERVE_SUPERVISED") == "1"
    ppid0 = os.getppid()
    while not server.shutdown_requested.wait(timeout=2.0):
        if supervised and os.getppid() != ppid0:
            log.warning("serve task: supervisor died; exiting")
            break
    bound = server.port
    server.stop()
    return bound
