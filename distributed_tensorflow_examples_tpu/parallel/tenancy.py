"""dtxtenant — the multi-tenant namespace substrate (r20).

Until now the cluster served exactly ONE run end-to-end: one PS object
space, one lease registry, one data-service job, one served model.  This
module is the namespace layer that lets N training runs and M served
models share one PS tier, one data service and one serve pool without
interfering — the tf.data-service sharing argument (disaggregated input
workers exist precisely to be shared across jobs) and the TensorFlow
paper's concurrent-sessions-on-one-runtime capability, rebuilt for the
flat-param substrate.

Tenancy is a KEY-PREFIX protocol, deliberately NOT a new wire op family:

- A tenant's PS objects live under ``t.<tenant>.<name>`` and its lease
  identities under ``t.<tenant>.<member>`` (:func:`qualify`).  The
  ``default`` tenant's keys carry NO prefix at all, so every untagged
  (pre-tenant) client interops byte-identically — v<=4 frames are the
  default tenant by construction, not by negotiation.
- :func:`split_qualified` is the inverse every consumer (lease watchers,
  STATS breakdowns, dtxtop) uses to attribute a key to its tenant.
- Data-service and serve requests tag the tenant into the existing
  ``name`` operand (:func:`tag_name` / :func:`untag_name`) — again
  absent for the default tenant, so the frames of an untagged client do
  not change by a single byte.
- :class:`TenantQuota` + :func:`parse_quotas` carry the per-tenant
  admission policy (``--tenant_quotas``) the server core's weighted-fair
  dispatcher enforces.

EVERY tenant-prefixed key in ``parallel/`` and ``serve/`` must be built
through :func:`qualify` — pinned by ``tools/dtxlint``'s ``tenant`` pass,
which refuses any other construction of the ``t.`` prefix.
"""

from __future__ import annotations

import dataclasses
import re

from . import wire

#: The tenant every untagged key/frame/member belongs to.  Its keys are
#: the BARE names — qualify() is the identity for it — which is the whole
#: back-compat story: a pre-tenant client IS a default-tenant client.
DEFAULT_TENANT = "default"

#: Legal tenant ids: short, no dots (dots delimit the qualified form), no
#: ``|`` (the pack_member field separator), no commas (the name-operand
#: tag separator) — safe inside PS object keys, lease member docs,
#: registry model names (``[A-Za-z0-9._-]``) and JSON alike.
_TENANT_RE = re.compile(r"^[A-Za-z0-9_-]{1,32}$")

_PREFIX = wire.TENANT_KEY_PREFIX

#: PS op numbers whose ``name`` is a tenant-scoped object key, derived
#: from the wire registry (never restated — dtxlint pins the derivation).
PS_SCOPED_OP_CODES = frozenset(
    wire.PS_OPS[name] for name in wire.TENANT_SCOPED_OPS["ps"]
)


def check_tenant(tenant: str) -> str:
    """Validate a tenant id (returns it).  Raises ValueError on anything
    that could not ride every key space unambiguously."""
    if not _TENANT_RE.match(tenant or ""):
        raise ValueError(
            f"tenant id {tenant!r} must match {_TENANT_RE.pattern} "
            "(no dots/pipes/commas — they delimit the key spaces)"
        )
    return tenant


def qualify(tenant: str, name: str) -> str:
    """The ONE tenant-key constructor: ``t.<tenant>.<name>`` for a
    non-default tenant, the bare name for the default tenant (identity —
    byte-identical back-compat) and for empty names (control ops carry no
    key to scope)."""
    if not name or tenant == DEFAULT_TENANT:
        return name
    return f"{_PREFIX}{check_tenant(tenant)}.{name}"


def split_qualified(name: str) -> tuple[str, str]:
    """Inverse of :func:`qualify`: ``(tenant, bare_name)``.  Unprefixed
    names (and malformed prefixes) belong to the default tenant."""
    if name.startswith(_PREFIX):
        rest = name[len(_PREFIX):]
        tenant, sep, bare = rest.partition(".")
        if sep and bare and _TENANT_RE.match(tenant):
            return tenant, bare
    return DEFAULT_TENANT, name


def tenant_of(name: str) -> str:
    """The tenant a (possibly qualified) key belongs to."""
    return split_qualified(name)[0]


def tenant_prefix(tenant: str) -> str:
    """The key prefix selecting everything a tenant owns — the CANCEL_ALL
    filter a non-default tenant sends so its reseed can never touch
    another tenant's objects ('' for the default tenant: its bare keys
    have no selectable prefix, so it cancels the whole space — the
    documented pre-tenant behavior)."""
    if tenant == DEFAULT_TENANT:
        return ""
    return f"{_PREFIX}{check_tenant(tenant)}."


# ----------------------------------------------------------------------------
# Name-operand tagging (dsvc / msrv): the tenant rides the existing
# ``name`` field as a ``,t=<tenant>`` suffix (bare ``t=<tenant>`` when the
# base name is empty) — absent for the default tenant, so untagged frames
# stay byte-identical.
# ----------------------------------------------------------------------------

_TAG_SEP = ",t="
_TAG_BARE = "t="


def tag_name(name: str, tenant: str) -> str:
    """Tag a request's ``name`` operand with the caller's tenant."""
    if tenant == DEFAULT_TENANT:
        return name
    check_tenant(tenant)
    if not name:
        return f"{_TAG_BARE}{tenant}"
    return f"{name}{_TAG_SEP}{tenant}"


def untag_name(name: str) -> tuple[str, str]:
    """Inverse of :func:`tag_name`: ``(bare_name, tenant)``."""
    if name.startswith(_TAG_BARE) and _TAG_SEP not in name:
        tenant = name[len(_TAG_BARE):]
        if _TENANT_RE.match(tenant):
            return "", tenant
        return name, DEFAULT_TENANT
    base, sep, tenant = name.rpartition(_TAG_SEP)
    if sep and _TENANT_RE.match(tenant):
        return base, tenant
    return name, DEFAULT_TENANT


# ----------------------------------------------------------------------------
# Per-tenant admission policy (the server core's weighted-fair dispatch).
# ----------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission policy.

    ``weight`` steers the fair-dispatch share (stride scheduling: a
    tenant with weight 2 drains twice as fast as weight 1 under
    contention — idle tenants cost nothing).  ``max_inflight`` caps the
    tenant's dispatched-but-unanswered requests across ALL its
    connections; ``max_dispatch`` caps its queued (admitted, undispatched)
    requests.  0 = unlimited (the core's global bounds still apply).  A
    tenant at quota is SHED with a RETRY_LATER hint while other tenants'
    traffic flows — that is the isolation contract.
    """

    weight: float = 1.0
    max_inflight: int = 0
    max_dispatch: int = 0

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {self.weight}")
        if self.max_inflight < 0 or self.max_dispatch < 0:
            raise ValueError("tenant quotas must be >= 0 (0 = unlimited)")


def parse_quotas(spec: str) -> dict[str, TenantQuota]:
    """Parse a ``--tenant_quotas`` spec: comma-separated
    ``tenant=weight[:max_inflight[:max_dispatch]]`` entries, e.g.
    ``a=1:32:128,b=4`` — tenant ``a`` at weight 1 with 32 in-flight / 128
    queued caps, tenant ``b`` at weight 4, uncapped."""
    out: dict[str, TenantQuota] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        tenant, sep, rhs = entry.partition("=")
        if not sep:
            raise ValueError(
                f"bad --tenant_quotas entry {entry!r}: want "
                "tenant=weight[:max_inflight[:max_dispatch]]"
            )
        check_tenant(tenant.strip())
        parts = rhs.split(":")
        if len(parts) > 3:
            raise ValueError(
                f"bad --tenant_quotas entry {entry!r}: at most "
                "weight:max_inflight:max_dispatch"
            )
        try:
            weight = float(parts[0]) if parts[0] else 1.0
            max_inflight = int(parts[1]) if len(parts) > 1 and parts[1] else 0
            max_dispatch = int(parts[2]) if len(parts) > 2 and parts[2] else 0
        except ValueError as e:
            raise ValueError(
                f"bad --tenant_quotas entry {entry!r}: {e}"
            ) from None
        out[tenant.strip()] = TenantQuota(weight, max_inflight, max_dispatch)
    return out
