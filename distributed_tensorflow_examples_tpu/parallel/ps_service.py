"""Client for the cross-process PS service (native/ps_server.cc).

The thread-mode async-PS emulation (parallel/async_ps.py) talks to the
native accumulator/token/gradient-queue structs through direct ctypes calls;
this module provides the SAME object APIs over a localhost TCP socket, so
the W1/W2 emulations run across real processes — the reference's PS/worker
process topology (SURVEY.md sections 3.1/3.2), with the chief process
hosting the service (the PS task role) and each worker process connecting.

One socket per client; requests are serialized on it (a worker's op
sequence is sequential anyway, and blocking ops — token pop, accumulator
take, gradient pop — tie up only that client's server-side thread).

Fault tolerance (r6): the reference's fault model lost the whole job when a
PS task died (a stalled session torn down and crash-restarted, SURVEY.md
section 5.3).  Here the client itself heals the connection:

- every op takes a DEADLINE (``op_timeout_s``); blocking ops are issued as
  bounded server-side waits the client re-issues, so a dead peer surfaces
  as a timeout instead of an eternal hang;
- a transport failure triggers exponential-backoff RECONNECT (bounded by
  ``reconnect_deadline_s``), after which the op is REPLAYED.  Gradient
  WRITES are exactly-once: applies/pushes are dedup-tagged with a
  per-worker monotone sequence number the server remembers, so a gradient
  that DID land before the drop is answered "duplicate", never applied
  twice.  Drain ops (take / token pop / gradient pop) are at-most-once:
  a response lost after the server commits loses that drained
  average/token/gradient.  Token pushes are at-LEAST-once: a replayed
  push may add extra same-step tokens, whose extra gradients are averaged
  in or staleness-dropped — the same effect (and tolerance) as the
  chief's stall-triggered token re-push
  (``AsyncPSTrainer.sync_stall_repush_s``), which heals the lost
  tokens/aggregations of the at-most-once drains.  A lost async gradient
  is equivalent to a stale-drop (harmless);
- on reconnect the client compares the server's INCARNATION id: a changed
  id means the PS restarted and lost all state, so the client re-issues
  its object-creation ops and runs registered ``on_reincarnation``
  callbacks (the chief republishes params and re-seeds step/tokens).

Every recovery action logs one structured ``dtx.faults`` line; fault
INJECTION (the ``DTX_FAULT_PLAN`` env var) hooks in at ``call()`` — see
``utils/faults.py``.

Transport fast path (r7): the framing is zero-copy in both directions —
requests leave as a scatter/gather ``sendmsg`` (header bytes + a
``memoryview`` over the caller's contiguous array; no ``tobytes()``, no
concat) and responses land via ``recv_into`` straight into the output
array (the old ``bytes +=`` accumulation was O(n²) in the payload size).
Payload encoding is a per-connection property negotiated at connect (wire
v2 ``HELLO``): f32 — byte-identical to the v1 framing — or bf16
(``wire_dtype="bf16"``), which halves param/grad bytes on the wire while
the server keeps storing f32.  ``RemoteParamStore.get`` is versioned: a
client-side cache plus the ``PSTORE_GET_IF_NEWER`` op make an
unchanged-step pull cost one header-sized round trip instead of re-shipping
the whole flat vector.

The frame layout, HELLO negotiation, zero-copy send/recv and the bf16
codec live in ``parallel/wire.py`` (r8), shared with the disaggregated
data service (``data/data_service.py``) so the two wires cannot drift.
On THIS wire, payload lengths count ELEMENTS of the negotiated dtype (the
C++ server's contract); the data wire counts bytes.

Sharded store (r9): ``parallel/ps_shard.py`` spreads the flat parameter
vector over N of these servers (one ``PSClient`` per shard, HELLO pinned
via ``expect_shard``) and scatter/gathers concurrently; this module stays
the single-connection layer it builds on.  ``call(out=...)`` receives a
response directly into a caller-provided buffer slice — the sharded
gather's zero-staging path.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time

import numpy as np

from .. import native
from ..utils import faults, telemetry
from . import retry, tenancy, wire

# Op codes — aliases into the ONE registry (wire.PS_OPS, the single Python
# definition site; tools/dtxlint pins it against native/ps_server.cc's
# enum Op by name and number).  Never restate the numbers here.
_ACC_GET = wire.PS_OPS["ACC_GET"]
_ACC_APPLY = wire.PS_OPS["ACC_APPLY"]
_ACC_TAKE = wire.PS_OPS["ACC_TAKE"]
_ACC_SET_STEP = wire.PS_OPS["ACC_SET_STEP"]
_ACC_DROPPED = wire.PS_OPS["ACC_DROPPED"]
_TQ_GET = wire.PS_OPS["TQ_GET"]
_TQ_PUSH = wire.PS_OPS["TQ_PUSH"]
_TQ_POP = wire.PS_OPS["TQ_POP"]
_GQ_GET = wire.PS_OPS["GQ_GET"]
_GQ_PUSH = wire.PS_OPS["GQ_PUSH"]
_GQ_POP = wire.PS_OPS["GQ_POP"]
_GQ_SET_MIN = wire.PS_OPS["GQ_SET_MIN"]
_GQ_DROPPED = wire.PS_OPS["GQ_DROPPED"]
_CANCEL_ALL = wire.PS_OPS["CANCEL_ALL"]
_PING = wire.PS_OPS["PING"]
_PSTORE_GET_OBJ = wire.PS_OPS["PSTORE_GET_OBJ"]
_PSTORE_SET = wire.PS_OPS["PSTORE_SET"]
_PSTORE_GET = wire.PS_OPS["PSTORE_GET"]
_INCARNATION = wire.PS_OPS["INCARNATION"]
_ACC_APPLY_TAGGED = wire.PS_OPS["ACC_APPLY_TAGGED"]
_GQ_PUSH_TAGGED = wire.PS_OPS["GQ_PUSH_TAGGED"]
_ACC_DEDUPED = wire.PS_OPS["ACC_DEDUPED"]
_GQ_DEDUPED = wire.PS_OPS["GQ_DEDUPED"]
_ACC_RESET_WORKER = wire.PS_OPS["ACC_RESET_WORKER"]
_GQ_RESET_WORKER = wire.PS_OPS["GQ_RESET_WORKER"]
_HELLO = wire.PS_OPS["HELLO"]
_PSTORE_GET_IF_NEWER = wire.PS_OPS["PSTORE_GET_IF_NEWER"]
_REPL_SYNC = wire.PS_OPS["REPL_SYNC"]
_REPL_TOKEN = wire.PS_OPS["REPL_TOKEN"]
_STATS = wire.PS_OPS["STATS"]
_LEASE_ACQUIRE = wire.PS_OPS["LEASE_ACQUIRE"]
_LEASE_RELEASE = wire.PS_OPS["LEASE_RELEASE"]
_LEASE_LIST = wire.PS_OPS["LEASE_LIST"]
_RESHARD_BEGIN = wire.PS_OPS["RESHARD_BEGIN"]
_RESHARD_COMMIT = wire.PS_OPS["RESHARD_COMMIT"]
_RESHARD_GET = wire.PS_OPS["RESHARD_GET"]
_RESHARD_ABORT = wire.PS_OPS["RESHARD_ABORT"]

# Client-side observability (r13 dtxobs): every PSClient in the process
# accumulates into these process-wide instruments — cached handles, so the
# per-op cost is one lock + an int add (the `ps_client/*` family the STATS
# scrapes of Python services, and tests, read via telemetry.snapshot()).
_OBS_OPS = telemetry.REGISTRY.counter("ps_client/ops")
_OBS_ERRS = telemetry.REGISTRY.counter("ps_client/op_errors")
_OBS_TX = telemetry.REGISTRY.counter("ps_client/bytes_tx")
_OBS_RX = telemetry.REGISTRY.counter("ps_client/bytes_rx")
_OBS_OP_MS = telemetry.REGISTRY.histogram("ps_client/op_ms")
_OBS_RECONNECTS = telemetry.REGISTRY.counter("ps_client/reconnects")
_OBS_CONN_LOST = telemetry.REGISTRY.counter("ps_client/conn_lost")
_OBS_REBUILDS = telemetry.REGISTRY.counter("ps_client/state_rebuilds")
_OBS_FAILOVERS = telemetry.REGISTRY.counter("ps_client/failovers")
_OBS_PULL_HITS = telemetry.REGISTRY.counter("ps_client/pull_cache_hits")

#: Wire protocol version this client speaks (ps_server.cc kWireVersion).
WIRE_VERSION = wire.WIRE_VERSION

#: Payload encodings (HELLO dtype codes).  f32 framing is byte-identical
#: to wire v1; bf16 halves payload bytes and REQUIRES a negotiated peer.
WIRE_DTYPES = wire.WIRE_DTYPES

# The bf16 codec (round-to-nearest-even, bit-exact with the C++ server)
# lives in parallel/wire.py; these module names stay as the stable import
# point for tests and the bench.
_f32_to_bf16 = wire.f32_to_bf16
_bf16_to_f32 = wire.bf16_to_f32

#: Deadline sentinel for bounded blocking ops (take/pop with ``timeout_s``).
TIMED_OUT = native.TIMED_OUT

#: How long a tagged gradient push keeps polling a FULL queue before the
#: stall is treated as a dead/wedged chief (PSDeadlineError) rather than
#: ordinary backpressure.
_PUSH_STALL_LIMIT_S = 600.0


class PSError(RuntimeError):
    """A PS op failed terminally (transport down and unrecoverable, or the
    server rejected the request)."""


class _StateLost(Exception):
    """Internal recovery signal: the replica just reconnected to carries a
    DIFFERENT state token (restarted empty, peer unreachable) — try the
    other replicas before falling back to the rebuild/reseed path.
    Deliberately not a PSError: the generic recovery retry must not
    swallow it."""


class PSDeadlineError(PSError):
    """Reconnect budget exhausted: the PS stayed unreachable past
    ``reconnect_deadline_s``."""


def start_server(
    port: int = 0, *, loopback_only: bool = True, shard_id: int = 0,
    shard_count: int = 1, layout_version: int = 0,
    peer: tuple[str, int] | None = None, sync_wait_s: float = 0.0,
) -> int:
    """Start an in-process C++ PS server; returns the bound port.

    ``loopback_only=False`` binds all interfaces — required when workers on
    OTHER hosts dial this PS task (the protocol is unauthenticated, so only
    do this on a trusted cluster network, as with the reference's gRPC).

    (``shard_id``, ``shard_count``) is the server's shard identity (r9):
    which contiguous slice of the flat parameter vector it owns.  HELLO
    validates a shard-aware client's expectation against it, so a
    mis-wired dial fails loudly.  One process may host SEVERAL shard
    servers (the chief-hosted sharded topology and the shard bench).

    Replication (r12): ``layout_version`` joins the HELLO identity (the
    shard-topology epoch — mixed-epoch clients fail the dial loudly), and
    ``peer`` names this shard's peer replica: state-mutating ops forward
    to it, and the start blocks up to ``sync_wait_s`` pulling the peer's
    full state (REPL_SYNC) — adopting its STATE TOKEN — before serving."""
    host, pport = peer if peer is not None else ("", 0)
    p = native._load().ps_server_start_replicated(
        port, 1 if loopback_only else 0, shard_id, shard_count,
        int(layout_version), host.encode() if host else None, int(pport),
        int(sync_wait_s * 1000),
    )
    if p < 0:
        raise RuntimeError("ps_server_start failed")
    return p


def set_server_peer(port: int, peer: tuple[str, int]) -> bool:
    """Wire a running shard server to its peer replica (the in-process
    replicated topology binds ephemeral ports first, then pairs them)."""
    return bool(
        native._load().ps_server_set_peer(port, peer[0].encode(), peer[1])
    )


def resync_server(port: int, wait_s: float = 5.0) -> bool:
    """On-demand REPL_SYNC: the server at ``port`` pulls its peer's full
    state (adopting the peer's state token).  The in-process analog of the
    restarted-task start-time catch-up."""
    return bool(
        native._load().ps_server_resync_port(port, int(wait_s * 1000))
    )


def set_server_partitioned(port: int, on: bool) -> bool:
    """Inject a replication partition at the server at ``port``: its
    peer's repl connections are refused by policy and its own forwards
    fail — the ``partition`` fault kind's server-side primitive."""
    return bool(
        native._load().ps_server_set_partitioned(port, 1 if on else 0)
    )


def server_state_token(port: int) -> int:
    """A shard server's state-lineage token (-1 = no server there)."""
    return int(native._load().ps_server_state_token_port(port))


def server_diverged(port: int) -> int:
    """Whether the server at ``port`` latched replication divergence
    (1/0; -1 = no server there)."""
    return int(native._load().ps_server_diverged_port(port))


def server_live_conns(port: int) -> int:
    """Live client connections at the server at ``port`` (-1 = none
    there) — the orphaned-replica signal ``host_ps_task`` watches."""
    return int(native._load().ps_server_live_conns_port(port))


def set_server_draining(port: int, on: bool = True) -> bool:
    """Mark the server at ``port`` DRAINING (r15): a reshard retired its
    layout and the host is waiting out the last connections before exit —
    exported in STATS so a mid-transition cluster reads correctly in
    dtxtop."""
    return bool(
        native._load().ps_server_set_draining(port, 1 if on else 0)
    )


def stop_server(port: int | None = None) -> None:
    """Stop ALL in-process servers, or — ``port`` given — just the shard
    server bound there (the targeted-kill primitive for single-shard fault
    tests against in-process topologies)."""
    if port is None:
        native._load().ps_server_stop()
    else:
        native._load().ps_server_stop_port(port)


def server_incarnation(port: int | None = None) -> int:
    """A live server's incarnation id (-1 when none runs): the oldest
    server's by default, or the shard server bound at ``port``."""
    lib = native._load()
    if port is None:
        return int(lib.ps_server_incarnation())
    return int(lib.ps_server_incarnation_port(port))


def server_request_count(port: int | None = None) -> int:
    """Requests served (-1 when no server runs) — the trigger for
    ``die:after_reqs`` fault specs.  Default: the SUM across this process's
    live servers (with several local shards, the process's total traffic);
    ``port`` narrows to one shard server."""
    lib = native._load()
    if port is None:
        return int(lib.ps_server_requests())
    return int(lib.ps_server_requests_port(port))


class PSClient:
    """One TCP connection to the PS server; thread-safe via a lock.

    ``timeout_s``            connect timeout AND the default op deadline
                             (pre-r6 compatible: None = block forever).
    ``op_timeout_s``         per-op deadline; overrides ``timeout_s`` for
                             ops.  Blocking ops get this ON TOP of their
                             bounded server-side wait.
    ``reconnect_deadline_s`` > 0 enables recovery: on a transport failure
                             the client reconnects (exponential backoff,
                             giving up — ``PSDeadlineError`` — after this
                             many seconds of unreachability) and replays
                             the op.  0 = pre-r6 fail-fast behavior.
    ``worker_tag``           this client's worker id: non-None makes
                             accumulator applies / gradient pushes
                             dedup-tagged (replay-safe).  Plain applies on
                             a recovering client are refused instead of
                             risking a double apply.
    ``role``                 fault-plan role for DTX_FAULT_PLAN matching
                             (defaults to the process role).
    ``wire_dtype``           payload encoding on this connection: "f32"
                             (default; v1-compatible framing, no handshake
                             needed) or "bf16" (half the payload bytes both
                             ways; negotiated at connect via HELLO, so a
                             peer that can't speak wire v2 fails the
                             connection loudly instead of misparsing).
    ``expect_shard``         (shard_id, shard_count) this client expects of
                             the server it dials (r9 sharded PS).  Non-None
                             forces the HELLO handshake on every connect
                             (f32 included) and a server owning a DIFFERENT
                             shard fails the connection loudly — a
                             mis-wired dial must never silently serve the
                             wrong slice of the parameter vector.  None =
                             no expectation (pre-r9 framing, byte-identical
                             for f32).
    ``expect_layout``        the shard-topology EPOCH this client expects
                             (r12 layout version; 0 = no expectation).
                             Non-zero forces the handshake and a server on
                             a different epoch fails the dial loudly
                             naming both versions — the guard that makes
                             mixed-epoch clients impossible during a
                             (future) live reshard.
    ``addrs``                the full ordered replica address list for
                             this shard (r12; entry 0 is the primary —
                             ``host``/``port`` must equal it when both are
                             given).  With a backup present, recovery
                             ALTERNATES replicas and compares the shard's
                             STATE TOKEN on every reconnect: a token match
                             means the state survived (failover or synced
                             restart — NO reseed, by design zero chief
                             involvement); only when every replica's token
                             proves the state lost does the full
                             reincarnation path (object re-create +
                             ``on_reincarnation`` callbacks, i.e. chief
                             reseed) run as the last resort.  Ops issued
                             while connected to a backup replica inject
                             faults under the ``<role>_b`` client role.
    """

    #: Server-side wait per blocking-op round trip when the client has a
    #: deadline/recovery configured; each expiry just re-issues, so this
    #: only bounds how fast a dead peer is noticed.
    block_chunk_s = 2.0

    def __init__(
        self, host: str, port: int, *, timeout_s: float | None = None,
        op_timeout_s: float | None = None, reconnect_deadline_s: float = 0.0,
        backoff_s: float = 0.25, worker_tag: int | None = None,
        role: str | None = None, wire_dtype: str = "f32",
        expect_shard: tuple[int, int] | None = None,
        expect_layout: int = 0,
        addrs: list[tuple[str, int]] | None = None,
        control_ops_are_fault_points: bool = False,
        tenant: str = tenancy.DEFAULT_TENANT,
    ):
        if wire_dtype not in WIRE_DTYPES:
            raise ValueError(
                f"wire_dtype {wire_dtype!r} not in {sorted(WIRE_DTYPES)}"
            )
        # Multi-tenancy (r20): every object-key op this client issues is
        # qualified under ``t.<tenant>.`` at the single call() choke point
        # (tenancy.qualify — the default tenant is the identity, keeping
        # pre-tenant clients byte-identical on the wire).
        self.tenant = (
            tenant if tenant == tenancy.DEFAULT_TENANT
            else tenancy.check_tenant(tenant)
        )
        self._addrs = list(addrs) if addrs else [(host, port)]
        if (host, port) != self._addrs[0]:
            raise ValueError(
                f"(host, port) ({host}:{port}) must be addrs[0] "
                f"({self._addrs[0][0]}:{self._addrs[0][1]})"
            )
        self._cur = 0
        self._host, self._port = self._addrs[0]
        self._expect_shard = expect_shard
        self._expect_layout = int(expect_layout)
        self._connect_timeout = timeout_s
        self._op_timeout = op_timeout_s if op_timeout_s is not None else timeout_s
        self._reconnect_deadline = reconnect_deadline_s
        self._backoff = backoff_s
        self.worker_tag = worker_tag
        self.role = role if role is not None else faults.current_role()
        self.wire_dtype = wire_dtype
        self._wire_code = WIRE_DTYPES[wire_dtype]
        self._lock = threading.RLock()
        self._in_recovery = False
        self._ensures: list[tuple[int, str, int, int]] = []
        self._callbacks: list = []
        self._reconnect_callbacks: list = []
        # Per-REPLICA injectors (the backup leg is its own fault role,
        # ``<role>_b``, with its own logical-op counter) — created lazily
        # so single-address clients keep the zero-cost no-faults path.
        # ``control_ops_are_fault_points``: a DEDICATED control client
        # (the ``_lm`` membership legs) counts its lease/control ops in
        # the fault op index — that stream IS its logical traffic; every
        # other client skips control ops (faults.control_op_codes) so
        # plan indices never drift with scrape/heartbeat/epoch cadence.
        self._control_fault_points = control_ops_are_fault_points
        self._injectors: dict[int, faults.ClientFaultInjector | None] = {}
        self._injector = self._leg_injector(0)
        # Shared retry discipline (r18, parallel/retry.py): replays and
        # shed retries spend this token-bucket budget (refilled by
        # successes), so N clients recovering from one blip can never
        # tighten into a retry storm; exhaustion surfaces as the typed
        # PSDeadlineError plus a flight-recorder event.
        self._budget = retry.RetryBudget()
        self._sock: socket.socket | None = None
        self._negotiated = False  # peer confirmed v4: deadline stamps OK
        self._hdr = bytearray(12)  # reusable response-header buffer
        # Per-replica incarnations + the shard's state-lineage token (r12):
        # a reconnect that finds the SAME token — on any replica — proves
        # the shard's state survived and skips every rebuild/reseed step.
        # None token = server predates REPL_TOKEN (incarnation semantics).
        self._incarnations: dict[int, int] = {}
        self._state_token: int | None = None
        try:
            self._connect()
            # The baseline incarnation: reconnects compare against this to
            # tell a transient drop from a restarted (state-lost) server.
            # Bounded by the configured deadlines so a stalled server fails
            # the ctor instead of hanging it.
            inc, _ = self._attempt(
                _INCARNATION,
                deadline_s=self._op_timeout
                if self._op_timeout is not None
                else self._connect_timeout,
            )
            self._incarnations[self._cur] = inc
            if len(self._addrs) > 1:
                # Token semantics are a REPLICATED-topology feature; a
                # single-address client keeps the exact pre-r12 op
                # sequence (and incarnation-only recovery).
                self._read_state_token()
        except OSError:
            if self._reconnect_deadline <= 0:
                raise
            # Construction during a PS outage (e.g. mid supervised restart)
            # gets the same recovery budget as any op: retry with backoff;
            # the empty incarnation map makes the first contact a plain
            # first-connect (replays the empty ensure list, records ids).
            self._recover(time.monotonic() + self._reconnect_deadline)

    def _leg_injector(self, idx: int):
        """The fault injector for replica leg ``idx``: the bare client role
        on the primary, ``<role>_b`` on a backup — so plans can target the
        failover leg without firing on the healthy one."""
        if idx not in self._injectors:
            leg_role = self.role if idx == 0 else f"{self.role}_b"
            self._injectors[idx] = faults.client_injector(
                leg_role, count_control_ops=self._control_fault_points,
            )
        return self._injectors[idx]

    def _switch_replica(self, idx: int) -> None:
        self._sever()
        self._cur = idx
        self._host, self._port = self._addrs[idx]
        self._injector = self._leg_injector(idx)

    def _read_state_token(self) -> None:
        """Learn the shard's state token from the connected server (None
        when the server predates the op)."""
        tok, _ = self._attempt(
            _REPL_TOKEN, deadline_s=self._op_timeout or 10.0
        )
        self._state_token = None if tok < 0 else tok

    # -- transport ----------------------------------------------------------

    def _connect(self) -> None:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        if (
            self._wire_code != WIRE_DTYPES["f32"]
            or self._expect_shard is not None
            or self._expect_layout
        ):
            # Encoding differs from the v1 framing (HELLO per connection —
            # the server's dtype is per-connection state, negotiated BEFORE
            # any payload op can be misparsed) — or the caller expects a
            # specific SHARD of a sharded store, which the server must
            # confirm before any payload lands on the wrong slice.  Plain
            # f32 connections without a shard expectation skip it: their
            # framing is byte-identical to v1, so nothing can misparse and
            # the connect stays one round trip cheaper.
            self._negotiate()
            # The peer answered a v4 HELLO: deadline stamps (r18) are
            # safe on this connection.  An UN-negotiated plain-f32
            # connection stays v1-byte-identical — it may be talking to
            # a pre-v4 peer that would misparse the stamp.
            self._negotiated = True

    def _negotiate(self) -> None:
        """HELLO on the fresh socket.  Transport failures raise OSError
        (retryable, like any connect failure); a peer that answers the
        wrong version — or doesn't know the op — raises PSError, which is
        PERMANENT and must not be retried by the reconnect loop."""
        # HELLO carries no payload either way, so it frames identically
        # under every encoding — safe to send before the answer arrives.
        # The "ps" service announcement (r10) rides in b's high bits: the
        # native server masks them out (back-compatible), while a Python
        # service reached by mistake refuses with a status naming itself.
        sid, scount = self._expect_shard if self._expect_shard else (0, 0)
        status, _ = self._attempt(
            _HELLO, a=WIRE_VERSION,
            b=wire.pack_hello_b(
                self._wire_code, sid, scount, service="ps",
                layout_version=self._expect_layout,
            ),
            deadline_s=self._connect_timeout
            if self._connect_timeout is not None
            else 10.0,
        )
        if status == WIRE_VERSION:
            return
        self._sever()
        got = wire.unpack_wrong_service(status)
        if got is not None:
            # Checked BEFORE the shard decode: wrong-service statuses live
            # in a range a genuine shard-mismatch echo can never produce
            # (its packed identity always carries shard_count >= 1 in bits
            # 20+, putting it far below this band).
            raise PSError(
                f"wrong-service dial: {self._host}:{self._port} is "
                f"{wire.SERVICE_NAMES[got]} ({got!r}), not the native PS "
                "state service — check --ps_hosts against the running tasks"
            )
        if status <= wire.HELLO_SHARD_MISMATCH:
            got_id, got_n, got_v = wire.unpack_shard_mismatch(status)
            if self._expect_layout and got_v != (
                self._expect_layout & wire.HELLO_LAYOUT_MASK
            ):
                raise PSError(
                    f"layout-version mismatch: {self._host}:{self._port} "
                    f"serves shard layout EPOCH {got_v} but this client "
                    f"expected epoch {self._expect_layout} — a mixed-epoch "
                    "client must never scatter onto a resharded store; "
                    "restart the stale end on the current topology"
                )
            raise PSError(
                f"mis-wired shard dial: {self._host}:{self._port} owns shard "
                f"{got_id}/{got_n} but this client expected shard "
                f"{sid}/{scount} — check the --ps_hosts order/--ps_shards "
                "against the running PS tasks"
            )
        raise PSError(
            f"wire negotiation with {self._host}:{self._port} failed: "
            f"asked v{WIRE_VERSION}/{self.wire_dtype}, peer answered "
            f"{status} (pre-v2 server, or unsupported dtype) — both ends "
            "must speak wire v2 for a non-f32 encoding"
        )

    def _sever(self) -> None:
        sock, self._sock = self._sock, None
        self._negotiated = False
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        # Also revoke the reconnect budget: an op issued after close (leaked
        # reference, teardown-ordered thread) must fail fast, not silently
        # resurrect a connection to the PS.
        self._reconnect_deadline = 0.0
        self._sever()

    def _encode_payload(self, payload: np.ndarray | None) -> np.ndarray | None:
        """The wire form of a payload: a contiguous f32 array (no copy when
        the caller's array already is one — the hot path) or its bf16 bit
        patterns (one vectorized conversion, the only data touch before the
        scatter/gather send)."""
        if payload is None:
            return None
        if self._wire_code == 1:
            return _f32_to_bf16(np.asarray(payload).reshape(-1))
        return np.ascontiguousarray(payload, np.float32).reshape(-1)

    def _send_frame(self, header: bytes, payload: np.ndarray | None) -> None:
        """Scatter/gather send: header + payload leave via ``sendmsg`` with
        a memoryview over the array — the payload bytes are never copied
        into a concatenated request buffer (wire.send_frame)."""
        wire.send_frame(self._sock, header, payload)

    def _recv_exact(self, view: memoryview) -> None:
        """Fill ``view`` from the socket via ``recv_into`` — no chunk
        accumulation (the old ``bytes +=`` loop was O(n²) in payload size),
        no staging copy: responses land directly in their final buffer
        (wire.recv_exact)."""
        wire.recv_exact(self._sock, view)

    def _attempt(
        self, op: int, name: str = "", a: int = 0, b: int = 0,
        payload: np.ndarray | None = None, *, deadline_s: float | None = None,
        out: np.ndarray | None = None, raw: bool = False,
    ) -> tuple[int, np.ndarray]:
        """One instrumented send/recv round trip (r13: per-op wall time and
        success/error counts land in the process ``ps_client/*`` telemetry
        family — one lock+add per op against cached instruments, cheap
        next to the socket round trip itself).  See ``_attempt_io``."""
        t0 = time.perf_counter()
        try:
            ret = self._attempt_io(
                op, name, a, b, payload, deadline_s=deadline_s, out=out,
                raw=raw,
            )
        except OSError:
            _OBS_ERRS.inc()
            raise
        _OBS_OPS.inc()
        _OBS_OP_MS.observe((time.perf_counter() - t0) * 1e3)
        return ret

    def _attempt_io(
        self, op: int, name: str = "", a: int = 0, b: int = 0,
        payload: np.ndarray | None = None, *, deadline_s: float | None = None,
        out: np.ndarray | None = None, raw: bool = False,
    ) -> tuple[int, np.ndarray]:
        """One send/recv round trip; severs the socket on ANY failure (the
        framing is broken mid-stream, so the connection is unusable).
        ``payload`` must already be wire-encoded (``_encode_payload``).
        ``out``: optional preallocated f32 destination — a response whose
        element count matches lands via ``recv_into`` DIRECTLY in it (the
        sharded gather's zero-staging path: each shard's slice of one
        output buffer); any other length falls back to a fresh array, so
        status-only answers (e.g. an unchanged-step pull) never clobber
        or misreport the caller's buffer.  ``raw``: the response payload is
        an UN-encoded byte blob counted in 4-byte units (STATS/REPL_SYNC
        shape) — returned as ``bytes``, never dtype-decoded."""
        if self._sock is None:
            raise ConnectionError("not connected")
        # Deadline propagation (r18): the caller's remaining per-op budget
        # rides in the frame header, so the server clamps blocking waits
        # to it and sheds work this client has already abandoned instead
        # of burning a thread on a dead request.  ONLY on a negotiated
        # (HELLO'd v4) connection — an un-negotiated plain-f32 socket may
        # be talking to a v1-framing peer that would misparse the stamp.
        header = wire.pack_request(
            op, name, a, b, 0 if payload is None else payload.size,
            deadline_ms=(
                0 if deadline_s is None or not self._negotiated
                else max(1, int(deadline_s * 1000))
            ),
        )
        try:
            self._sock.settimeout(deadline_s)
            self._send_frame(header, payload)
            _OBS_TX.inc(
                len(header) + (0 if payload is None else payload.nbytes)
            )
            hdr = memoryview(self._hdr)
            self._recv_exact(hdr)
            status, plen = struct.unpack("<qI", self._hdr)
            _OBS_RX.inc(
                12 + plen * (4 if raw else (2 if self._wire_code == 1 else 4))
            )
            if raw:
                blob = bytearray(plen * 4)
                if plen:
                    self._recv_exact(memoryview(blob))
                return status, bytes(blob)
            if status == wire.REPL_DIVERGED:
                # The replica refuses to accept a write it can no longer
                # replicate (its peer is alive but the link is down by
                # policy) — a PERMANENT loud failure, never retried: a
                # silent split-brain would diverge the two replicas'
                # state under every client that kept writing.  Fatal for
                # the run, so the flight recorder dumps NOW: the events
                # leading here (partitions, drops, failovers) are the
                # post-mortem (r13 dtxobs).
                faults.log_event(
                    "repl_diverged", role=self.role, host=self._host,
                    port=self._port, op_code=op,
                )
                telemetry.dump_flight_recorder("repl_diverged")
                raise PSError(
                    f"replication diverged: the PS at {self._host}:"
                    f"{self._port} refuses state-mutating ops because its "
                    "peer replica cannot mirror them (partitioned link, or "
                    "the peer restarted without syncing) — heal the link / "
                    "re-sync the lagging replica before resuming training"
                )
            if not plen:
                return status, np.empty((0,), np.float32)
            # Receive straight into the result array (f32) or its bf16
            # staging array (upconverted in one vectorized pass).  Freshly
            # allocated per response unless the caller supplied a matching
            # ``out`` — then the payload lands in the caller's buffer with
            # zero staging copies.
            if self._wire_code == 0:
                dst = out if out is not None and out.size == plen else None
                if dst is None:
                    dst = np.empty((plen,), np.float32)
                self._recv_exact(memoryview(dst.reshape(-1)).cast("B"))
                return status, dst
            raw = np.empty((plen,), np.uint16)
            self._recv_exact(memoryview(raw).cast("B"))
            if out is not None and out.size == plen:
                out.reshape(-1)[:] = _bf16_to_f32(raw)
                return status, out
            return status, _bf16_to_f32(raw)
        except OSError:
            self._sever()
            raise

    # -- recovery -----------------------------------------------------------

    def _qual(self, op: int, name: str) -> str:
        """Tenant-qualify an object key (r20): identity for the default
        tenant and for control/lease ops — only the object-key op families
        (tenancy.PS_SCOPED_OP_CODES) carry tenant-scoped names."""
        if self.tenant == tenancy.DEFAULT_TENANT:
            return name
        if op in tenancy.PS_SCOPED_OP_CODES:
            return tenancy.qualify(self.tenant, name)
        return name

    def _register_ensure(self, op: int, name: str, a: int, b: int) -> None:
        self._ensures.append((op, name, a, b))

    def ensure_object(self, op: int, name: str, a: int = 0, b: int = 0) -> int:
        """Issue a get-or-create op AND remember it, so a reincarnated
        server (restart lost every object) gets them re-created on
        reconnect.  Returns the status.  Only a SUCCESSFUL create is
        remembered — a rejected one (type/name clash) must not poison the
        reincarnation replay for the client's healthy objects.  The ensure
        list records the tenant-QUALIFIED name: the reincarnation replay
        goes through _attempt (below call()'s qualification point), so the
        stored name must already be the wire-level key."""
        status, _ = self.call(op, name, a, b)
        if status >= 0:
            self._register_ensure(op, self._qual(op, name), a, b)
        return status

    def on_reincarnation(self, fn) -> None:
        """Register a callback run (after object re-creation) whenever a
        reconnect lands on a NEW server incarnation — the chief re-seeds
        volatile state here (republish params, reset step, re-push
        tokens).  Callbacks may use this client; their ops run
        single-attempt (no nested recovery)."""
        self._callbacks.append(fn)

    def on_reconnect(self, fn) -> None:
        """Register a callback run on EVERY successful reconnect (same or
        new incarnation, before any reincarnation handling) — cache
        invalidation hooks: anything a client mirrors locally (e.g. the
        param-pull cache) must be re-validated against the server after a
        transport gap.  Must be cheap and must not issue remote ops."""
        self._reconnect_callbacks.append(fn)

    def _recover(self, t_end: float) -> None:
        """Reconnect with exponential backoff until ``t_end``; on success,
        detect state loss (token/incarnation) and rebuild only as the LAST
        resort.  With replicas configured (r12), attempts ALTERNATE the
        replica addresses — a dead primary fails over to its backup within
        one retry, with zero chief involvement when the backup's token
        proves the state intact."""
        attempt = 0
        lost: set[int] = set()
        lost_retries = 0
        immediate = False
        while True:
            if attempt and not immediate:
                # first attempt is immediate — the common drop is transient
                # with a healthy server; JITTERED backoff paces retries so
                # N clients recovering from one blip spread their
                # re-arrival instead of re-dialing in lockstep (r18).
                delay = retry.jittered(self._backoff, attempt - 1, cap_s=2.0)
                time.sleep(min(delay, max(0.0, t_end - time.monotonic())))
            immediate = False
            if time.monotonic() >= t_end:
                faults.log_event(
                    "reconnect_gave_up", role=self.role, host=self._host,
                    port=self._port, attempts=attempt,
                )
                # Budget exhausted = fatal for this client's caller: dump
                # the flight recorder so the outage window is attributable.
                telemetry.dump_flight_recorder("reconnect_gave_up")
                raise PSDeadlineError(
                    f"PS at {self._host}:{self._port} unreachable for "
                    f"{self._reconnect_deadline:.0f}s ({attempt} attempts)"
                )
            attempt += 1
            # Per-address circuit breaker (r18, process-wide): an address
            # that just failed ``threshold`` consecutive dials is OPEN —
            # skip the dial (fail over to the other replica, which has
            # its own breaker, or wait out part of the window) instead of
            # burning another connect timeout against a dead peer.
            breaker = retry.breaker_for((self._host, self._port))
            if not breaker.allow():
                if len(self._addrs) > 1:
                    self._switch_replica((self._cur + 1) % len(self._addrs))
                else:
                    breaker.wait_for_probe(t_end)
                    immediate = True  # the wait was this attempt's pacing
                continue
            try:
                self._connect()
            except OSError:
                breaker.on_failure()
                if len(self._addrs) > 1:
                    self._switch_replica((self._cur + 1) % len(self._addrs))
                continue
            breaker.on_success()
            try:
                # After several rounds stuck on state-lost replicas (the
                # OTHER replica stayed unreachable throughout), stop
                # waiting for a survivor that isn't coming and rebuild on
                # what we have — the both-replicas-dead last resort.
                self._post_reconnect(
                    attempt, lost, force_rebuild=lost_retries >= 3
                )
                return
            except _StateLost:
                lost_retries += 1
                nxt = next(
                    i for i in range(len(self._addrs)) if i not in lost
                )
                self._switch_replica(nxt)
                immediate = True
                continue
            except (OSError, PSError):
                # PSError: a transport failure inside a reincarnation
                # callback (callbacks run single-attempt and wrap their
                # OSError) — same fault as a raw drop, same retry, same
                # deadline.
                self._sever()
                continue

    def _post_reconnect(
        self, attempts: int, lost: set[int] | None = None,
        force_rebuild: bool = False,
    ) -> None:
        deadline = self._op_timeout or 10.0
        inc, _ = self._attempt(_INCARNATION, deadline_s=deadline)
        token = None
        if len(self._addrs) > 1:  # token semantics are replicated-only
            tok, _ = self._attempt(_REPL_TOKEN, deadline_s=deadline)
            token = None if tok < 0 else tok  # -2 = pre-r12 server
        prev = self._incarnations.get(self._cur)
        changed = prev is not None and inc != prev
        self._incarnations[self._cur] = inc
        _OBS_RECONNECTS.inc()
        faults.log_event(
            "reconnected", role=self.role, attempts=attempts,
            incarnation_changed=changed, replica=self._cur,
        )
        for fn in list(self._reconnect_callbacks):
            fn()
        if token is not None and self._state_token is not None:
            if token == self._state_token:
                # The shard's state LINEAGE survived — on this replica
                # (transient drop, or a restart that REPL_SYNCed from the
                # survivor) or by failing over to its peer.  Nothing to
                # rebuild, nothing to reseed: the zero-stall path.
                if changed or self._cur != 0:
                    _OBS_FAILOVERS.inc()
                    faults.log_event(
                        "replica_state_intact", role=self.role,
                        replica=self._cur, incarnation_changed=changed,
                    )
                return
            if not force_rebuild and lost is not None:
                lost.add(self._cur)
                if len(lost) < len(self._addrs):
                    raise _StateLost()
        else:
            # Legacy (token-less) server, or first contact: incarnation
            # semantics, exactly the pre-r12 behavior.
            if not changed:
                if self._state_token is None:
                    self._state_token = token
                return
        # State lost on every replica (or a legacy server restarted):
        # re-create objects in creation order, then let the owner re-seed
        # volatile state — the chief-reseed last resort.
        self._in_recovery = True
        try:
            for op, name, a, b in list(self._ensures):
                status, _ = self._attempt(
                    op, name, a, b, deadline_s=self._op_timeout or 10.0
                )
                if status < 0:
                    raise ConnectionError(
                        f"object re-create op {op} {name!r} rejected ({status})"
                    )
            for fn in list(self._callbacks):
                fn()
        finally:
            self._in_recovery = False
        self._state_token = token
        _OBS_REBUILDS.inc()
        faults.log_event(
            "state_rebuilt", role=self.role, objects=len(self._ensures),
            callbacks=len(self._callbacks),
        )

    # -- ops ----------------------------------------------------------------

    def call(
        self, op: int, name: str = "", a: int = 0, b: int = 0,
        payload: np.ndarray | None = None, *, replay_safe: bool = True,
        server_wait_s: float = 0.0, fault_point: bool = True,
        out: np.ndarray | None = None, raw: bool = False,
        raw_payload: bool = False,
    ) -> tuple[int, np.ndarray]:
        """One request/response; recovers + replays on transport failure
        when recovery is enabled and the op is ``replay_safe`` (idempotent
        or dedup-tagged).  ``server_wait_s``: how long the server may
        legitimately block on this op — added to the op deadline so a
        bounded wait is never mistaken for a dead peer.  ``fault_point``:
        whether this call advances the fault-injection op counter — the
        chunked re-issues of one logical blocking op pass False so plan
        indices count LOGICAL ops, not timing-dependent chunks.
        (Control-plane ops are additionally skipped INSIDE the injector,
        from wire.CONTROL_OPS via faults.control_op_codes — no call site
        restates that set.)  ``out``:
        optional preallocated response destination (see ``_attempt``).
        ``raw_payload``: the payload is an UN-encoded byte blob already
        framed as 4-byte units (the RESHARD_BEGIN record shape) — sent
        verbatim, never dtype-converted, so a bf16 connection ships the
        same bytes as an f32 one."""
        # Tenant qualification (r20): the ONE place a PS object key gets
        # its ``t.<tenant>.`` prefix — every helper object (accumulator,
        # queues, param store) passes bare names through here.
        name = self._qual(op, name)
        # Encode once, outside the retry loop: a replay re-sends the same
        # wire bytes without re-converting (bf16) or re-checking layout.
        wire_payload = (
            payload if raw_payload else self._encode_payload(payload)
        )
        deadline = (
            self._op_timeout + server_wait_s
            if self._op_timeout is not None
            else None
        )
        with self._lock:
            if (
                fault_point
                and self._injector is not None
                and self._injector.before_op(op)
            ):
                self._sever()  # injected drop_conn: fail this op's transport
            t_end = None
            shed = retry.ShedRetry(self._budget, self._op_timeout)
            while True:
                if self._sock is not None:
                    try:
                        status, data = self._attempt(
                            op, name, a, b, wire_payload, deadline_s=deadline,
                            out=out, raw=raw,
                        )
                    except OSError as e:
                        if self._in_recovery or self._reconnect_deadline <= 0:
                            raise PSError(f"PS op {op} failed: {e!r}") from e
                        if not replay_safe:
                            raise PSError(
                                f"PS op {op} not replay-safe; connection lost "
                                f"mid-op: {e!r}"
                            ) from e
                        _OBS_CONN_LOST.inc()
                        faults.log_event(
                            "conn_lost", role=self.role, op_code=op,
                            error=type(e).__name__,
                        )
                    else:
                        hint = wire.retry_after_ms(status)
                        if hint is None:
                            # Every success funds future retries (the
                            # token-bucket budget, r18).
                            self._budget.on_success()
                            return status, data
                        # The server SHED this request (RETRY_LATER,
                        # r18 admission control): retry with jittered
                        # backoff THROUGH the budget, bounded by the op
                        # deadline — never at line rate
                        # (retry.ShedRetry, the one spelling).
                        if not shed.backoff(hint):
                            raise PSDeadlineError(
                                f"PS at {self._host}:{self._port} kept "
                                f"shedding op {op} (RETRY_LATER) past the "
                                "op deadline / retry budget — the server "
                                "is overloaded; back off and retry later"
                            )
                        continue
                elif self._in_recovery or self._reconnect_deadline <= 0:
                    raise PSError(f"PS op {op} failed: not connected")
                if t_end is None:
                    t_end = time.monotonic() + self._reconnect_deadline
                # A transport replay is a RETRY: it spends the shared
                # budget, so a storm of failing ops cannot re-dial and
                # replay unboundedly (budget exhaustion = the typed
                # deadline error, with the flight-recorder event the
                # budget logs).
                if not self._budget.try_spend():
                    raise PSDeadlineError(
                        f"PS at {self._host}:{self._port} retry budget "
                        f"exhausted replaying op {op} — refusing to feed "
                        "the retry storm"
                    )
                self._recover(t_end)

    def block_wait_s(self, t_end: float | None = None) -> float:
        """Server-side wait for the next blocking-op round trip: chunked
        (``block_chunk_s``) when this client has a deadline or recovery to
        honor, else 0 (= block forever, the pre-r6 wire behavior)."""
        chunk = (
            self.block_chunk_s
            if (self._op_timeout is not None or self._reconnect_deadline > 0)
            else 0.0
        )
        if t_end is None:
            return chunk
        remaining = max(0.05, t_end - time.monotonic())
        return min(chunk, remaining) if chunk else remaining

    def timed_blocking(
        self, op: int, name: str, make_ab, timeout_s: float | None = None
    ):
        """One LOGICAL blocking op issued as bounded server-side waits that
        are re-issued on expiry (-3) until data, cancellation, or
        ``timeout_s``.  ``make_ab(wait_ms) -> (a, b)`` builds the operands
        for each chunk.  Returns ``(status, payload)``, or ``(TIMED_OUT,
        None)`` when the caller deadline expires.  Only the first chunk is
        a fault-injection point — plan op indices count logical ops."""
        t_end = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        first = True
        while True:
            wait_s = self.block_wait_s(t_end)
            a, b = make_ab(int(wait_s * 1000))
            status, out = self.call(
                op, name, a, b, server_wait_s=wait_s, fault_point=first
            )
            first = False
            if status == -3:
                if t_end is not None and time.monotonic() >= t_end:
                    return TIMED_OUT, None
                continue
            return status, out

    def fail_fast(self) -> None:
        """Disable reconnect/recovery for all subsequent ops on this
        client.  Teardown-time best-effort signals (e.g. the chief's
        ``ps_shutdown`` push) must not spend the reconnect budget on a
        peer that may already be gone."""
        self._reconnect_deadline = 0.0

    def ping(self) -> None:
        status, _ = self.call(_PING)
        if status != 0:
            raise RuntimeError("PS server ping failed")

    def incarnation(self) -> int:
        status, _ = self.call(_INCARNATION)
        return status

    def stats(self) -> dict:
        """The server's whole counter table (r13 STATS): identity,
        incarnation/state token, request/connection counts, replication
        forward/sync/mirror counters and summed dedup/dropped counters —
        one JSON object per scrape, dtype-independent (the blob is raw
        bytes in 4-byte units, space-padded).  A pre-r13 server answers
        -2: surfaced as a loud PSError, never decoded as garbage."""
        status, blob = self.call(_STATS, raw=True)
        if status < 0 or not blob:
            raise PSError(
                f"PS at {self._host}:{self._port} does not answer STATS "
                f"(status {status}; pre-r13 server?)"
            )
        return json.loads(bytes(blob).decode())

    # -- membership leases (r14) --------------------------------------------

    def lease_acquire(self, name: str, ttl_s: float) -> int:
        """Acquire-or-renew the lease ``name`` (an opaque member string —
        see ``parallel.membership.pack_member``) for ``ttl_s`` seconds.
        Returns 1 when NEWLY acquired — a fresh member, or a re-acquire
        after the previous lease EXPIRED (the lapse signal a heartbeat
        watches for) — or 2 on a renewal of a live lease.  Replay-safe:
        a replayed acquire just renews again.  A pre-r14 server answers
        -2, surfaced as PSError so callers can degrade loudly."""
        status, _ = self.call(_LEASE_ACQUIRE, name, int(ttl_s * 1000))
        if status < 0:
            raise PSError(
                f"lease acquire {name!r} rejected ({status}); pre-r14 "
                "server, or a malformed member string"
            )
        return status

    def lease_release(self, name: str) -> bool:
        """Clean departure: drop the lease NOW instead of waiting out the
        TTL.  Idempotent; True when a live lease was released."""
        status, _ = self.call(_LEASE_RELEASE, name)
        if status < 0:
            raise PSError(f"lease release {name!r} rejected ({status})")
        return status == 1

    def lease_list(self) -> dict:
        """The coordinator's live-member registry: ``{"leases": [{"m":
        <member string>, "ttl_ms": ..., "age_ms": ..., "renewals": ...}],
        "expired_total": N}`` — expired entries already pruned (and
        counted) server-side.  Raw JSON blob like :meth:`stats`."""
        status, blob = self.call(_LEASE_LIST, raw=True)
        if status < 0 or not blob:
            raise PSError(
                f"PS at {self._host}:{self._port} does not answer "
                f"LEASE_LIST (status {status}; pre-r14 server?)"
            )
        return json.loads(bytes(blob).decode())

    # -- live resharding (r15) ----------------------------------------------

    def reshard_announce(self, version: int, blob: bytes) -> None:
        """Store ``blob`` as the coordinator's PENDING reshard record at
        epoch ``version`` (``parallel/reshard.py`` owns the schema).
        Idempotent — every joining shard task may announce the same
        record; refused for a version not above the committed one."""
        padded = blob + b" " * (-len(blob) % 4)
        status, _ = self.call(
            _RESHARD_BEGIN, "", version, raw_payload=True,
            payload=np.frombuffer(padded, np.uint8).view(np.float32),
        )
        if status < 0:
            raise PSError(
                f"reshard announce v{version} rejected ({status}): version "
                "not above the committed epoch, record oversized, or "
                "pre-r15 server"
            )

    def reshard_commit(self, version: int) -> None:
        """Promote the matching PENDING record to COMMITTED — the epoch
        flip every polling client converges to.  Idempotent when already
        committed at ``version``."""
        status, _ = self.call(_RESHARD_COMMIT, "", version)
        if status < 0:
            raise PSError(
                f"reshard commit v{version} rejected ({status}): no "
                "matching pending record (aborted, superseded, or pre-r15 "
                "server)"
            )

    def reshard_abort(self, version: int) -> bool:
        """Clear a matching PENDING record (the loud mid-transition
        bail-out); True when one was cleared."""
        status, _ = self.call(_RESHARD_ABORT, "", version)
        if status < 0:
            raise PSError(f"reshard abort v{version} rejected ({status})")
        return status == 1

    def reshard_poll(
        self, have_version: int = 0, *, pending: bool = False,
    ) -> tuple[int, bytes]:
        """The coordinator's reshard record: ``(version, blob)`` where the
        blob is non-empty only when ``version > have_version`` — the
        steady-state epoch poll is O(header), like an unchanged-step
        pull.  ``version`` 0 = no record.  A pre-r15 server answers -2,
        surfaced as ``(0, b"")`` so pollers degrade to the static
        topology silently (resharding simply never fires)."""
        status, blob = self.call(
            _RESHARD_GET, "", have_version, 1 if pending else 0, raw=True,
        )
        if status < 0:
            return 0, b""
        return status, bytes(blob).rstrip(b" ") if blob else b""

    def cancel_all(self) -> None:
        """Cancel blocked waiters on THIS client's tenant namespace: the
        request name is a key-prefix filter (r20) — empty for the default
        tenant (the whole space, the documented pre-tenant behavior), the
        ``t.<tenant>.`` prefix otherwise, so one tenant's teardown/reseed
        can never wake-and-fail another tenant's waiters."""
        self.call(_CANCEL_ALL, tenancy.tenant_prefix(self.tenant))


def _check(status: int, what: str) -> int:
    if status == -2:
        raise RuntimeError(f"PS server rejected {what} (bad object/request)")
    return status


# Wire packing of the (worker, seq) dedup tag — one definition, shared with
# the in-process ctypes wrappers (ps_server.cc layout, 15-bit worker).
_pack_tag = native._tag




class RemoteAccumulator:
    """API-compatible with native.GradientAccumulator, over the socket.

    On a client with a ``worker_tag``, applies are dedup-tagged: each
    logical apply gets the next per-object sequence number, retries of it
    replay the SAME number, and the server drops anything it has already
    processed — zero duplicate applications across reconnects."""

    def __init__(self, client: PSClient, name: str, num_elems: int):
        self._c, self._name, self._n = client, name, num_elems
        self._seq = 0
        _check(client.ensure_object(_ACC_GET, name, num_elems), "acc_get")
        if client.worker_tag is not None:
            # Announce this (possibly restarted) worker: the server forgets
            # the dead incarnation's sequences so our fresh 0-based stream
            # is not answered "duplicate".  Idempotent, replay-safe.
            _check(
                client.call(_ACC_RESET_WORKER, name, client.worker_tag)[0],
                "acc_reset_worker",
            )

    def apply(self, local_step: int, grad: np.ndarray) -> bool:
        if self._c.worker_tag is None:
            s, _ = self._c.call(
                _ACC_APPLY, self._name, local_step, payload=grad,
                replay_safe=False,
            )
            return _check(s, "acc_apply") == 1
        self._seq += 1
        s, _ = self._c.call(
            _ACC_APPLY_TAGGED, self._name, local_step,
            _pack_tag(self._c.worker_tag, self._seq), payload=grad,
        )
        # 1 = freshly accepted; 0 = stale-dropped; 2 = duplicate replay —
        # the first delivery's outcome (accepted OR dropped) is unknown, so
        # report False ("did not newly count"), matching
        # native.GradientAccumulator.apply_tagged.
        return _check(s, "acc_apply_tagged") == 1

    def take(self, num_required: int, timeout_s: float | None = None):
        """Blocking average; None when cancelled, ``TIMED_OUT`` when
        ``timeout_s`` expires.  Issued as bounded server-side waits so a
        dead PS surfaces between chunks and the reconnect path heals it."""
        s, out = self._c.timed_blocking(
            _ACC_TAKE, self._name, lambda w: (num_required, w), timeout_s
        )
        if s is TIMED_OUT:
            return TIMED_OUT
        return out if _check(s, "acc_take") >= 0 else None

    def set_global_step(self, step: int) -> None:
        _check(self._c.call(_ACC_SET_STEP, self._name, step)[0], "acc_set_step")

    @property
    def dropped(self) -> int:
        return _check(self._c.call(_ACC_DROPPED, self._name)[0], "acc_dropped")

    @property
    def deduped(self) -> int:
        return _check(self._c.call(_ACC_DEDUPED, self._name)[0], "acc_deduped")

    def cancel(self) -> None:
        self._c.cancel_all()


class RemoteTokenQueue:
    """API-compatible with native.TokenQueue."""

    def __init__(self, client: PSClient, name: str):
        self._c, self._name = client, name
        _check(client.ensure_object(_TQ_GET, name), "tq_get")

    def push(self, step: int, n: int = 1) -> None:
        _check(self._c.call(_TQ_PUSH, self._name, step, n)[0], "tq_push")

    def pop(self, timeout_s: float | None = None):
        """Blocking; token step, None when cancelled, ``TIMED_OUT`` when
        ``timeout_s`` expires first."""
        s, _ = self._c.timed_blocking(
            _TQ_POP, self._name, lambda w: (w, 0), timeout_s
        )
        if s is TIMED_OUT:
            return TIMED_OUT
        return s if s >= 0 else None

    def cancel(self) -> None:
        self._c.cancel_all()


class RemoteGradientQueue:
    """API-compatible with native.GradientQueue (tagged pushes on clients
    with a ``worker_tag`` — see RemoteAccumulator)."""

    def __init__(self, client: PSClient, name: str, num_elems: int, capacity: int = 16):
        self._c, self._name, self._n = client, name, num_elems
        self._seq = 0
        _check(client.ensure_object(_GQ_GET, name, num_elems, capacity), "gq_get")
        if client.worker_tag is not None:
            # See RemoteAccumulator: restarted-worker announcement.
            _check(
                client.call(_GQ_RESET_WORKER, name, client.worker_tag)[0],
                "gq_reset_worker",
            )

    def push(self, local_step: int, grad: np.ndarray) -> bool | None:
        """Tri-state like native.GradientQueue.push: True enqueued, False
        stale-dropped, None cancelled (termination signal)."""
        if self._c.worker_tag is None:
            s, _ = self._c.call(
                _GQ_PUSH, self._name, local_step, payload=grad,
                replay_safe=False,
            )
            return None if _check(s, "gq_push") < 0 else s == 1
        self._seq += 1
        tag = _pack_tag(self._c.worker_tag, self._seq)
        # Backpressure on a full queue becomes a dedup-safe ~2 s poll (the
        # server bounds its own space wait and answers -3).  Each re-issue
        # re-sends the payload, so the poll period is deliberately coarse;
        # the overall stall is bounded — a chief wedged this long is a job
        # failure, not backpressure.
        t_end = time.monotonic() + _PUSH_STALL_LIMIT_S
        first = True
        while True:
            s, _ = self._c.call(
                _GQ_PUSH_TAGGED, self._name, local_step, tag, payload=grad,
                server_wait_s=2.5, fault_point=first,
            )
            first = False
            if s == -3:
                if time.monotonic() >= t_end:
                    raise PSDeadlineError(
                        f"gradient queue {self._name!r} full for "
                        f"{_PUSH_STALL_LIMIT_S:.0f}s (chief stalled?)"
                    )
                continue
            _check(s, "gq_push_tagged")
            # 1 enqueued / 2 duplicate-of-enqueued -> True; 0 stale -> False.
            return None if s < 0 else s != 0

    def pop(self, timeout_s: float | None = None):
        """Blocking; (local_step, grad), None when cancelled+drained, or
        ``TIMED_OUT`` when ``timeout_s`` expires first."""
        s, out = self._c.timed_blocking(
            _GQ_POP, self._name, lambda w: (self._n, w), timeout_s
        )
        if s is TIMED_OUT:
            return TIMED_OUT
        return (s, out) if s >= 0 else None

    def set_min_step(self, step: int) -> None:
        _check(self._c.call(_GQ_SET_MIN, self._name, step)[0], "gq_set_min")

    @property
    def dropped(self) -> int:
        return _check(self._c.call(_GQ_DROPPED, self._name)[0], "gq_dropped")

    @property
    def deduped(self) -> int:
        return _check(self._c.call(_GQ_DEDUPED, self._name)[0], "gq_deduped")

    def cancel(self) -> None:
        self._c.cancel_all()


class RemoteParamStore:
    """Published (step, flat params) snapshot — the PS variable-hosting
    role; chief sets after every applied update, workers get before every
    gradient computation (SURVEY.md section 3.1 hot path).

    Versioned pulls (r7): ``get`` keeps a client-side (step, params) cache
    and issues ``PSTORE_GET_IF_NEWER`` with the cached step — when the
    published step hasn't advanced the server answers status-only (~12
    bytes) and the cached array is returned, so an unchanged-step pull
    costs O(header), not O(params).  The cache is invalidated on every
    reconnect (transport gap => local mirror unproven) and a reincarnated
    server re-fills it on the next pull.  Callers must treat the returned
    array as READ-ONLY: repeated unchanged-step gets share one buffer.
    ``cache_pulls=False`` restores the always-full-fetch behavior."""

    def __init__(
        self, client: PSClient, name: str, num_elems: int, *,
        cache_pulls: bool = True,
    ):
        self._c, self._name, self._n = client, name, num_elems
        self._cache_step = -1
        self._cache: np.ndarray | None = None
        self._cache_enabled = cache_pulls
        _check(client.ensure_object(_PSTORE_GET_OBJ, name, num_elems), "pstore_get_obj")
        if cache_pulls:
            client.on_reconnect(self.invalidate_cache)

    def invalidate_cache(self) -> None:
        self._cache_step, self._cache = -1, None

    def set(self, step: int, flat: np.ndarray) -> None:
        # Replay-safe: single-writer (the chief), so a replayed set can
        # never be reordered against a newer one on the same connection.
        _check(self._c.call(_PSTORE_SET, self._name, step, payload=flat)[0],
               "pstore_set")

    def _get_full(self) -> tuple[int, np.ndarray]:
        s, out = self._c.call(_PSTORE_GET, self._name)
        return _check(s, "pstore_get"), out

    def get(self) -> tuple[int, np.ndarray]:
        if not self._cache_enabled:
            return self._get_full()
        # Empty cache pulls with have_step=-1: a published store answers
        # with the full payload (same as a full get), an UNPUBLISHED one
        # answers status-only — so the poll loop waiting out a PS-restart
        # recovery window costs O(header) per probe, not a full zero-vector
        # ship per 50 ms from every worker connection.
        have = self._cache_step if self._cache is not None else -1
        s, out = self._c.call(_PSTORE_GET_IF_NEWER, self._name, have)
        if s == -2:
            # Pre-v2 server (op unknown): fall back to full pulls for the
            # life of this store rather than failing the caller.
            self._cache_enabled = False
            return self._get_full()
        _check(s, "pstore_get_if_newer")
        if out.size == 0:
            # The reconnect hook may have cleared the cache while this
            # very call was being replayed (_cache_step is then -1,
            # matching an empty store's step) — only a LIVE cache
            # satisfies the unchanged-step fast path.
            if s == self._cache_step and self._cache is not None:
                _OBS_PULL_HITS.inc()
                return s, self._cache
            if s < 0:
                # Never published: status-only, payload deliberately empty
                # (callers gate on step < 0 before touching the array).
                return s, out
            # Step moved without a payload (republished at a lower step,
            # e.g. a reseed the reconnect hook didn't see): distrust the
            # mirror and refetch in full.
            self.invalidate_cache()
            s, out = self._get_full()
        if s >= 0 and out.size:
            self._cache_step, self._cache = s, out
        return s, out
