"""Client for the cross-process PS service (native/ps_server.cc).

The thread-mode async-PS emulation (parallel/async_ps.py) talks to the
native accumulator/token/gradient-queue structs through direct ctypes calls;
this module provides the SAME object APIs over a localhost TCP socket, so
the W1/W2 emulations run across real processes — the reference's PS/worker
process topology (SURVEY.md sections 3.1/3.2), with the chief process
hosting the service (the PS task role) and each worker process connecting.

One socket per client; requests are serialized on it (a worker's op
sequence is sequential anyway, and blocking ops — token pop, accumulator
take, gradient pop — tie up only that client's server-side thread).
"""

from __future__ import annotations

import socket
import struct
import threading

import numpy as np

from .. import native

# Op codes (must match native/ps_server.cc).
_ACC_GET, _ACC_APPLY, _ACC_TAKE, _ACC_SET_STEP, _ACC_DROPPED = 1, 2, 3, 4, 5
_TQ_GET, _TQ_PUSH, _TQ_POP = 6, 7, 8
_GQ_GET, _GQ_PUSH, _GQ_POP, _GQ_SET_MIN, _GQ_DROPPED = 9, 10, 11, 12, 13
_CANCEL_ALL, _PING = 14, 15
_PSTORE_GET_OBJ, _PSTORE_SET, _PSTORE_GET = 16, 17, 18


def start_server(port: int = 0, *, loopback_only: bool = True) -> int:
    """Start the in-process C++ PS server; returns the bound port.

    ``loopback_only=False`` binds all interfaces — required when workers on
    OTHER hosts dial this PS task (the protocol is unauthenticated, so only
    do this on a trusted cluster network, as with the reference's gRPC)."""
    lib = native._load()
    import ctypes

    lib.ps_server_start.restype = ctypes.c_int
    lib.ps_server_start.argtypes = [ctypes.c_int, ctypes.c_int]
    p = lib.ps_server_start(port, 1 if loopback_only else 0)
    if p < 0:
        raise RuntimeError("ps_server_start failed")
    return p


def stop_server() -> None:
    lib = native._load()
    lib.ps_server_stop()


class PSClient:
    """One TCP connection to the PS server; thread-safe via a lock."""

    def __init__(self, host: str, port: int, *, timeout_s: float | None = None):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _recv_n(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("PS server closed the connection")
            buf += chunk
        return buf

    def call(
        self, op: int, name: str = "", a: int = 0, b: int = 0,
        payload: np.ndarray | None = None,
    ) -> tuple[int, np.ndarray]:
        nm = name.encode()
        pl = (
            np.ascontiguousarray(payload, np.float32).tobytes()
            if payload is not None
            else b""
        )
        req = (
            struct.pack("<BB", op, len(nm)) + nm
            + struct.pack("<qqI", a, b, len(pl) // 4) + pl
        )
        with self._lock:
            self._sock.sendall(req)
            status, plen = struct.unpack("<qI", self._recv_n(12))
            out = (
                np.frombuffer(self._recv_n(plen * 4), np.float32).copy()
                if plen
                else np.empty((0,), np.float32)
            )
        return status, out

    def ping(self) -> None:
        status, _ = self.call(_PING)
        if status != 0:
            raise RuntimeError("PS server ping failed")

    def cancel_all(self) -> None:
        self.call(_CANCEL_ALL)


def _check(status: int, what: str) -> int:
    if status == -2:
        raise RuntimeError(f"PS server rejected {what} (bad object/request)")
    return status


class RemoteAccumulator:
    """API-compatible with native.GradientAccumulator, over the socket."""

    def __init__(self, client: PSClient, name: str, num_elems: int):
        self._c, self._name, self._n = client, name, num_elems
        _check(client.call(_ACC_GET, name, num_elems)[0], "acc_get")

    def apply(self, local_step: int, grad: np.ndarray) -> bool:
        s, _ = self._c.call(_ACC_APPLY, self._name, local_step, payload=grad)
        return _check(s, "acc_apply") == 1

    def take(self, num_required: int) -> np.ndarray | None:
        s, out = self._c.call(_ACC_TAKE, self._name, num_required)
        return out if _check(s, "acc_take") >= 0 else None

    def set_global_step(self, step: int) -> None:
        _check(self._c.call(_ACC_SET_STEP, self._name, step)[0], "acc_set_step")

    @property
    def dropped(self) -> int:
        return _check(self._c.call(_ACC_DROPPED, self._name)[0], "acc_dropped")

    def cancel(self) -> None:
        self._c.cancel_all()


class RemoteTokenQueue:
    """API-compatible with native.TokenQueue."""

    def __init__(self, client: PSClient, name: str):
        self._c, self._name = client, name
        _check(client.call(_TQ_GET, name)[0], "tq_get")

    def push(self, step: int, n: int = 1) -> None:
        _check(self._c.call(_TQ_PUSH, self._name, step, n)[0], "tq_push")

    def pop(self) -> int | None:
        s, _ = self._c.call(_TQ_POP, self._name)
        return s if s >= 0 else None

    def cancel(self) -> None:
        self._c.cancel_all()


class RemoteGradientQueue:
    """API-compatible with native.GradientQueue."""

    def __init__(self, client: PSClient, name: str, num_elems: int, capacity: int = 16):
        self._c, self._name, self._n = client, name, num_elems
        _check(client.call(_GQ_GET, name, num_elems, capacity)[0], "gq_get")

    def push(self, local_step: int, grad: np.ndarray) -> bool | None:
        """Tri-state like native.GradientQueue.push: True enqueued, False
        stale-dropped, None cancelled (termination signal)."""
        s, _ = self._c.call(_GQ_PUSH, self._name, local_step, payload=grad)
        return None if _check(s, "gq_push") < 0 else s == 1

    def pop(self) -> tuple[int, np.ndarray] | None:
        s, out = self._c.call(_GQ_POP, self._name, self._n)
        return (s, out) if s >= 0 else None

    def set_min_step(self, step: int) -> None:
        _check(self._c.call(_GQ_SET_MIN, self._name, step)[0], "gq_set_min")

    @property
    def dropped(self) -> int:
        return _check(self._c.call(_GQ_DROPPED, self._name)[0], "gq_dropped")

    def cancel(self) -> None:
        self._c.cancel_all()


class RemoteParamStore:
    """Published (step, flat params) snapshot — the PS variable-hosting
    role; chief sets after every applied update, workers get before every
    gradient computation (SURVEY.md section 3.1 hot path)."""

    def __init__(self, client: PSClient, name: str, num_elems: int):
        self._c, self._name, self._n = client, name, num_elems
        _check(client.call(_PSTORE_GET_OBJ, name, num_elems)[0], "pstore_get_obj")

    def set(self, step: int, flat: np.ndarray) -> None:
        _check(self._c.call(_PSTORE_SET, self._name, step, payload=flat)[0],
               "pstore_set")

    def get(self) -> tuple[int, np.ndarray]:
        s, out = self._c.call(_PSTORE_GET, self._name)
        return _check(s, "pstore_get"), out
