"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe schedule).

No reference analog (the reference's five workloads are data-parallel /
PS-sharded only — SURVEY.md §2b strategy table lists PP as "out of scope for
parity; note only"); this module exists because a complete TPU framework
must scale depth-wise past one chip's HBM, and because pipeline parallelism
composes with the other axes this framework already serves (data/model/seq).

TPU-first design:

- The layer stack is STACKED: per-layer pytrees become one pytree whose
  leaves carry a leading layer dim, sharded ``P('pipe')`` — each pipe rank
  physically holds only its own stage's weights in HBM (the depth analog of
  PS variable sharding).
- The schedule is a ``lax.scan`` over ``M + S - 1`` ticks inside a
  PARTIAL-MANUAL ``jax.shard_map``: manual over ``pipe`` only
  (``axis_names={'pipe'}``) — stage handoff is an explicit ``ppermute``
  ring over ICI — while ``data``/``seq``/``model`` stay AUTO axes, so the
  stage body remains ordinary jnp code that GSPMD shards for dp/sp/tp.
  This is the idiomatic JAX composition: hand-schedule exactly the axis
  whose dataflow XLA cannot infer (the pipeline), delegate the rest.
- Microbatching: the batch splits into ``M`` microbatches; bubble fraction
  is ``(S-1)/(M+S-1)`` (GPipe).  The backward schedule is jax.grad applied
  to the scan — reverse ticks with reversed ``ppermute``s, no hand-written
  backward.
- Each stage body is wrapped in ``jax.checkpoint``: activations are
  rematerialised in the backward pipeline instead of being saved per tick
  (the standard GPipe memory trade).

Caveat (documented, enforced): a Pallas custom call cannot live on an AUTO
axis inside a partial-manual shard_map, so blocks inside the pipeline use
XLA attention (``ops.attention.mha``) rather than the flash kernel.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from . import collectives
from .mesh import AXIS_PIPE


def stack_stages(per_stage: list[Any]) -> Any:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def unstack_stages(stacked: Any, n: int) -> list[Any]:
    return [jax.tree.map(lambda x: x[i], stacked) for i in range(n)]


def pipeline_apply(
    mesh: Mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    h: jax.Array,
    *,
    microbatches: int,
    axis: str = AXIS_PIPE,
    remat: bool = True,
):
    """Run ``h`` through ``S`` pipeline stages; returns the final activations.

    ``stacked_params``: pytree whose leaves carry a leading LAYER dim L
    (L % S == 0), sharded ``P(axis)`` on that dim — each pipe rank holds
    L/S consecutive layers.  ``stage_fn(rank_params, x) -> x`` is one
    stage's forward; ``rank_params`` keeps the leading dim (length L/S),
    so the stage body typically ``lax.scan``s over its local layers.
    ``h``: [B, ...] activations; B must divide by ``microbatches``.

    Differentiable end-to-end; the output is replicated over ``axis`` (last
    rank's results are broadcast by a masked psum — one [B, ...] all-reduce
    over the pipe axis per call).
    """
    S = mesh.shape.get(axis, 1)
    if S == 1:
        # No pipe axis: the whole stack is one "stage".
        return stage_fn(stacked_params, h)

    M = microbatches
    B = h.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by microbatches={M}")
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    # The shard_map BOUNDARY is f32 on both sides: a replicated (P())
    # input's transpose inserts a psum of the cotangent over the manual
    # axis, and a bf16 psum on a partial-manual axis crashes XLA CPU
    # ("Invalid binary instruction opcode copy").  Casting at the boundary
    # keeps every pipe-axis collective — fwd broadcast and bwd input
    # cotangent — in f32; stage compute stays in the caller's dtype.
    dtype = h.dtype
    h_mb = h.reshape(M, B // M, *h.shape[1:]).astype(jnp.float32)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def pipelined(stage_params, h_mb):
        # stage_params: this rank's layer slice (leading dim L/S).
        r = lax.axis_index(axis)
        n_ticks = M + S - 1

        def tick(buf, t):
            # Rank 0 injects a fresh microbatch; everyone else consumes the
            # activation its predecessor pushed last tick.  Trailing ticks
            # re-inject the last microbatch on rank 0 — bubble compute whose
            # output is never collected (inherent GPipe bubble).
            inject = lax.dynamic_index_in_dim(
                h_mb, jnp.minimum(t, M - 1), axis=0, keepdims=False
            )
            x = jnp.where(r == 0, inject.astype(dtype), buf)
            out = stage_fn(stage_params, x)
            return lax.ppermute(out, axis, perm), out

        buf0 = jnp.zeros(h_mb.shape[1:], dtype)
        _, outs = lax.scan(tick, buf0, jnp.arange(n_ticks))
        # Valid results live on the LAST rank at ticks S-1 .. S-1+M-1.
        valid = lax.dynamic_slice_in_dim(outs, S - 1, M, axis=0)
        mask = (r == S - 1).astype(jnp.float32)
        return lax.psum(valid.astype(jnp.float32) * mask, axis)

    out_mb = collectives.shard_map(
        pipelined,
        mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        axis_names={axis},
        check_vma=False,
    )(stacked_params, h_mb)
    return out_mb.reshape(B, *h.shape[1:]).astype(dtype)


def stage_sharding_rules(inner_rules: tuple, prefix: str, axis: str = AXIS_PIPE) -> tuple:
    """Lift a per-layer rule table onto stacked params: every leaf gains a
    leading stage dim sharded over ``axis``; inner specs shift right.

    ``(r"qkv/kernel", P(None, "model"))`` ->
    ``(rf"{prefix}/qkv/kernel", P("pipe", None, "model"))``.
    """
    out = []
    for pat, spec in inner_rules:
        out.append((f"{prefix}/{pat}", P(axis, *spec)))
    # Default: any stacked leaf not matched above still shards its stage dim.
    out.append((f"{prefix}/.*", P(axis)))
    return tuple(out)
