"""Sharding rules: the TPU-native ``replica_device_setter``.

The reference places every variable on a parameter-server task via a
round-robin device function (``tf.train.replica_device_setter``; SURVEY.md
section 2b, D3) and splits big variables across PS tasks with partitioners
(D4).  Here placement is declarative: a rule table maps parameter *paths*
(``"dense_1/kernel"``) to ``PartitionSpec``s, and arrays are laid out in mesh
HBM with ``NamedSharding``.  The "PS role" disappears — a sharded parameter
lives distributed across the chips that compute with it, and XLA inserts the
gathers/reduce-scatters the gRPC rendezvous used to perform (SURVEY.md
section 3.5).
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

#: A rule table: ordered (path-regex, PartitionSpec) pairs.  First match wins;
#: no match means fully replicated — the analog of an un-partitioned mirrored
#: variable.
ShardingRules = Sequence[tuple[str, PartitionSpec]]

REPLICATED = P()


def path_of(key_path: tuple) -> str:
    """Render a jax tree key-path as ``"a/b/0"``."""
    parts = []
    for k in key_path:
        if isinstance(k, jax.tree_util.DictKey):
            parts.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            parts.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            parts.append(str(k.name))
        elif isinstance(k, jax.tree_util.FlattenedIndexKey):
            parts.append(str(k.key))
        else:
            parts.append(str(k))
    return "/".join(parts)


def spec_for_path(path: str, rules: ShardingRules) -> PartitionSpec:
    spec = match_rule(path, rules)
    return REPLICATED if spec is None else spec


def match_rule(path: str, rules: ShardingRules) -> PartitionSpec | None:
    """First matching rule's spec, or None when NO rule matches (callers that
    need to distinguish no-match from an explicit replicated rule)."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return None


def _clamp_spec(spec: PartitionSpec, ndim: int, shape, mesh: Mesh) -> PartitionSpec:
    """Drop trailing axes beyond ndim; drop shardings that don't divide the
    dimension (falls back to replication on that dim, mirroring how TF
    partitioners refuse to split a dim unevenly)."""
    entries = list(spec)[:ndim]
    out: list[Any] = []
    for dim, entry in enumerate(entries):
        if entry is None:
            out.append(None)
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        out.append(entry if shape[dim] % size == 0 else None)
    return P(*out)


def named_sharding(mesh: Mesh, *spec_entries) -> NamedSharding:
    return NamedSharding(mesh, P(*spec_entries))


def sharding_tree(
    tree: Any, mesh: Mesh, rules: ShardingRules, *, default_spec_fn=None
) -> Any:
    """Pytree of ``NamedSharding`` matching ``tree`` — usable as jit
    in/out shardings, checkpoint restore layouts, or device_put targets.

    ``default_spec_fn(path, leaf) -> PartitionSpec`` decides leaves NO rule
    matches (the auto-partitioner hook, D4); default replicated."""

    def _one(key_path, leaf):
        path = path_of(key_path)
        spec = match_rule(path, rules)
        if spec is None:
            spec = default_spec_fn(path, leaf) if default_spec_fn else REPLICATED
        shape = getattr(leaf, "shape", ())
        spec = _clamp_spec(spec, len(shape), shape, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(_one, tree)


def shard_pytree(tree: Any, mesh: Mesh, rules: ShardingRules = ()) -> Any:
    """Lay a pytree out in mesh HBM per the rule table (device_put)."""
    shardings = sharding_tree(tree, mesh, rules)
    return jax.device_put(tree, shardings)


def batch_sharding(mesh: Mesh, data_axes=("slice", "data")) -> NamedSharding:
    """Input-batch sharding: leading (batch) dim split over the data axes —
    the analog of ``Dataset.shard``/``DistributedDataset`` per-replica splits
    (SURVEY.md section 2b, D14).  The default includes the multi-slice
    'slice' axis (outermost, r4 ghost-BN meshes); absent or size-1 axes are
    filtered, so single-slice meshes are unchanged."""
    present = tuple(a for a in data_axes if a in mesh.shape and mesh.shape[a] > 1)
    if not present:
        return NamedSharding(mesh, P())
    entry = present[0] if len(present) == 1 else present
    return NamedSharding(mesh, P(entry))
