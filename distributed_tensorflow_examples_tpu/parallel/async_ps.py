"""Async / sync-replicas parameter-server EMULATION over SPMD workers.

The reference's W2 config is asynchronous SGD: each worker applies its
gradient to the PS-hosted variables immediately, with no aggregation and no
staleness gate (SURVEY.md section 3.2); its W1 config is the opposite pole,
``SyncReplicasOptimizer``: per-variable accumulators average
``replicas_to_aggregate`` gradients, drop stale ones, and a chief pushes
tokens that gate the workers (section 3.1, D5).

**Semantic divergence (documented per SURVEY.md section 7 step 6):** TPU SPMD
is synchronous by construction — there is no per-chip async apply.  This
module reproduces the reference's *coordination semantics* at the level of
"islands" (independent workers, each an SPMD program): variables are hosted
host-side (the PS role), workers compute gradients against possibly-stale
snapshots on device, and the native C++ accumulator/token-queue service
(``native/accumulator.cc`` — the conditional_accumulator.h / chief-queue
analog, D5/D12) coordinates applies.  Differences from the reference:

- Single-host emulation time-shares the chip between worker threads, so
  wall-clock interleaving differs from a real PS cluster; the *ordering and
  staleness semantics* (what makes async-SGD async) are faithful: each
  pushed gradient is popped and applied INDIVIDUALLY, in arrival order
  (native GradientQueue — the worker->PS Send/Recv role), never coalesced.
- Both modes move whole gradients atomically: sync aggregation uses one flat
  accumulator over the concatenated gradient instead of the reference's
  per-variable accumulators (numerically identical for equal counts, and it
  closes the torn-cross-variable-update race the per-variable scheme admits
  when replicas_to_aggregate < num_workers).
- ``max_staleness`` adds a bound the reference's async mode lacks (its sync
  mode's staleness drop is mirrored exactly).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Iterator

import numpy as np

import jax
import jax.numpy as jnp
import optax

from .. import native
from ..utils import faults, telemetry

log = logging.getLogger("dtx.async_ps")


@dataclasses.dataclass
class AsyncPSConfig:
    num_workers: int = 2
    mode: str = "async"  # "async" (W2) | "sync_replicas" (W1/D5 semantics)
    replicas_to_aggregate: int | None = None  # sync mode; default num_workers
    max_staleness: int | None = None  # async mode: drop grads older than this
    #: Async mode only: replace free-running worker threads with a
    #: deterministic round-robin schedule — every applied gradient was
    #: computed one schedule slot per peer earlier, so applies still happen
    #: at STALE params (true W2 semantics) but the interleaving (and hence
    #: the trajectory) is exactly reproducible.  The determinism analog of
    #: the reference harness's fixed-seed async tests; the CLI's
    #: ``--deterministic`` selects it (tests/test_examples_e2e.py W2 gate).
    #: Resume caveat (ADVICE r4): reproducibility is UNINTERRUPTED-run
    #: scoped — pending (in-flight) gradients are not checkpointed, so a
    #: preempted-and-resumed run recomputes them at the restored params and
    #: diverges bitwise from an uninterrupted run with the same seed.  Two
    #: runs agree bitwise iff they share the same checkpoint/restart
    #: schedule.
    fixed_interleave: bool = False
    train_steps: int = 100
    # Checkpoint/resume (SURVEY.md section 5.4: the reference's PS world
    # recovered async runs from Saver checkpoints; same contract here).
    ckpt_dir: str | None = None
    checkpoint_every: int = 50  # applied updates between saves
    #: Cross-process mode only — the PS client's fault posture (r6).
    #: Per-op deadline: blocking ops become bounded server-side waits the
    #: client re-issues, so a dead PS surfaces within ~one chunk instead of
    #: hanging forever.  None = pre-r6 unbounded ops.
    ps_op_timeout_s: float | None = 30.0
    #: How long a client keeps reconnecting (exponential backoff) before a
    #: PS outage becomes fatal (``PSDeadlineError`` -> the supervisor's
    #: whole-job crash-restart path).  Must comfortably cover one PS-task
    #: restart: supervise() backoff + process relaunch + import.  0 = the
    #: pre-r6 fail-fast client.
    ps_reconnect_deadline_s: float = 60.0
    #: Cross-process mode only — payload encoding on the PS wire (r7):
    #: "f32" (exact) or "bf16" (half the param/grad bytes; the server
    #: stores f32 and converts at the socket boundary).  bf16 pays a
    #: host-side conversion per transfer, so it wins on real networks where
    #: bytes are the bottleneck, not on loopback — see RUNBOOK "PS
    #: transport tuning" for when it is accuracy-safe.
    ps_wire_dtype: str = "f32"
    #: Cross-process ASYNC workers only — double-buffer param pulls on a
    #: dedicated background connection: the next step's pull runs under the
    #: current step's gradient compute, so an unchanged snapshot costs a
    #: header-sized round trip of latency and a fresh one streams while the
    #: chip is busy.  Adds at most one step of parameter staleness (the
    #: same +1 the fixed interleave schedules deliberately).  Sync mode
    #: never prefetches: a pre-token snapshot would be guaranteed-stale and
    #: the staleness gate would starve the worker.
    ps_prefetch: bool = True
    #: Cross-process mode only — membership leases (r14 elasticity): every
    #: async worker (and serve replica) heartbeats a lease on the
    #: coordinator shard, so the chief/data-service/dtxtop learn the LIVE
    #: worker set from the registry instead of static ``--worker_hosts``
    #: and a worker can join or leave mid-run with no restart of anything
    #: else.  Degrades loudly to the static posture against a pre-r14 PS.
    membership_leases: bool = True
    #: Lease TTL: a member whose heartbeats stop for this long is treated
    #: as departed (its splits reassigned, its lease pruned).  Renewals
    #: run at ttl/3.
    lease_ttl_s: float = 10.0
    #: Live resharding (r15): whether the chief ADOPTS a pending layout
    #: epoch announced on the coordinator (new shard tasks started with
    #: ``--ps_reshard_to``), and whether workers/clients follow committed
    #: epochs.  Off = the pre-r15 frozen-topology posture.
    reshard_watch: bool = True
    #: Epoch-poll cadence for every follower (chief pending-poll, worker
    #: committed-poll).  Each unchanged poll is one O(header) round trip.
    reshard_poll_s: float = 0.5
    #: How long the chief waits for every new-layout shard to present a
    #: synced snapshot before ABORTING the transition loudly (the
    #: never-half-applies guarantee: a joiner killed mid-transition fails
    #: this probe and the old topology serves on).
    reshard_ready_timeout_s: float = 60.0
    #: How long a retired old-layout task waits out its remaining client
    #: connections (drain) before exiting anyway.
    reshard_drain_s: float = 20.0
    #: Multi-tenancy (r20): the tenant this RUN belongs to.  Every PS
    #: object the run creates lives under the tenant's key namespace and
    #: every lease it registers is tenant-scoped, so several runs share
    #: one PS tier without their params, reshards, or membership views
    #: ever touching.  "default" = the untagged pre-r20 wire posture
    #: (byte-identical frames).
    tenant: str = "default"


class AsyncPSTrainer:
    """Host-hosted parameters ("PS role"), device-computed gradients, native
    accumulator/token coordination.

    ``loss_fn`` is the framework-standard callable; ``batch_fns`` is one
    local-batch iterator per worker (the per-worker data shard).
    """

    def __init__(
        self,
        cfg: AsyncPSConfig,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        init_params: Any,
        *,
        model_state: Any = None,
        rng: jax.Array | None = None,
    ):
        self.cfg = cfg
        self.optimizer = optimizer
        self.params = jax.tree.map(np.asarray, init_params)
        self.model_state = model_state if model_state is not None else {}
        self.opt_state = optimizer.init(init_params)
        self.rng = rng if rng is not None else jax.random.key(0)
        self.global_step = 0
        self._params_lock = threading.Lock()
        self._stop = threading.Event()
        self.history: list[tuple[int, int, float]] = []  # (worker, local_step, loss)
        #: Fixed-interleave only: (wid, computed_at, applied_at, dropped)
        #: per scheduled gradient — the apply-time staleness evidence.
        self.apply_log: list[tuple[int, int, int, bool]] = []
        self._history_lock = threading.Lock()
        self.total_dropped = 0
        #: Duplicate replays suppressed by the (worker, seq) dedup tables —
        #: stays 0 unless a connection drop forced a replay of an op the
        #: server had already processed (fault-recovery observability).
        self.total_deduped = 0
        self._worker_excs: list[tuple[int, BaseException]] = []

        leaves, self._treedef = jax.tree.flatten(self.params)
        self._leaf_shapes = [l.shape for l in leaves]
        self._leaf_sizes = [int(np.prod(s)) if s else 1 for s in self._leaf_shapes]

        self._gq = None
        self._accs: list = []
        if cfg.mode == "sync_replicas":
            # One FLAT accumulator: whole-gradient applies are atomic.
            self._accs = [native.GradientAccumulator(sum(self._leaf_sizes))]
        elif cfg.mode == "async":
            self._gq = native.GradientQueue(
                sum(self._leaf_sizes), capacity=max(4, 2 * cfg.num_workers)
            )
        else:
            raise ValueError(f"unknown mode {cfg.mode!r}")
        self._tq = native.TokenQueue()

        def _grad(params, model_state, batch, rng):
            (loss, (_, metrics)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, model_state, batch, rng
            )
            return loss, grads

        self._grad_fn = jax.jit(_grad)

        def _apply(params, opt_state, grads):
            updates, new_opt = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        self._apply_fn = jax.jit(_apply)

    # -- worker side ---------------------------------------------------------

    def _snapshot(self):
        with self._params_lock:
            return self.params, self.global_step

    def _flat(self, grads) -> list[np.ndarray]:
        return [np.asarray(g).reshape(-1) for g in jax.tree.leaves(grads)]

    def _worker(self, wid: int, batches: Iterator):
        """Thread wrapper: a worker crash must not strand the chief in a
        blocking ``acc.take()``/``gq.pop()`` — record, cancel, re-raise from
        ``run()`` (the reference surfaced worker errors through sess.run)."""
        try:
            self._worker_body(wid, batches)
        except BaseException as e:  # noqa: BLE001 — propagated via run()
            self._worker_excs.append((wid, e))
            self._stop.set()
            self._cancel_services()

    def _cancel_services(self) -> None:
        self._tq.cancel()
        for acc in self._accs:
            acc.cancel()
        if self._gq is not None:
            self._gq.cancel()

    def _worker_body(self, wid: int, batches: Iterator):
        it = 0
        while not self._stop.is_set():
            if self.cfg.mode == "sync_replicas":
                token = self._tq.pop()
                if token is None:
                    return
                local_step = token
            else:
                local_step = None  # read after snapshot
            params, snap_step = self._snapshot()
            if local_step is None:
                local_step = snap_step
            rng = jax.random.fold_in(jax.random.fold_in(self.rng, wid), it)
            try:
                batch = next(batches)
            except StopIteration:
                return
            loss, grads = self._grad_fn(params, self.model_state, batch, rng)
            with self._history_lock:
                self.history.append((wid, local_step, float(loss)))
            flat = np.concatenate(self._flat(grads))
            if self.cfg.mode == "sync_replicas":
                self._accs[0].apply(local_step, flat)
            else:
                self._gq.push(local_step, flat)
            it += 1

    # -- chief / updater side ------------------------------------------------

    def _unflatten_concat(self, flat: np.ndarray):
        offsets = np.cumsum([0] + self._leaf_sizes)
        arrs = [
            flat[offsets[i] : offsets[i + 1]].reshape(s)
            for i, s in enumerate(self._leaf_shapes)
        ]
        return jax.tree.unflatten(self._treedef, arrs)

    def _apply_update(self, grads) -> None:
        new_params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads
        )
        with self._params_lock:
            self.params = jax.tree.map(np.asarray, new_params)
            self.global_step += 1

    #: Sync mode: a take() stalled this long re-pushes the current step's
    #: tokens.  Tokens and drained aggregations are the two coordination
    #: quantities a connection drop can lose without a trace (their drain
    #: ops are not replay-idempotent — see ps_service docstring); extra
    #: tokens only produce gradients the staleness gate drops, so periodic
    #: re-push converts both loss windows from deadlock into delay.
    #: None in the in-process thread emulation — no transport, nothing can
    #: be lost, and a merely-slow aggregation must not receive extra
    #: same-step tokens (they would pass the staleness gate and change the
    #: averaged count).  RemotePSChief (the socket path) enables it.
    sync_stall_repush_s: float | None = None

    def _reshard_tick(self) -> None:
        """Live-resharding hook (r15): overridden by the socket chief to
        adopt a pending layout epoch; a no-op in thread mode (there is no
        topology to change inside one process)."""

    def _chief_sync(self):
        n_agg = self.cfg.replicas_to_aggregate or self.cfg.num_workers
        acc = self._accs[0]
        acc.set_global_step(self.global_step)
        self._tq.push(self.global_step, self.cfg.num_workers)
        while self.global_step < self.cfg.train_steps:
            # Accumulators/token queue may be SWAPPED by a reshard tick
            # (socket chief): tick first, then re-read them.
            self._reshard_tick()
            acc = self._accs[0]
            out = acc.take(n_agg, timeout_s=self.sync_stall_repush_s)
            if out is native.TIMED_OUT:
                faults.log_event(
                    "sync_stall_repush", step=self.global_step, n_agg=n_agg
                )
                self._tq.push(self.global_step, self.cfg.num_workers)
                continue
            if out is None:
                return
            self._apply_update(self._unflatten_concat(out))
            acc.set_global_step(self.global_step)
            self._maybe_checkpoint()
            if self.global_step < self.cfg.train_steps:
                self._tq.push(self.global_step, self.cfg.num_workers)

    def _chief_async(self):
        # Each gradient applies individually, in arrival order — the W2
        # semantics (no coalescing; see module docstring).
        for _ in range(self.global_step, self.cfg.train_steps):
            item = self._gq.pop()
            if item is None:
                return
            _, flat = item
            self._apply_update(self._unflatten_concat(flat))
            if self.cfg.max_staleness is not None:
                self._gq.set_min_step(self.global_step - self.cfg.max_staleness)
            self._maybe_checkpoint()

    # -- checkpoint/resume (section 5.4) --------------------------------------

    def _ckpt_state(self) -> dict:
        return {
            "params": self.params,
            "opt_state": self.opt_state,
            "step": np.asarray(self.global_step),
        }

    def _maybe_checkpoint(self) -> None:
        # <=1 (incl. the CheckpointSaverHook convention of 0) = every step.
        every = max(1, self.cfg.checkpoint_every)
        if self.cfg.ckpt_dir and self.global_step % every == 0:
            self.save_checkpoint()

    def save_checkpoint(self) -> None:
        """Synchronous save of params+opt_state+step (chief thread only —
        host-side state is small; sync keeps it race-free vs worker snapshots)."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(os.path.join(self.cfg.ckpt_dir, str(self.global_step)))
        if os.path.exists(path):
            return
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(path, self._ckpt_state())

    def restore_latest(self) -> bool:
        """Restore newest checkpoint under ``cfg.ckpt_dir`` if any; returns
        whether a restore happened.  ``run()`` calls this automatically."""
        import orbax.checkpoint as ocp

        d = self.cfg.ckpt_dir
        if not d or not os.path.isdir(d):
            return False
        steps = sorted(
            (int(n) for n in os.listdir(d) if n.isdigit()), reverse=True
        )
        if not steps:
            return False
        template = jax.tree.map(ocp.utils.to_shape_dtype_struct, self._ckpt_state())
        with ocp.StandardCheckpointer() as ckptr:
            restored = ckptr.restore(
                os.path.abspath(os.path.join(d, str(steps[0]))), template
            )
        with self._params_lock:
            self.params = jax.tree.map(np.asarray, restored["params"])
            self.opt_state = restored["opt_state"]
            self.global_step = int(restored["step"])
        log.info("async-PS resumed from step %d", self.global_step)
        return True

    # -- run -----------------------------------------------------------------

    def _run_async_fixed(self, batch_fns: list[Iterator]) -> Any:
        """Deterministic async schedule (cfg.fixed_interleave): one pending
        gradient per worker, applied round-robin — each apply uses a
        gradient computed while the other workers' applies advanced the
        params, i.e. genuinely STALE (staleness ~ num_workers-1), but the
        order is fixed, so two runs produce bitwise-identical params.
        ``apply_log`` records (wid, computed_at, applied_at, dropped) for
        every scheduled gradient (the staleness evidence tests assert on).

        No transport is involved, so gradients stay pytrees — the threaded
        path's flatten/unflatten wire format would be two full host copies
        per step for nothing."""
        n = self.cfg.num_workers
        if (
            self.cfg.max_staleness is not None
            and self.cfg.max_staleness < n - 1
        ):
            # Steady-state staleness of the rotation IS n-1; a tighter bound
            # would deterministically drop the SAME trailing workers' every
            # gradient — silent 100% starvation, unlike thread mode where
            # random interleaving makes drops transient.
            raise ValueError(
                f"fixed_interleave with max_staleness="
                f"{self.cfg.max_staleness} < num_workers-1={n - 1} would "
                "starve trailing workers deterministically; raise the bound "
                "or drop --deterministic"
            )
        its = [0] * n
        pending: list[tuple[int, int, Any]] = []

        def compute(wid: int) -> bool:
            try:
                batch = next(batch_fns[wid])
            except StopIteration:
                return False
            rng = jax.random.fold_in(jax.random.fold_in(self.rng, wid), its[wid])
            loss, grads = self._grad_fn(self.params, self.model_state, batch, rng)
            self.history.append((wid, self.global_step, float(loss)))
            pending.append((wid, self.global_step, grads))
            its[wid] += 1
            return True

        for w in range(n):
            compute(w)
        while self.global_step < self.cfg.train_steps and pending:
            wid, local_step, grads = pending.pop(0)
            # Apply-time staleness is bounded by n-1 (at most the other
            # n-1 pending entries advanced global_step since compute), and
            # the guard above requires max_staleness >= n-1 — so this
            # schedule never drops; apply_log's field stays for the
            # thread-mode-compatible contract.
            self.apply_log.append((wid, local_step, self.global_step, False))
            self._apply_update(grads)
            self._maybe_checkpoint()
            compute(wid)
        if self.cfg.ckpt_dir:
            self.save_checkpoint()
        log.info(
            "async-PS fixed-interleave run done: %d applied steps",
            self.global_step,
        )
        return self.params

    def run(self, batch_fns: list[Iterator]) -> Any:
        """Train to ``train_steps`` applied updates; returns final params."""
        if len(batch_fns) != self.cfg.num_workers:
            raise ValueError(
                f"need {self.cfg.num_workers} batch iterators, got {len(batch_fns)}"
            )
        self.restore_latest()
        if self.global_step >= self.cfg.train_steps:
            return self.params
        if self.cfg.mode == "async" and self.cfg.fixed_interleave:
            return self._run_async_fixed(batch_fns)
        workers = [
            threading.Thread(target=self._worker, args=(i, batch_fns[i]), daemon=True)
            for i in range(self.cfg.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            if self.cfg.mode == "sync_replicas":
                self._chief_sync()
            else:
                self._chief_async()
        finally:
            self._stop.set()
            self._cancel_services()
            for w in workers:
                w.join(timeout=10)
        if self._worker_excs:
            wid, exc = self._worker_excs[0]
            raise RuntimeError(f"async-PS worker {wid} failed") from exc
        if self.cfg.ckpt_dir:
            self.save_checkpoint()
        self.total_dropped = sum(acc.dropped for acc in self._accs) + (
            self._gq.dropped if self._gq is not None else 0
        )
        self.total_deduped = sum(acc.deduped for acc in self._accs) + (
            self._gq.deduped if self._gq is not None else 0
        )
        log.info(
            "async-PS run done: %d applied steps, %d stale grads dropped",
            self.global_step,
            self.total_dropped,
        )
        return self.params


# ----------------------------------------------------------------------------
# Cross-process mode (r3): the same emulation over native/ps_server.cc
# ----------------------------------------------------------------------------


class RemotePSChief(AsyncPSTrainer):
    """Chief PROCESS: hosts the C++ PS service in-process (the PS-task role
    — ``tf.train.Server`` started by every task, SURVEY.md section 3.1),
    publishes parameter snapshots to the param store after every applied
    update, and runs the chief loop.  Workers are SEPARATE PROCESSES running
    :func:`remote_worker_loop`; thread mode (AsyncPSTrainer) stays the CI
    default.

    ``ps_addr``: connect to an EXTERNAL PS service (a ``--job_name=ps``
    process running :func:`host_ps_task`) instead of hosting in-process —
    the reference's dedicated-PS-task topology; the chief then signals
    ``ps_shutdown`` when training ends so the PS process exits 0.

    Sharded store (r9): ``ps_addrs`` (or ``ports`` for the in-process
    topology) lists N shard servers — the flat parameter vector is
    partitioned per :class:`ps_shard.ShardLayout` and every publish/pull/
    gradient moves as N concurrent per-shard transfers
    (``replica_device_setter`` spreading over multiple ``--ps_hosts``,
    SURVEY.md section 3.1).  Step tokens and the shutdown signal stay on
    shard 0 (the coordinator).  N = 1 keeps the r7 single-connection wire
    byte-identical.

    Fault posture (r6): each shard client carries per-op deadlines and a
    reconnect budget (cfg.ps_op_timeout_s / ps_reconnect_deadline_s); when
    a reconnect lands on a NEW server incarnation (that PS task was
    restarted, e.g. by ``supervise()``, losing its state) the chief
    re-seeds THAT SHARD individually — republish its params slice, restore
    its accumulator's global step, re-push tokens if it is the coordinator
    shard — so one shard's crash-restart never disturbs the other shards'
    state or the workers' versioned caches of them."""

    #: Socket path: lost tokens/aggregations are real here — self-heal
    #: (see AsyncPSTrainer.sync_stall_repush_s).
    sync_stall_repush_s = 30.0

    def __init__(
        self, cfg, loss_fn, optimizer, init_params, *,
        port: int = 0, ps_addr: tuple[str, int] | None = None,
        ps_addrs: list[tuple[str, int]] | None = None,
        ports: list[int] | None = None,
        listen_all: bool = False, ps_replicas: int = 1,
        layout_version: int = 0, **kw,
    ):
        """``listen_all``: bind the in-process service on all interfaces
        (workers on other hosts; unauthenticated — explicit opt-in only,
        same contract as ``host_ps_task``).  ``ps_addrs``: external shard
        servers, one per shard (``ps_addr`` = the 1-shard shorthand);
        ``ports``: host N shard servers in-process at these ports (0 =
        ephemeral; ``port`` = the 1-shard shorthand).

        Replication (r12): ``ps_replicas=2`` reads the address/port list
        replica-major (shards*2 entries: primaries then backups).  The
        in-process topology starts every replica server here and wires
        each pair as peers; clients fail over inside their own recovery
        loop, so a killed primary costs NO chief reseed.
        ``layout_version`` pins every connection to the shard-topology
        epoch."""
        from . import ps_service, ps_shard

        if ps_addrs is None and ps_addr is not None:
            ps_addrs = [ps_addr]
        client_kw = dict(
            op_timeout_s=cfg.ps_op_timeout_s,
            reconnect_deadline_s=cfg.ps_reconnect_deadline_s,
            wire_dtype=cfg.ps_wire_dtype,
            tenant=cfg.tenant,
        )
        role = faults.current_role() or "chief0"
        self.ps_replicas = int(ps_replicas)
        self._role = role
        self._client_kw = dict(client_kw)
        #: Chief reseeds performed (the last-resort path) — the replicated
        #: acceptance gate asserts this stays ZERO across a primary kill.
        #: The resharding acceptance gate (r15) asserts it stays zero
        #: across a whole N→M→N cycle too: the new layout's state comes
        #: from ranged REPL_SYNC + the chief's swap-time republish, never
        #: from the reseed path.
        self.reseeds = 0
        #: Committed layout-epoch transitions this chief performed (r15).
        self.reshards = 0
        self._next_reshard_poll = 0.0
        if ps_addrs is not None:
            self._owns_server = False
            n = len(ps_addrs) // self.ps_replicas
            self.ports = [p for _, p in ps_addrs[:n]]
        else:
            all_ports = list(ports) if ports else [port]
            n = len(all_ports) // self.ps_replicas
            bound = [
                ps_service.start_server(
                    p, loopback_only=not listen_all, shard_id=i % n,
                    shard_count=n, layout_version=layout_version,
                )
                for i, p in enumerate(all_ports)
            ]
            if self.ps_replicas > 1:
                # Ephemeral ports force start-then-pair: wire each shard's
                # two cold servers as peers (replica-major grouping — the
                # ONE definition, ps_shard.replica_major), then have the
                # backup adopt the primary's state TOKEN via one REPL_SYNC
                # — both are empty, but the pair must share one state
                # lineage or the first failover would misread the backup
                # as state-lost.
                for primary, backup in ps_shard.replica_major(
                    bound, n, self.ps_replicas
                ):
                    ps_service.set_server_peer(
                        primary, ("127.0.0.1", backup)
                    )
                    ps_service.set_server_peer(
                        backup, ("127.0.0.1", primary)
                    )
                    ps_service.resync_server(backup, wait_s=10.0)
            self.ports = bound[:n]
            ps_addrs = [("127.0.0.1", p) for p in bound]
            self._owns_server = True
        self.port = self.ports[0]
        self._group = ps_shard.ShardedPSClients(
            ps_addrs, role=role, replicas=self.ps_replicas,
            layout_version=layout_version, **client_kw,
        )
        self._client = self._group.coordinator
        super().__init__(cfg, loss_fn, optimizer, init_params, **kw)
        total = sum(self._leaf_sizes)
        self._layout = ps_shard.ShardLayout(
            total, self._group.num_shards,
            num_replicas=self.ps_replicas, version=layout_version,
        )
        # Replace the in-process services with their (sharded) socket
        # proxies, so the chief exercises the same transport the workers do.
        if cfg.mode == "sync_replicas":
            self._accs = [
                ps_shard.ShardedAccumulator(self._group, "acc", self._layout)
            ]
        else:
            self._gq = ps_shard.ShardedGradientQueue(
                self._group, "gq", self._layout,
                capacity=max(4, 2 * cfg.num_workers),
            )
        self._tq = ps_service.RemoteTokenQueue(self._group.coordinator, "tokens")
        self._pstore = ps_shard.ShardedParamStore(
            self._group, "params", self._layout
        )
        for i, c in enumerate(self._group.clients):
            c.on_reincarnation(lambda i=i: self._reseed_ps_state(i))
        self._publish()

    # -- live resharding (r15): the chief side of the epoch transition -------

    @property
    def layout_version(self) -> int:
        return self._layout.version

    def _reshard_tick(self) -> None:
        """Adopt a PENDING layout epoch announced on the coordinator (new
        shard tasks started with ``--ps_reshard_to``), time-gated to one
        O(header) poll per ``cfg.reshard_poll_s``.  Runs between applied
        updates in both chief loops — the swap happens at a quiescent
        point of the chief's own state, never mid-gather."""
        from . import reshard

        if not self.cfg.reshard_watch:
            return
        now = time.monotonic()
        if now < self._next_reshard_poll:
            return
        self._next_reshard_poll = now + self.cfg.reshard_poll_s
        try:
            rec = reshard.poll_pending(self._group.coordinator)
        except Exception:  # noqa: BLE001 — coordinator mid-failover
            return
        if rec is None or rec["version"] <= self._layout.version:
            return
        self._adopt_record(rec)

    def _adopt_record(self, rec: dict) -> bool:
        """Verify → republish → commit → swap → drain: the whole epoch
        transition, driven by one pending record.  Returns True when the
        new layout was committed; a failed verify ABORTS the pending
        record loudly and keeps the old topology serving — a transition
        completes or aborts, never half-applies."""
        from . import ps_service, ps_shard, reshard

        version, total = rec["version"], sum(self._leaf_sizes)
        faults.log_event(
            "reshard_adopting", version=version, shards=rec["shards"],
            step=self.global_step,
        )
        if rec["num_elems"] != total:
            log.error(
                "reshard v%d names %d elems but this run trains %d — "
                "aborting the transition", version, rec["num_elems"], total,
            )
            self._reshard_abort(version)
            return False
        # VERIFY: dial every new shard (epoch-pinned HELLO) and wait for a
        # synced snapshot.  A joiner killed mid-transition fails here.
        new_group = None
        try:
            new_group = ps_shard.ShardedPSClients.for_record(
                rec, role=self._role, **self._client_kw
            )
            new_layout = new_group.layout_for(total)
            new_pstore = ps_shard.ShardedParamStore(
                new_group, "params", new_layout
            )
            deadline = time.monotonic() + self.cfg.reshard_ready_timeout_s
            while True:
                step, _ = new_pstore.get()
                if step >= 0:
                    break
                if time.monotonic() >= deadline:
                    raise ConnectionError(
                        f"new layout v{version} never presented a synced "
                        f"snapshot within {self.cfg.reshard_ready_timeout_s}s"
                    )
                time.sleep(0.1)
            # Republish the CURRENT params at the CURRENT step onto the
            # new layout: the swap must never serve the (stale) step the
            # joiners synced at.
            new_pstore.set(self.global_step, self._flat_params())
            # Recreate the coordination objects on the new topology; the
            # dedup tag space re-scopes with them (fresh servers, fresh
            # tables — every swapped worker re-announces via
            # *_RESET_WORKER and restarts its 0-based stream).
            new_tq = ps_service.RemoteTokenQueue(
                new_group.coordinator, "tokens"
            )
            if self.cfg.mode == "sync_replicas":
                new_accs = [
                    ps_shard.ShardedAccumulator(new_group, "acc", new_layout)
                ]
                new_accs[0].set_global_step(self.global_step)
                new_gq = None
            else:
                new_accs = []
                new_gq = ps_shard.ShardedGradientQueue(
                    new_group, "gq", new_layout,
                    capacity=max(4, 2 * self.cfg.num_workers),
                )
                if self.cfg.max_staleness is not None:
                    new_gq.set_min_step(
                        self.global_step - self.cfg.max_staleness
                    )
            # Seed the NEW coordinator's record slots: late joiners and
            # restarted members discover the committed topology from
            # either end, and dtxtop follows the chain.
            blob = reshard.pack_record(
                version, rec["addrs"], total, replicas=rec["replicas"],
                from_version=rec["from"]["version"],
                from_addrs=rec["from"]["addrs"],
                from_replicas=rec["from"]["replicas"],
            )
            new_group.coordinator.reshard_announce(version, blob)
            new_group.coordinator.reshard_commit(version)
        except Exception as e:  # noqa: BLE001 — abort, keep old topology
            log.error("reshard v%d failed verification: %s", version, e)
            faults.log_event(
                "reshard_aborted", version=version, error=type(e).__name__,
            )
            telemetry.REGISTRY.inc("ps_chief/reshard_aborts")
            if new_group is not None:
                new_group.close()
            self._reshard_abort(version)
            return False
        # COMMIT on the old coordinator: every polling client now swaps.
        old_group, old_layout = self._group, self._layout
        old_replica_addrs = [
            a for rl in zip(*old_group.replica_addrs) for a in rl
        ] if old_group.replica_addrs else []
        try:
            old_group.coordinator.reshard_commit(version)
        except Exception:  # noqa: BLE001
            # The old coordinator died at the worst moment: the pending
            # record is gone with it, but the NEW topology is already
            # committed on its own coordinator — finish the swap; old
            # clients heal through their own recovery paths.
            log.exception("old-coordinator commit failed; swapping anyway")
        # SWAP the chief's own state.
        self._group, self._layout = new_group, new_layout
        self._client = new_group.coordinator
        self._pstore, self._tq = new_pstore, new_tq
        if self.cfg.mode == "sync_replicas":
            self._accs = new_accs
            if self.global_step < self.cfg.train_steps:
                self._tq.push(self.global_step, self.cfg.num_workers)
        else:
            self._gq = new_gq
        for i, c in enumerate(new_group.clients):
            c.on_reincarnation(lambda i=i: self._reseed_ps_state(i))
        self.reshards += 1
        telemetry.REGISTRY.inc("ps_chief/reshards")
        faults.log_event(
            "reshard_committed", version=version, shards=rec["shards"],
            step=self.global_step,
        )
        # DRAIN the old layout: flush sync workers first (one round of
        # tokens on the OLD queue unblocks a worker parked in a token pop
        # so its next loop iteration polls the epoch and swaps — the
        # extra tokens' gradients land in the abandoned old accumulator,
        # the usual harmless at-least-once token posture), close our own
        # legs (they must not hold the drain open), then signal each old
        # task drain-then-exit.
        if self.cfg.mode == "sync_replicas":
            try:
                ps_service.RemoteTokenQueue(
                    old_group.coordinator, "tokens"
                ).push(self.global_step, self.cfg.num_workers)
            except Exception:  # noqa: BLE001 — old coordinator may be gone
                pass
        try:
            # Unblock every waiter parked on the OLD layout (a worker
            # wedged in a full-queue push or a token pop cannot poll the
            # epoch): cancelled ops answer None, and the worker's
            # cancelled-path forced epoch poll swaps it immediately
            # instead of stalling out the drain window.
            old_group.cancel_all()
        except Exception:  # noqa: BLE001
            pass
        old_group.fail_fast()
        old_group.close()
        self._drain_old_layout(old_layout, old_replica_addrs)
        return True

    def _reshard_abort(self, version: int) -> None:
        try:
            self._group.coordinator.reshard_abort(version)
        except Exception:  # noqa: BLE001 — best effort; record may be gone
            log.exception("reshard abort signal failed")

    def _drain_old_layout(self, old_layout, old_replica_addrs) -> None:
        """Retire the old layout's servers.  In-process servers (the
        chief-hosted topology) stop once their connections drain; external
        tasks get the DRAIN shutdown token (``ps_shutdown`` value 1 —
        ``host_ps_task`` flags itself draining, waits out its clients,
        exits 0)."""
        from . import ps_service

        if self._owns_server:
            old_ports = list(self.ports)
            self.ports = [p for _, p in self._group.addrs]
            self.port = self.ports[0]

            def _drain() -> None:
                for p in old_ports:
                    ps_service.set_server_draining(p, True)
                deadline = time.monotonic() + self.cfg.reshard_drain_s
                while time.monotonic() < deadline and any(
                    ps_service.server_live_conns(p) > 0 for p in old_ports
                ):
                    time.sleep(0.2)
                for p in old_ports:
                    ps_service.stop_server(p)
                faults.log_event("reshard_old_stopped", ports=old_ports)

            threading.Thread(
                target=_drain, daemon=True, name="dtx-reshard-drain"
            ).start()
            return
        self.ports = [p for _, p in self._group.addrs]
        self.port = self.ports[0]
        for h, p in old_replica_addrs:
            try:
                c = ps_service.PSClient(h, p, timeout_s=5.0)
                try:
                    ps_service.RemoteTokenQueue(c, "ps_shutdown").push(1)
                finally:
                    c.close()
            except Exception:  # noqa: BLE001
                log.info("drain signal not delivered to %s:%d", h, p)

    def reshard_to(
        self, new_shards: int, ports: list[int] | None = None,
        adopt: bool = False,
    ) -> bool:
        """In-process N→M reshard (tests / the chief-hosted topology):
        start ``new_shards`` fresh in-process servers on the next layout
        epoch, sync their slices from the live old layout over ranged
        REPL_SYNC, and ANNOUNCE the transition on the coordinator — the
        chief loop's own ``_reshard_tick`` then adopts it at its next
        quiescent point (callable from any thread while training runs).
        ``adopt=True`` runs the adopt/commit/swap/drain inline instead —
        only safe when the chief loop is NOT running.  External clusters
        never call this — their joiners are ``--ps_reshard_to`` tasks and
        the chief adopts the pending record they announce."""
        from . import ps_service, reshard

        if not self._owns_server:
            raise RuntimeError(
                "reshard_to() drives the chief-hosted topology only; "
                "external clusters start --ps_reshard_to tasks instead"
            )
        version = max(self._layout.version, 0) + 1
        old_version = self._layout.version
        ports = list(ports) if ports else [0] * new_shards
        bound = [
            ps_service.start_server(
                p, shard_id=j, shard_count=new_shards,
                layout_version=version,
            )
            for j, p in enumerate(ports)
        ]
        new_addrs = [("127.0.0.1", p) for p in bound]
        meta = reshard.discover_old_layout(
            self._group.replica_addrs, old_version=old_version
        )
        for j, addr in enumerate(new_addrs):
            reshard.install_assembled(
                addr,
                reshard.assemble_for_shard(
                    self._group.replica_addrs, j, new_shards,
                    old_version=old_version, layout_meta=meta,
                ),
                layout_version=version,
            )
        old_replica_major = [
            a for rl in zip(*self._group.replica_addrs) for a in rl
        ]
        blob = reshard.pack_record(
            version, new_addrs, sum(self._leaf_sizes),
            from_version=old_version, from_addrs=old_replica_major,
            from_replicas=self.ps_replicas,
        )
        self._group.coordinator.reshard_announce(version, blob)
        if adopt:
            return self._adopt_record(reshard.parse_record(blob))
        return True

    def _chief_async(self):
        # The socket chief's async loop: the thread-mode semantics (each
        # gradient applies individually, in arrival order) plus a bounded
        # pop so a pending reshard is adopted even between gradient
        # arrivals (workers may all be mid-swap).
        while self.global_step < self.cfg.train_steps:
            self._reshard_tick()
            item = self._gq.pop(timeout_s=2.0)
            if item is native.TIMED_OUT:
                continue
            if item is None:
                return
            _, flat = item
            self._apply_update(self._unflatten_concat(flat))
            if self.cfg.max_staleness is not None:
                self._gq.set_min_step(self.global_step - self.cfg.max_staleness)
            self._maybe_checkpoint()

    def _reseed_ps_state(self, shard: int = 0) -> None:
        """Run after a reconnect re-created the (empty) objects on a
        restarted shard server: push back the volatile state that only the
        chief can reconstruct — for THAT shard alone (r9: the other
        shards' servers, and every worker's versioned cache of them, are
        untouched).  In-flight worker gradients from the old incarnation
        are lost — exactly the reference's stale-drop posture — and
        re-pushed tokens may admit an extra gradient per worker, which the
        staleness gate then drops.

        With replication (r12) this is the LAST-RESORT path: it fires only
        when a shard's state was lost on EVERY replica (the client-side
        state-token check short-circuits the callback otherwise), so the
        ``reseeds`` counter stays 0 across any single-replica incident."""
        self.reseeds += 1
        telemetry.REGISTRY.inc("ps_chief/reseeds")
        faults.log_event(
            "chief_reseed", step=self.global_step, mode=self.cfg.mode,
            shard=shard,
        )
        self._pstore.set_shard(shard, self.global_step, self._flat_params())
        if self.cfg.mode == "sync_replicas":
            self._accs[0].set_global_step_shard(shard, self.global_step)
            if shard == 0 and self.global_step < self.cfg.train_steps:
                # Tokens live on the coordinator shard only.
                self._tq.push(self.global_step, self.cfg.num_workers)
        elif self.cfg.max_staleness is not None:
            self._gq.set_min_step_shard(
                shard, self.global_step - self.cfg.max_staleness
            )

    def live_workers(self) -> list[dict]:
        """The live async-worker set per the coordinator's lease registry
        (r14) — the elastic replacement for counting ``--worker_hosts``.
        Empty against a registry nobody heartbeats into (static clusters,
        or ``membership_leases`` off)."""
        from . import membership

        return membership.live_members(
            self._group.coordinator, "worker", tenant=self.cfg.tenant
        )

    def _flat_params(self) -> np.ndarray:
        return np.concatenate(
            [np.asarray(l).reshape(-1) for l in jax.tree.leaves(self.params)]
        ).astype(np.float32)

    def _publish(self) -> None:
        self._pstore.set(self.global_step, self._flat_params())

    def _apply_update(self, grads) -> None:
        super()._apply_update(grads)
        self._publish()

    def run_chief(self):
        """Run the chief loop against EXTERNAL worker processes; returns the
        final params.  Cancels all blocked waiters at the end so workers'
        pending pops return None and they exit."""
        from . import ps_service

        self.restore_latest()
        self._publish()
        try:
            if self.global_step < self.cfg.train_steps:
                if self.cfg.mode == "sync_replicas":
                    self._chief_sync()
                else:
                    self._chief_async()
        finally:
            # Unblock the workers FIRST and unconditionally: any remote call
            # placed before cancel_all could raise on a broken transport and
            # strand every external worker in a blocking pop.
            try:
                self._publish()  # final step: async workers observe done-ness
            except Exception:
                log.exception("final publish failed")
            try:
                # Broadcast: workers may be blocked on ANY shard's queues.
                self._group.cancel_all()
            except Exception:
                log.exception("cancel_all failed (server already down?)")
            try:
                self.total_dropped = sum(
                    acc.dropped for acc in self._accs
                ) + (self._gq.dropped if self._gq is not None else 0)
                self.total_deduped = sum(
                    acc.deduped for acc in self._accs
                ) + (self._gq.deduped if self._gq is not None else 0)
            except Exception:
                self.total_dropped = -1  # transport gone; counter unknown
                self.total_deduped = -1
        if self.cfg.ckpt_dir:
            self.save_checkpoint()
        if not self._owns_server:
            # Dedicated-PS topology: release the external PS tasks LAST —
            # after the dropped-counter reads above — so host_ps_task only
            # tears each service down once nothing will dial it again.
            # EVERY replica task of EVERY shard waits on its own server's
            # ps_shutdown queue, and a shard's group client may have
            # failed over away from the task that still needs the signal —
            # so each replica ADDRESS gets a direct, short-lived,
            # fail-fast dial (a PS may already have exited via its
            # cancel-grace window; never spend a reconnect budget here).
            self._group.fail_fast()
            for i, replica_list in enumerate(self._group.replica_addrs):
                for r, (h, p) in enumerate(replica_list):
                    try:
                        c = ps_service.PSClient(h, p, timeout_s=5.0)
                        try:
                            ps_service.RemoteTokenQueue(c, "ps_shutdown").push(0)
                        finally:
                            c.close()
                    except Exception:
                        log.info(
                            "ps_shutdown signal not delivered to shard %d "
                            "replica %d (ps already down)", i, r,
                        )
        log.info(
            "remote async-PS chief done: %d applied steps, %d stale drops",
            self.global_step,
            self.total_dropped,
        )
        return self.params


def host_ps_task(
    port: int, *, loopback_only: bool = True, shard_id: int = 0,
    shard_count: int = 1, layout_version: int = 0,
    peer: tuple[str, int] | None = None, peer_role: str = "",
    sync_wait_s: float = 0.0,
    coordinator_addrs: list[tuple[str, int]] | None = None,
    reshard_from: dict | None = None,
    lease_ttl_s: float = 10.0,
    drain_timeout_s: float = 20.0,
) -> int:
    """Dedicated PS-task body (``--job_name=ps`` under cross-process PS
    emulation): host the C++ state service on ``port`` and block until the
    chief signals ``ps_shutdown`` (the analog of ``server.join()``, except
    it RETURNS when training ends instead of blocking forever).  Returns
    the bound port.  ``loopback_only=False`` serves other hosts (trusted
    networks only — see ps_service.start_server).

    (``shard_id``, ``shard_count``) is this task's shard identity in the
    sharded-store topology (r9): which contiguous slice of the flat
    parameter vector it owns.  HELLO-validated on every shard-aware
    connection, so a mis-wired worker fails its dial loudly.  The chief
    signals ``ps_shutdown`` to EVERY shard task at the end of training.

    Replication (r12): ``peer`` names this task's peer replica of the same
    shard — the start pulls the peer's full state (REPL_SYNC, bounded by
    ``sync_wait_s``) before serving, so a supervised RESTART rejoins with
    the survivor's state AND state token (clients then reconnect without
    any chief reseed), and state-mutating ops forward to the peer while
    serving.  ``peer_role`` (the peer task's fault role) arms ``partition``
    fault specs: a matching spec makes this server refuse the pair's
    replication traffic by policy while both stay alive — the split-brain
    injection the divergence guard is tested against.

    Arms any ``die`` fault specs for this process (``DTX_FAULT_PLAN``) —
    ``after_reqs`` triggers off the server's request counter (with a
    replicated pair, forwarded mirror traffic counts too), the
    deterministic "kill the PS at request N" fault the recovery tests
    inject; a supervisor (``supervise()``) restarts the task and the
    clients reconnect into the fresh incarnation.

    Live resharding (r15): ``reshard_from`` makes this task a JOINER of a
    layout-epoch transition (``--ps_reshard_to``): before entering the
    serve loop it assembles its slice of every param-store object from
    the OLD layout over ranged REPL_SYNC, installs it locally, announces
    the transition as the old coordinator's PENDING record (idempotent —
    every joiner announces the same record; the chief verifies, commits
    or aborts), and heartbeats a membership lease (``psv<V>s<j>``, kind
    "ps") on the NEW topology's coordinator, so a mid-transition cluster
    is readable in dtxtop.  Keys: ``addrs`` (the old replica-major host
    list), ``shards``/``replicas``/``version`` (the old topology),
    ``new_addrs`` (the target topology; this task serves entry
    ``shard_id``), ``wait_published_s``.

    ``coordinator_addrs`` (r15, RUNBOOK 4e): the lease/epoch registry this
    task consults for the idle-pair self-exit — a REPLICATED task whose
    peer is alive but that has served no client, sees no live worker/
    serve/chief lease and is claimed by no pending reshard record for a
    sustained window concludes the run is over and exits 0 on its own
    (the both-replicas-restarted corner that used to need an operator
    stop).  Defaults to this task's own server (correct for single-shard
    topologies)."""
    import time as _time

    from . import membership, ps_service, reshard

    bound = ps_service.start_server(
        port, loopback_only=loopback_only, shard_id=shard_id,
        shard_count=shard_count, layout_version=layout_version,
        peer=peer, sync_wait_s=sync_wait_s,
    )
    heartbeat = None
    if reshard_from is not None:
        old_shards = int(reshard_from.get("shards") or 1)
        old_replicas = int(reshard_from.get("replicas") or 1)
        old_version = int(reshard_from.get("version") or 0)
        old_addrs = list(reshard_from["addrs"])
        new_addrs = list(reshard_from["new_addrs"])
        from .ps_shard import replica_major

        old_by_shard = replica_major(old_addrs, old_shards, old_replicas)
        try:
            meta = reshard.join_new_shard(
                ("127.0.0.1", bound), shard_id, shard_count, layout_version,
                old_by_shard, old_version=old_version,
                wait_published_s=float(
                    reshard_from.get("wait_published_s") or 60.0
                ),
            )
        except (ConnectionError, OSError) as e:
            # A joiner RESTARTED after the commit finds the old tier
            # drained: if its own topology is already committed, serve on
            # empty — the chief's client-side reincarnation path reseeds
            # this shard (the standard restarted-shard healing); anything
            # else is a genuine failed join and must fail the task loudly.
            committed = 0
            try:
                probe = ps_service.PSClient(
                    new_addrs[0][0], new_addrs[0][1], timeout_s=5.0
                )
                try:
                    committed, _ = probe.reshard_poll(0)
                finally:
                    probe.close()
            except Exception:  # noqa: BLE001
                pass
            if committed != layout_version:
                ps_service.stop_server(bound)
                raise
            log.warning(
                "reshard joiner shard %d: old layout gone but v%d already "
                "committed — serving empty, chief reseed heals (%s)",
                shard_id, layout_version, e,
            )
            meta = None
        if meta is not None:
            num_elems = meta["num_elems"].get(
                "params", max(meta["num_elems"].values(), default=0)
            )
            blob = reshard.pack_record(
                layout_version, new_addrs, num_elems,
                from_version=old_version, from_addrs=old_addrs,
                from_replicas=old_replicas,
            )
            try:
                c = ps_service.PSClient(
                    old_by_shard[0][0][0], old_by_shard[0][0][1],
                    timeout_s=10.0,
                    addrs=old_by_shard[0] if old_replicas > 1 else None,
                )
                try:
                    c.reshard_announce(layout_version, blob)
                finally:
                    c.close()
            except ps_service.PSError as e:
                # Another joiner (or the chief) already moved the record
                # past pending — announce is idempotent only below commit.
                log.info("reshard announce v%d: %s", layout_version, e)
            faults.log_event(
                "reshard_join_synced", shard=shard_id,
                version=layout_version, num_elems=num_elems,
            )
        try:
            heartbeat = membership.LeaseHeartbeat(
                [new_addrs[0]], f"psv{layout_version}s{shard_id}",
                kind="ps",
                addr=f"{new_addrs[shard_id][0]}:{new_addrs[shard_id][1]}",
                ttl_s=lease_ttl_s,
            )
        except Exception:  # noqa: BLE001 — visibility only, never fatal
            log.warning("reshard joiner lease unavailable", exc_info=True)

    def _partition(spec) -> bool:
        if peer_role and not spec.matches_peer(peer_role):
            return False
        return ps_service.set_server_partitioned(bound, True)

    faults.arm_process_faults(
        request_count_fn=ps_service.server_request_count,
        partition_fn=_partition if peer is not None else None,
    )
    log.info(
        "PS task serving on port %d (shard %d/%d, layout v%d%s), "
        "incarnation %d (blocking until chief shutdown)", bound, shard_id,
        shard_count, layout_version,
        f", peer {peer[0]}:{peer[1]}" if peer else "",
        ps_service.server_incarnation(),
    )
    client = ps_service.PSClient("127.0.0.1", bound, timeout_s=10.0)
    tq = ps_service.RemoteTokenQueue(client, "ps_shutdown")
    cancelled = 0
    # Supervised child (ps_experiment --ps_restarts): a SIGKILL of the
    # visible PS pid kills only the supervisor — it cannot forward an
    # uncatchable signal — so watch for re-parenting and exit rather than
    # serve on as an orphan squatting the port.
    supervised = os.environ.get("DTX_PS_SUPERVISED") == "1"
    ppid0 = os.getppid()
    orphan_polls = 0
    desert_polls = 0
    # The registry the idle-pair self-exit consults (RUNBOOK 4e fix, r15):
    # live non-PS leases or a pending reshard record naming this server
    # are evidence of a live cluster; created lazily, fail-fast — a scrape
    # failure is NO evidence and resets the counter.
    desert_client: ps_service.PSClient | None = None
    coord = (coordinator_addrs or [("127.0.0.1", bound)])[0]
    own_addr_in = None
    if reshard_from is not None:
        na = reshard_from["new_addrs"][shard_id]
        own_addr_in = (str(na[0]), int(na[1]))

    def _cluster_deserted() -> bool:
        """True when the coordinator registry shows NO live worker/serve/
        chief lease AND no pending reshard record claims this server —
        the dead-cluster evidence the idle-pair exit requires.  Any
        scrape failure answers False (no evidence)."""
        nonlocal desert_client
        try:
            if desert_client is None:
                desert_client = ps_service.PSClient(
                    coord[0], coord[1], timeout_s=2.0,
                )
            live = membership.parse_leases(desert_client.lease_list())
            if any(m["kind"] != "ps" for m in live):
                return False
            v, blob = desert_client.reshard_poll(0, pending=True)
            if v > 0 and blob:
                rec = reshard.parse_record(blob)
                if own_addr_in in rec["addrs"] or (
                    "127.0.0.1", bound
                ) in rec["addrs"]:
                    return False  # we are a claimed joiner mid-transition
            return True
        except Exception:  # noqa: BLE001 — registry unreachable: no evidence
            if desert_client is not None:
                desert_client.close()
                desert_client = None
            return False

    try:
        while True:
            # Bounded pops keep this thread responsive (fault triggers, signal
            # delivery) without consuming the shutdown contract below; 2 s
            # keeps idle polling to a trickle so ``die:after_reqs`` triggers
            # stay dominated by real coordination traffic.
            token = tq.pop(timeout_s=2.0)
            if token is ps_service.TIMED_OUT:
                if supervised and os.getppid() != ppid0:
                    log.warning("PS task: supervisor died; exiting")
                    break
                # Orphaned-replica exit (r12): a replicated task that restarts
                # AFTER training ended can miss the chief's ps_shutdown push
                # entirely (its clients failed over to the peer and never came
                # back — training no longer stalls on a dead primary, so the
                # run may finish before this incarnation is even up).  Detect
                # the orphan state: the PEER is gone AND nobody but our own
                # shutdown client is connected, for a sustained window — a
                # peer merely crashing mid-run keeps the clients' connections
                # here, so a serving replica can never match this.
                if peer is not None and ps_service.server_live_conns(bound) <= 1:
                    try:
                        import socket as _socket

                        probe = _socket.create_connection(peer, timeout=0.5)
                        probe.close()
                        orphan_polls = 0
                        # Idle-PAIR exit (r15, the RUNBOOK 4e double-restart
                        # corner): the peer is ALIVE — but if neither of us
                        # has a client, the registry shows no live member of
                        # any other role, and no pending reshard claims this
                        # server, the run is over and BOTH replicas may exit
                        # on their own.  The window is deliberately long
                        # (~60 s of sustained evidence): a cluster merely
                        # booting brings its chief/workers — and their leases
                        # and connections — well inside it.
                        if _cluster_deserted():
                            desert_polls += 1
                            if desert_polls >= 30:
                                log.warning(
                                    "PS task: peer alive but no client, no "
                                    "live member lease and no reshard claim "
                                    "for ~%ds; idle replica pair exiting "
                                    "(RUNBOOK 4e)", 2 * desert_polls,
                                )
                                break
                        else:
                            desert_polls = 0
                    except OSError:
                        desert_polls = 0
                        orphan_polls += 1
                        if orphan_polls >= 10:
                            log.warning(
                                "PS task: peer gone and no clients for ~%ds; "
                                "orphaned replica exiting", 2 * orphan_polls,
                            )
                            break
                else:
                    orphan_polls = 0
                    desert_polls = 0
                continue
            if token is not None:
                if token == 1:
                    # DRAIN shutdown (r15): a reshard retired this layout.
                    # Flag draining (visible in STATS/dtxtop), wait out the
                    # remaining client connections as they swap to the new
                    # epoch, then exit 0 like any clean shutdown.
                    if heartbeat is not None:
                        heartbeat.close()
                        heartbeat = None
                    ps_service.set_server_draining(bound, True)
                    faults.log_event("ps_draining", port=bound)
                    deadline = _time.monotonic() + drain_timeout_s
                    while _time.monotonic() < deadline and \
                            ps_service.server_live_conns(bound) > 1:
                        _time.sleep(0.2)
                    log.info(
                        "PS task: drained (conns=%d); retired layout exiting",
                        ps_service.server_live_conns(bound),
                    )
                break
            # cancel_all reaches this queue too (the chief cancels before its
            # final counter reads); give the real shutdown push a grace window
            # rather than tearing the service down under the chief.
            cancelled += 1
            if cancelled >= 10:
                log.warning("PS task: repeated cancels without shutdown; exiting")
                break
            _time.sleep(0.5)
    finally:
        # EVERY exit — clean shutdown, drain, orphan/idle-pair exit,
        # or an exception out of the serve loop — releases the lease
        # heartbeat and the clients: a leaked heartbeat advertises a
        # dead PS task forever (the r14 leaked-worker-heartbeat bug
        # class; dtxlint's lifecycle pass pins this shape).
        if desert_client is not None:
            desert_client.close()
        if heartbeat is not None:
            heartbeat.close()
        client.close()
        ps_service.stop_server()
    return bound


def _await_published(pstore, wait_budget_s: float):
    """Latest published snapshot from ``pstore``, waiting out the window
    where a restarted PS has an empty (step = -1) param store until the
    owner's reseed lands; None when the budget expires first.  The ONE
    definition both the direct worker pull and the prefetch path use."""
    deadline = time.monotonic() + wait_budget_s
    step, flat = pstore.get()
    while step < 0:
        if time.monotonic() >= deadline:
            return None
        time.sleep(0.05)
        step, flat = pstore.get()
    return step, flat


class ParamPrefetcher:
    """Double-buffered param pulls on a DEDICATED PS connection (r7): while
    the worker computes the gradient for step k, the background thread
    already runs the pull for step k+1 — communication overlapped under
    compute, the TF-Replicator/parameter-server overlap the transport
    bench prices (ISSUE 2).

    Contract:

    - ``kick()`` starts the next pull if none is pending (idempotent);
      ``get()`` blocks for the pending pull (kicking one if needed),
      re-raising any error the background fetch hit — a prefetch failure
      surfaces on the CONSUMING step, never corrupts it.  After an error
      the pstore cache is invalidated and the next ``get()`` starts fresh,
      so a transient fault heals instead of wedging the worker.
    - transient transport faults (drops/delays, ``DTX_FAULT_PLAN``) are
      healed INSIDE the owned ``PSClient`` (reconnect/replay, cache
      invalidated via its ``on_reconnect`` hook); only terminal errors
      (``PSDeadlineError`` budget exhaustion) reach the caller.
    - ``None`` from ``get()`` means the published snapshot never became
      valid within the wait budget (the await_params contract).
    """

    def __init__(self, client, pstore, *, wait_budget_s: float):
        self._client, self._pstore = client, pstore
        self._wait_budget_s = wait_budget_s
        self._lock = threading.Lock()
        self._want = threading.Event()
        self._have = threading.Event()
        self._pending = False
        self._result: tuple[int, np.ndarray] | None = None
        self._exc: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dtx-ps-prefetch"
        )
        self._thread.start()

    def _fetch(self):
        return _await_published(self._pstore, self._wait_budget_s)

    def _loop(self):
        while True:
            self._want.wait()
            self._want.clear()
            if self._closed:
                return
            try:
                r, e = self._fetch(), None
            except BaseException as exc:  # noqa: BLE001 — re-raised in get()
                r, e = None, exc
                self._pstore.invalidate_cache()
            with self._lock:
                self._result, self._exc = r, e
            self._have.set()

    def kick(self) -> None:
        with self._lock:
            if self._pending or self._closed:
                return
            self._pending = True
        self._have.clear()
        self._want.set()

    def get(self):
        self.kick()
        # The fetch itself is bounded by the client's own deadlines
        # (op timeout + reconnect budget) plus the unpublished-store wait;
        # the margin only guards against a wedged prefetch thread.
        if not self._have.wait(timeout=self._wait_budget_s * 2 + 60.0):
            from . import ps_service

            raise ps_service.PSDeadlineError("param prefetch thread stalled")
        with self._lock:
            r, e = self._result, self._exc
            self._result, self._exc = None, None
            self._pending = False
        if e is not None:
            raise e
        return r

    def close(self) -> None:
        self._closed = True
        self._want.set()
        self._client.close()


def remote_worker_loop(
    host: str,
    port: int,
    wid: int,
    *,
    cfg: AsyncPSConfig,
    loss_fn: Callable,
    init_fn: Callable,
    batches: Iterator,
    model_state: Any = None,
    rng: jax.Array | None = None,
    addrs: list[tuple[str, int]] | None = None,
    ps_replicas: int = 1,
    layout_version: int = 0,
    metrics_dir: str | None = None,
    metrics_every: int = 20,
) -> int:
    """Worker PROCESS body: fetch the latest published params, compute a
    gradient on a local batch, push it (accumulator in sync mode, gradient
    queue in async mode).  Returns the number of gradients contributed.

    ``init_fn`` rebuilds the parameter STRUCTURE locally (deterministic
    shapes/treedef); values always come from the param store.

    Sharded store (r9): ``addrs`` lists the N shard servers in shard order
    (defaults to the single ``(host, port)``); pulls/pushes then move as N
    concurrent per-shard transfers and the per-shard wall times are
    exported as ``ps/pull_ms_shard<i>`` / ``ps/push_ms_shard<i>`` scalars
    under ``metrics_dir`` (every ``metrics_every`` contributed gradients)
    so shard imbalance is visible in TensorBoard.

    Fault posture (r6): each shard client reconnects through PS outages
    (bounded by cfg.ps_reconnect_deadline_s) and its pushes are
    dedup-tagged with this worker's id, so a push replayed after a drop is
    never applied twice.  After a shard server *restart*, that shard's
    store is empty until the chief re-seeds it — the worker waits for a
    republished snapshot instead of training on zeros (the OTHER shards'
    versioned caches stay valid throughout).
    """
    from . import ps_shard, ps_service, reshard
    from ..utils import metrics
    from ..utils.metrics import MetricsWriter

    if addrs is None:
        addrs = [(host, port)]
    role = faults.current_role() or f"worker{wid}"
    client_kw = dict(
        op_timeout_s=cfg.ps_op_timeout_s,
        reconnect_deadline_s=cfg.ps_reconnect_deadline_s,
        wire_dtype=cfg.ps_wire_dtype,
        tenant=cfg.tenant,
    )
    template = init_fn(jax.random.key(0))
    total, unflatten = ps_shard.flat_param_spec(template)

    class _Epoch:
        """One layout epoch's client-side objects, rebuilt whole on a
        committed reshard (r15): new pools, new layout, fresh dedup-tag
        streams (the Remote* ctors run *_RESET_WORKER and restart the
        0-based sequence — the per-epoch re-scoping that keeps a replayed
        pre-epoch push from ever colliding with the new stream)."""

        def __init__(self, e_addrs, e_replicas, e_version):
            self.acc = self.gq = self.prefetcher = None
            self._addrs = list(e_addrs)
            self._replicas, self._version = e_replicas, e_version
            self.group = ps_shard.ShardedPSClients(
                self._addrs, role=role, worker_tag=wid,
                replicas=e_replicas, layout_version=e_version, **client_kw
            )
            # Everything past the pool is one ctor transaction: a failed
            # object ensure must close the pool(s), or the swap-retry
            # loop would leak N sockets per poll against an erroring
            # new shard.
            try:
                self._build()
            except BaseException:
                self.close()
                raise

        def _build(self):
            self.layout = self.group.layout_for(total)
            self.pstore = ps_shard.ShardedParamStore(
                self.group, "params", self.layout
            )
            self.tq = ps_service.RemoteTokenQueue(
                self.group.coordinator, "tokens"
            )
            if cfg.mode == "sync_replicas":
                self.acc = ps_shard.ShardedAccumulator(
                    self.group, "acc", self.layout
                )
                self.push_ms_src = self.acc
            else:
                self.gq = ps_shard.ShardedGradientQueue(
                    self.group, "gq", self.layout,
                    capacity=max(4, 2 * cfg.num_workers),
                )
                self.push_ms_src = self.gq
                if cfg.ps_prefetch:
                    # Async only: double-buffer the pull on dedicated
                    # connections (one per shard) so the next snapshot
                    # streams while this step's gradient computes.
                    # Distinct fault role ("<role>_pf", shard i > 0
                    # appending "_s<i>") so plans can target the prefetch
                    # connections specifically; "worker*" globs match both.
                    pf_group = ps_shard.ShardedPSClients(
                        self._addrs, role=f"{role}_pf",
                        replicas=self._replicas,
                        layout_version=self._version, **client_kw
                    )
                    try:
                        pf_store = ps_shard.ShardedParamStore(
                            pf_group, "params", self.layout
                        )
                    except BaseException:
                        pf_group.close()
                        raise
                    self.prefetcher = ParamPrefetcher(
                        pf_group, pf_store,
                        wait_budget_s=max(cfg.ps_reconnect_deadline_s, 5.0),
                    )
                    self.pstore_timing = pf_store
            if self.prefetcher is None:
                self.pstore_timing = self.pstore
            # The committed-epoch poll rides the coordinator connection —
            # O(header) per cfg.reshard_poll_s while unchanged.
            self.follower = reshard.EpochFollower(
                self.group.coordinator, self._version, cfg.reshard_poll_s
            )

        def close(self):
            if self.prefetcher is not None:
                self.prefetcher.close()
            self.group.close()

    E = _Epoch(addrs, ps_replicas, layout_version)
    # Membership (r14): announce this worker in the coordinator's lease
    # registry and keep the lease renewed for the life of the loop — a
    # worker started MID-RUN becomes visible to the chief/data-service/
    # dtxtop within one heartbeat, and one that dies stops renewing and
    # is pruned within one TTL (the elastic join/leave contract).
    heartbeat = None
    if cfg.membership_leases:
        from . import membership

        heartbeat = membership.LeaseHeartbeat(
            E.group.coordinator_replica_addrs, role, kind="worker",
            ttl_s=cfg.lease_ttl_s, role=role,
            op_timeout_s=cfg.ps_op_timeout_s,
            reconnect_deadline_s=cfg.ps_reconnect_deadline_s,
            tenant=cfg.tenant,
        )
        # A ``leave`` fault (graceful departure) releases the lease on
        # its way out, so the registry records a departure, not a lapse.
        faults.register_leave_hook(heartbeat.close)
    writer = None
    contributed = 0
    reshards_followed = 0
    # Everything below runs under one finally: an exception anywhere
    # (a ctor op against a failing PS, a terminal PSDeadlineError in
    # the loop) must still release the lease — a leaked heartbeat
    # would advertise a dead worker as live forever.
    try:
        writer = MetricsWriter(metrics_dir) if metrics_dir else None
        model_state = model_state if model_state is not None else {}
        rng = rng if rng is not None else jax.random.key(0)

        def _grad(params, model_state, batch, rng):
            (loss, _), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, model_state, batch, rng
            )
            return loss, grads

        grad_fn = jax.jit(_grad)

        def await_params():
            return _await_published(
                E.pstore, max(cfg.ps_reconnect_deadline_s, 5.0)
            )

        def maybe_swap_epoch(force: bool = False) -> bool:
            """Follow a committed reshard: rebuild the whole epoch object
            set onto the record's topology; True when a swap happened.  A
            failed rebuild keeps the CURRENT epoch serving (the old tier
            drains only after every client swaps or times out) and
            retries on the next poll."""
            nonlocal E, reshards_followed
            if not cfg.reshard_watch:
                return False
            rec = E.follower.poll(force=force)
            if rec is None:
                return False
            if rec["num_elems"] != total:
                log.error(
                    "worker %d: reshard v%d names %d elems, this run "
                    "trains %d — ignoring the record", wid, rec["version"],
                    rec["num_elems"], total,
                )
                return False
            old_version = E.layout.version
            try:
                new_e = _Epoch(rec["addrs"], rec["replicas"], rec["version"])
            except (ps_service.PSError, OSError, RuntimeError) as e:
                E.follower.version = old_version  # retry next poll
                faults.log_event(
                    "worker_epoch_swap_failed", role=role,
                    version=rec["version"], error=type(e).__name__,
                )
                return False
            old, E = E, new_e
            old.close()
            reshards_followed += 1
            if heartbeat is not None:
                heartbeat.retarget(E.group.coordinator_replica_addrs)
            faults.log_event(
                "worker_epoch_swapped", role=role, version=rec["version"],
                shards=E.layout.num_shards,
            )
            return True

        it = 0
        while True:
            # EVERY remote call is inside the guard: the chief exiting (socket
            # closed mid-recv) must end the worker cleanly, not crash it.
            try:
                maybe_swap_epoch()
                if cfg.mode == "sync_replicas":
                    token = E.tq.pop()
                    if token is None:
                        # Cancelled: the chief finished — or the OLD
                        # coordinator just drain-stopped after a reshard
                        # this worker hasn't followed yet.  A forced epoch
                        # poll disambiguates: swap and continue, or exit.
                        if maybe_swap_epoch(force=True):
                            continue
                        break
                    local_step = token
                    got = await_params()
                else:
                    got = (
                        E.prefetcher.get() if E.prefetcher else await_params()
                    )
                if got is None:
                    log.warning("worker %d: no republished params; exiting", wid)
                    break
                step, flat = got
                if cfg.mode != "sync_replicas":
                    if step >= cfg.train_steps:
                        break
                    local_step = max(step, 0)
                    if E.prefetcher:
                        # Overlap the NEXT pull with this step's gradient
                        # compute (the communication/compute overlap the
                        # transport fast path exists for).
                        E.prefetcher.kick()
            except (RuntimeError, ConnectionError, OSError):
                break
            params = unflatten(flat)
            try:
                batch = next(batches)
            except StopIteration:
                break
            r = jax.random.fold_in(jax.random.fold_in(rng, wid), it)
            _, grads = grad_fn(params, model_state, batch, r)
            flat_g = np.concatenate(
                [np.asarray(g).reshape(-1) for g in jax.tree.leaves(grads)]
            ).astype(np.float32)
            try:
                if cfg.mode == "sync_replicas":
                    E.acc.apply(local_step, flat_g)
                else:
                    pushed = E.gq.push(local_step, flat_g)
                    if pushed is None:
                        # Cancelled: the chief is done — or this epoch was
                        # RETIRED under us (the chief cancels the old
                        # layout's waiters at drain).  A forced epoch poll
                        # disambiguates; the un-pushed gradient is lost
                        # exactly like a stale drop (at-most-once holds).
                        if maybe_swap_epoch(force=True):
                            continue
                        break
            except (RuntimeError, ConnectionError, OSError):
                break  # chief finished and tore the service down
            contributed += 1
            it += 1
            if writer is not None and contributed % max(1, metrics_every) == 0:
                # Per-shard transport wall times (r9 satellite): shard
                # imbalance — one slow/hot shard server — shows up as one
                # ps/*_ms_shard<i> series running away from the others.
                writer.scalars(
                    local_step,
                    {
                        **metrics.shard_scalars("pull", E.pstore_timing.last_pull_ms),
                        **metrics.shard_scalars("push", E.push_ms_src.last_push_ms),
                    },
                )
    finally:
        if writer is not None:
            writer.close()
        if heartbeat is not None:
            heartbeat.close()  # releases the lease: the clean leave signal
        E.close()
    return contributed
