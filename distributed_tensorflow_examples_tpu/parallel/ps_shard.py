"""Sharded parameter store: parallel scatter/gather over N PS shards (r9).

The reference round-robins variables over *multiple* ``--ps_hosts`` tasks
(``tf.train.replica_device_setter`` — SURVEY.md section 3.1); until r9 our
port funneled the entire flat param/gradient vector through ONE PS process
and one connection, so that host's NIC and the serialized pull/push were
the scaling bottleneck ("TensorFlow: a system for large-scale machine
learning", arXiv:1605.08695 section 4.4; weight-update sharding per
arXiv:2004.13336).  This module partitions the flat vector into N
contiguous shards — one per PS server — and turns the client hot path into
parallel scatter/gather:

- :class:`ShardLayout` is the ONE deterministic partition: sizes/offsets
  derived from ``(num_elems, num_shards)`` alone — checkpoint-stable,
  independent of worker count and identical in every process, so clients
  and the chief can never disagree about which server owns which slice.
  The HELLO handshake additionally pins each connection to its shard
  (``PSClient(expect_shard=...)``): a mis-wired dial fails loudly.
- :class:`ShardedParamStore` pulls with ``recv_into`` DIRECTLY into
  disjoint slices of a single preallocated output buffer — and pushes
  zero-copy ``memoryview`` slices of the flat vector — concurrently via a
  per-shard thread pool, so wall-clock pull time drops toward
  ``max(shard) ~ total/N`` instead of ``sum``.  Versioned pulls
  (``PSTORE_GET_IF_NEWER``) stay per-shard: an unchanged shard answers
  O(header) and its bytes are reused from the previous assembled buffer,
  so a reseeded shard refetches alone while the other shards' caches stay
  valid.
- :class:`ShardedAccumulator` / :class:`ShardedGradientQueue` scatter
  gradient slices to per-shard accumulator/queue objects and gather the
  per-shard averages/pops back into one flat vector.  Blocking gathers
  retain per-shard partial results across a ``TIMED_OUT`` return, so the
  chief's stall-repush loop never loses an already-drained shard average
  (drains are at-most-once — see ps_service).

**Semantic notes (documented divergence, SURVEY.md section 7 step 6):**

- The chief's publish and the workers' pushes are no longer atomic across
  the whole vector: two shards can briefly disagree by one step mid-
  publish, and in sync mode two shard accumulators can aggregate different
  worker subsets when ``replicas_to_aggregate < num_workers`` — exactly
  the torn-cross-variable-update window the reference's per-variable PS
  placement admits (our pre-r9 single flat store was *stricter* than the
  reference).  The chief's stall-repush heals the rare count-divergence
  stall the tear can cause.  N=1 keeps the strict pre-r9 semantics and is
  wire-byte-identical to the r7 path.
- Async pops gather each shard's head-of-queue slice; under reordered
  arrivals an assembled "gradient" may mix slices from different workers'
  same-regime pushes — elementwise-valid for every elementwise optimizer,
  and again the reference's own per-variable async behavior.

Step tokens and other coordination scalars stay on shard 0 (the
coordinator shard); ``async_ps.RemotePSChief`` publishes each shard to its
own server and reseeds a restarted shard INDIVIDUALLY via that client's
``on_reincarnation`` hook.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from ..utils import telemetry
from . import ps_service

# Sharded-store observability (r13 dtxobs): whole-gather/scatter counters
# and wall-time histograms for the `ps_shard/*` family (per-shard wall
# times stay on ``last_pull_ms``/``last_push_ms`` for the TensorBoard
# scalars; these instruments are the cross-process STATS/dtxtop view).
_OBS_PULLS = telemetry.REGISTRY.counter("ps_shard/pulls")
_OBS_PULL_HITS = telemetry.REGISTRY.counter("ps_shard/pull_cache_hits")
_OBS_PULL_MS = telemetry.REGISTRY.histogram("ps_shard/pull_ms")
_OBS_PUSHES = telemetry.REGISTRY.counter("ps_shard/pushes")
_OBS_PUSH_MS = telemetry.REGISTRY.histogram("ps_shard/push_ms")
_OBS_SCATTERS = telemetry.REGISTRY.counter("ps_shard/grad_scatters")
_OBS_GATHERS = telemetry.REGISTRY.counter("ps_shard/grad_gathers")

__all__ = [
    "ShardLayout",
    "ShardedPSClients",
    "ShardedParamStore",
    "ShardedAccumulator",
    "ShardedGradientQueue",
    "flat_param_spec",
    "replica_major",
]


def replica_major(addrs, num_shards: int, num_replicas: int):
    """Group a flat ``--ps_hosts``-ordered address list into per-shard
    replica lists — THE one definition of the replica-major convention
    (entry ``r*num_shards + s`` is replica r of shard s: the first
    ``num_shards`` entries are the primaries, so a replicas=1 list is
    exactly the pre-r12 one and adding a replica tier never renumbers the
    primaries).  Returns ``out[s][r]``.  Every site that pairs replicas
    (clients, the in-process chief topology, the ps-task peer mapping)
    must go through here — a second spelling of the arithmetic is how a
    future reshard silently pairs a client with the wrong shard's
    backup."""
    need = num_shards * num_replicas
    if len(addrs) < need:
        raise ValueError(
            f"need {need} addresses ({num_shards} shards x {num_replicas} "
            f"replicas), got {len(addrs)}"
        )
    return [
        [addrs[r * num_shards + s] for r in range(num_replicas)]
        for s in range(num_shards)
    ]


def flat_param_spec(template):
    """``(total_elems, unflatten)`` for a parameter-tree TEMPLATE — the ONE
    definition of the flat-vector convention every PS consumer shares
    (training worker loops and serving replicas): leaves in ``jax.tree``
    order, row-major reshape, contiguous concatenation.  Chief-side
    flatten (``RemotePSChief``) and every consumer's unflatten must agree
    leaf for leaf, or a published vector decodes into the wrong tree with
    no loud failure — keep this the only spelling."""
    import jax

    leaves, treedef = jax.tree.flatten(template)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    offsets = np.cumsum([0] + sizes)

    def unflatten(flat):
        return jax.tree.unflatten(
            treedef,
            [
                flat[offsets[i] : offsets[i + 1]].reshape(s)
                for i, s in enumerate(shapes)
            ],
        )

    return int(offsets[-1]), unflatten


class ShardLayout:
    """Deterministic contiguous partition of ``num_elems`` over
    ``num_shards`` servers.

    Shard ``i`` owns ``[offsets[i], offsets[i+1])``; the first
    ``num_elems % num_shards`` shards are one element larger, so the cover
    is exact for every (size, N) pair — including N > num_elems, where the
    trailing shards own zero elements (their servers stay on the launch
    topology but carry NO objects and see no data traffic — the native
    services reject zero-element objects, so empty shards are handled
    entirely client-side).  A pure function of its two inputs:
    every process, every restart, and every worker count derives the SAME
    layout, which is what makes sharded checkpoints/publishes stable.
    """

    def __init__(
        self, num_elems: int, num_shards: int, *, num_replicas: int = 1,
        version: int = 0,
    ):
        if num_elems < 0:
            raise ValueError(f"num_elems must be >= 0, got {num_elems}")
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        self.num_elems = int(num_elems)
        self.num_shards = int(num_shards)
        #: Replica dimension (r12): how many servers hold EACH shard.  The
        #: partition itself is replica-independent (replicas are copies,
        #: not slices) — checkpoint stability is untouched by replication.
        self.num_replicas = int(num_replicas)
        #: Layout version (r12): the shard-topology EPOCH, carried in the
        #: HELLO identity word so mixed-epoch clients fail loudly.  Not
        #: part of the partition math (same (num_elems, num_shards) =>
        #: same slices in every epoch that shares them).
        self.version = int(version)
        base, rem = divmod(self.num_elems, self.num_shards)
        self.sizes: tuple[int, ...] = tuple(
            base + (1 if i < rem else 0) for i in range(self.num_shards)
        )
        offs = [0]
        for s in self.sizes:
            offs.append(offs[-1] + s)
        self.offsets: tuple[int, ...] = tuple(offs)

    def slice(self, i: int) -> slice:
        return slice(self.offsets[i], self.offsets[i + 1])

    def shard_of(self, elem: int) -> int:
        """The shard owning flat index ``elem``."""
        if not 0 <= elem < max(self.num_elems, 1):
            raise IndexError(elem)
        return int(np.searchsorted(self.offsets, elem, side="right") - 1)

    def replica_addrs(
        self, addrs: list[tuple[str, int]],
    ) -> list[list[tuple[str, int]]]:
        """This layout's view of :func:`replica_major` (the ONE grouping
        definition): entry ``[s][r]`` serves shard ``s``, replica ``r``."""
        try:
            return replica_major(addrs, self.num_shards, self.num_replicas)
        except ValueError as e:
            raise ValueError(f"{self!r}: {e}") from None

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ShardLayout)
            and other.num_elems == self.num_elems
            and other.num_shards == self.num_shards
        )

    def __repr__(self) -> str:
        return (
            f"ShardLayout(num_elems={self.num_elems}, "
            f"num_shards={self.num_shards}, "
            f"num_replicas={self.num_replicas}, version={self.version})"
        )


class _ShardPool:
    """One persistent daemon thread per shard, executing the per-shard leg
    of a scatter/gather.  Persistent (not per-op spawn) so the
    unchanged-step fast path — N parallel O(header) round trips — isn't
    dominated by thread start-up, and daemon so a leaked pool can never
    wedge interpreter shutdown behind a blocked socket.

    Each ``run`` call carries its OWN completion queue (r11, a dtxlint
    blocking-under-lock fix): the pre-r11 pool serialized ``run`` with a
    lock held across the blocking result gather, so one wedged shard leg
    convoyed every other caller of the pool behind an unbounded wait.
    Routing results by per-call queue needs no lock at all — concurrent
    ``run`` calls can never cross-read each other's results, and per-shard
    ordering still holds (each shard thread drains its task queue in FIFO
    order)."""

    def __init__(self, n: int, name: str):
        self._tasks: list[queue.SimpleQueue] = [queue.SimpleQueue() for _ in range(n)]
        self._threads = [
            threading.Thread(
                target=self._loop, args=(i,), daemon=True, name=f"{name}-s{i}"
            )
            for i in range(n)
        ]
        for t in self._threads:
            t.start()

    def _loop(self, i: int) -> None:
        while True:
            item = self._tasks[i].get()
            if item is None:
                return
            fn, done = item
            try:
                done.put((i, fn(), None))
            except BaseException as e:  # noqa: BLE001 — re-raised in run()
                done.put((i, None, e))

    def run(self, fns: dict[int, object]) -> dict[int, object]:
        """Execute ``fns[i]`` on shard thread ``i`` concurrently; returns
        the per-shard results.  The first per-shard exception is re-raised
        AFTER every leg completes (a half-landed scatter must not leave
        stray worker threads racing the caller's next op)."""
        done: queue.SimpleQueue = queue.SimpleQueue()
        for i, fn in fns.items():
            self._tasks[i].put((fn, done))
        out: dict[int, object] = {}
        first_exc: BaseException | None = None
        for _ in range(len(fns)):
            i, r, e = done.get()
            if e is not None and first_exc is None:
                first_exc = e
            out[i] = r
        if first_exc is not None:
            raise first_exc
        return out

    def close(self) -> None:
        for q in self._tasks:
            q.put(None)


class ShardedPSClients:
    """One :class:`ps_service.PSClient` per shard server, plus the shared
    scatter/gather machinery the sharded objects hang off.

    ``addrs`` orders the servers BY SHARD (entry i serves shard i — the
    ``--ps_hosts`` order); with N > 1 every connection carries an
    ``expect_shard`` HELLO so a permuted/mis-copied host list fails the
    connect loudly.  N == 1 keeps the pre-r9 framing byte-identical (no
    HELLO on f32) and every sharded object degrades to a zero-overhead
    pass-through around its single-shard Remote* counterpart.

    Replication (r12): ``replicas`` > 1 reads ``addrs`` as replica-major —
    the first N entries are the shard primaries, the next N their backups
    — and each shard's ONE client carries the full replica list: a dead
    or state-lost primary fails over to the backup inside the client's
    own recovery loop (state-token checked, zero chief involvement).
    ``layout_version`` != 0 pins every connection to the shard-topology
    epoch (mixed-epoch dials fail loudly).

    Client fault roles: shard 0 keeps the caller's bare ``role`` (so
    existing single-shard fault plans keep matching), shard i > 0 gets
    ``<role>_s<i>`` — a plan can target one shard's client specifically —
    and ops issued while failed over to a backup replica inject under a
    further ``_b`` suffix (``<role>_s<i>_b``).
    """

    def __init__(
        self, addrs: list[tuple[str, int]], *, role: str | None = None,
        replicas: int = 1, layout_version: int = 0, **client_kw,
    ):
        if not addrs:
            raise ValueError("need at least one shard address")
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if len(addrs) % replicas:
            raise ValueError(
                f"{len(addrs)} addresses do not tile {replicas} replicas"
            )
        self.replicas = int(replicas)
        self.layout_version = int(layout_version)
        n = len(addrs) // replicas
        #: Per-shard PRIMARY addresses (the pre-r12 meaning of ``addrs``).
        self.addrs = list(addrs[:n])
        #: Per-shard full replica lists: ``replica_addrs[s][r]``.
        self.replica_addrs = replica_major(addrs, n, replicas)
        self.clients: list[ps_service.PSClient] = []
        try:
            for i, (host, port) in enumerate(self.addrs):
                kw = dict(client_kw)
                if role is not None:
                    kw["role"] = role if i == 0 else f"{role}_s{i}"
                self.clients.append(
                    ps_service.PSClient(
                        host, port,
                        expect_shard=(i, n) if n > 1 else None,
                        expect_layout=layout_version,
                        addrs=self.replica_addrs[i] if replicas > 1 else None,
                        **kw,
                    )
                )
        except BaseException:
            self.close()
            raise

    @classmethod
    def for_record(cls, rec: dict, *, role: str | None = None, **client_kw):
        """The next layout epoch's client pool, built from a committed
        reshard record (``parallel/reshard.py`` schema) — THE one spelling
        of the record→pool swap every epoch follower (worker loop, serve
        refresher, chief) uses: addrs replica-major from the record, every
        connection pinned to the record's epoch, so a swap onto a stale or
        half-written record fails its dials loudly instead of scattering
        onto the wrong partition."""
        return cls(
            list(rec["addrs"]), role=role, replicas=rec["replicas"],
            layout_version=rec["version"], **client_kw,
        )

    def layout_for(self, num_elems: int) -> ShardLayout:
        """This pool's deterministic partition of ``num_elems`` — shard
        count/replicas/epoch all from the pool, so a rebuilt pool and its
        layout can never disagree about the topology."""
        return ShardLayout(
            num_elems, self.num_shards, num_replicas=self.replicas,
            version=self.layout_version,
        )

    @property
    def num_shards(self) -> int:
        return len(self.addrs)

    @property
    def coordinator(self) -> ps_service.PSClient:
        """Shard 0's client — where step tokens and other unsharded
        coordination scalars live."""
        return self.clients[0]

    @property
    def coordinator_replica_addrs(self) -> list[tuple[str, int]]:
        """The coordinator shard's full replica address list — where the
        lease registry and the reshard records live (heartbeats re-target
        here on an epoch swap)."""
        return list(self.replica_addrs[0])

    def cancel_all(self) -> None:
        """Broadcast CANCEL_ALL to every shard server (chief teardown:
        workers may be blocked on any shard's queue)."""
        for c in self.clients:
            c.cancel_all()

    def fail_fast(self) -> None:
        for c in self.clients:
            c.fail_fast()

    def close(self) -> None:
        for c in self.clients:
            try:
                c.close()
            except Exception:
                pass


def _pool_for(group: ShardedPSClients, tag: str) -> _ShardPool | None:
    return (
        _ShardPool(group.num_shards, f"dtx-ps-{tag}")
        if group.num_shards > 1
        else None
    )


class ShardedParamStore:
    """The published (step, flat params) snapshot, spread over N shard
    servers — pulls gather concurrently into one preallocated buffer,
    publishes scatter zero-copy slices.  API-compatible with
    :class:`ps_service.RemoteParamStore` (``set``/``get``/
    ``invalidate_cache`` and the read-only-result contract); N == 1
    delegates to it outright, so the single-shard wire stays
    byte-identical to r7.

    Versioned pulls are per-shard: ``get`` issues ``PSTORE_GET_IF_NEWER``
    with each shard's cached step.  All-unchanged returns the previous
    assembled buffer untouched (N O(header) round trips, zero copies);
    any changed shard receives straight into its slice of a FRESH buffer
    (never the one previously returned — a consumer may still be reading
    it under the prefetch overlap) and only genuinely unchanged slices
    are copied across from the previous buffer (rare: the chief publishes
    every shard each step, so the steady state is all-changed or
    all-unchanged).

    ``last_pull_ms``/``last_push_ms`` expose the most recent per-shard
    wall times — the shard-imbalance signal the worker loop exports as
    ``ps/pull_ms_shard<i>`` TensorBoard scalars.
    """

    def __init__(
        self, group: ShardedPSClients, name: str, layout: ShardLayout, *,
        cache_pulls: bool = True,
    ):
        if layout.num_shards != group.num_shards:
            raise ValueError(
                f"{layout} does not match {group.num_shards} shard clients"
            )
        self._group, self._name, self._layout = group, name, layout
        n = layout.num_shards
        self.last_pull_ms = [0.0] * n
        self.last_push_ms = [0.0] * n
        self._single: ps_service.RemoteParamStore | None = None
        if n == 1:
            self._single = ps_service.RemoteParamStore(
                group.clients[0], name, layout.num_elems,
                cache_pulls=cache_pulls,
            )
            return
        self._pool = _pool_for(group, "pull")
        self._cache_enabled = cache_pulls
        self._steps = [-1] * n
        self._front: np.ndarray | None = None
        # Shards with a zero-size slice (N > num_elems layouts) carry no
        # remote objects and see no traffic — handled entirely here.
        self._active = [i for i in range(n) if layout.sizes[i] > 0]
        for i in self._active:
            c = group.clients[i]
            ps_service._check(
                c.ensure_object(
                    ps_service._PSTORE_GET_OBJ, name, layout.sizes[i]
                ),
                "pstore_get_obj",
            )
            if cache_pulls:
                # A transport gap proves only THAT shard's mirror stale —
                # the other shards' versioned caches stay valid (their
                # connections never dropped), so a single restarted shard
                # refetches alone.
                c.on_reconnect(lambda i=i: self.invalidate_shard(i))

    # -- cache management ---------------------------------------------------

    def invalidate_shard(self, i: int) -> None:
        if self._single is not None:
            self._single.invalidate_cache()
            return
        self._steps[i] = -1

    def invalidate_cache(self) -> None:
        if self._single is not None:
            self._single.invalidate_cache()
            return
        self._steps = [-1] * self._layout.num_shards
        self._front = None

    # -- publish (scatter) --------------------------------------------------

    def set_shard(self, i: int, step: int, flat: np.ndarray) -> None:
        """Publish ONE shard's slice of ``flat`` at ``step`` — the chief's
        targeted reseed of a restarted shard server (the other shards'
        stores, and every client's cache of them, stay untouched)."""
        if self._single is not None:
            self._single.set(step, flat)
            return
        if self._layout.sizes[i] == 0:
            return
        flat = np.ascontiguousarray(flat, np.float32).reshape(-1)
        s, _ = self._group.clients[i].call(
            ps_service._PSTORE_SET, self._name, step,
            payload=flat[self._layout.slice(i)],
        )
        ps_service._check(s, "pstore_set")

    def set(self, step: int, flat: np.ndarray) -> None:
        """Publish ``flat`` at ``step``: each shard server receives its
        contiguous slice — a zero-copy view of the caller's array on the
        f32 wire — concurrently."""
        if self._single is not None:
            t0 = time.perf_counter()
            self._single.set(step, flat)
            self.last_push_ms[0] = (time.perf_counter() - t0) * 1e3
            _OBS_PUSHES.inc()
            _OBS_PUSH_MS.observe(self.last_push_ms[0])
            return
        flat = np.ascontiguousarray(flat, np.float32).reshape(-1)
        if flat.size != self._layout.num_elems:
            raise ValueError(
                f"flat vector has {flat.size} elems, layout expects "
                f"{self._layout.num_elems}"
            )

        def one(i: int):
            t0 = time.perf_counter()
            s, _ = self._group.clients[i].call(
                ps_service._PSTORE_SET, self._name, step,
                payload=flat[self._layout.slice(i)],
            )
            self.last_push_ms[i] = (time.perf_counter() - t0) * 1e3
            return ps_service._check(s, "pstore_set")

        t_all = time.perf_counter()
        self._pool.run({i: (lambda i=i: one(i)) for i in self._active})
        _OBS_PUSHES.inc()
        _OBS_PUSH_MS.observe((time.perf_counter() - t_all) * 1e3)

    # -- pull (gather) ------------------------------------------------------

    def _gather_full(self) -> tuple[int, np.ndarray]:
        """Unconditional full pull of every shard into one fresh buffer."""
        buf = np.empty(self._layout.num_elems, np.float32)

        def one(i: int):
            t0 = time.perf_counter()
            s, _ = self._group.clients[i].call(
                ps_service._PSTORE_GET, self._name,
                out=buf[self._layout.slice(i)],
            )
            self.last_pull_ms[i] = (time.perf_counter() - t0) * 1e3
            return ps_service._check(s, "pstore_get")

        res = self._pool.run({i: (lambda i=i: one(i)) for i in self._active})
        step = min(res.values())
        if step >= 0 and self._cache_enabled:
            for i, s in res.items():
                self._steps[i] = int(s)
            self._front = buf
        return step, buf

    def get(self) -> tuple[int, np.ndarray]:
        """Latest assembled snapshot: ``(step, flat)``.  ``step`` is the
        MINIMUM across shards — negative while any shard is still
        unpublished (restart/reseed window: callers keep polling, exactly
        the single-shard contract), and briefly one less than the newest
        shard mid-publish (the documented sharding tear).  The returned
        array is READ-ONLY and owned by the store."""
        if self._single is not None:
            t0 = time.perf_counter()
            out = self._single.get()
            self.last_pull_ms[0] = (time.perf_counter() - t0) * 1e3
            _OBS_PULLS.inc()
            _OBS_PULL_MS.observe(self.last_pull_ms[0])
            return out
        if not self._cache_enabled:
            t0 = time.perf_counter()
            out = self._gather_full()
            _OBS_PULLS.inc()
            _OBS_PULL_MS.observe((time.perf_counter() - t0) * 1e3)
            return out
        t_all = time.perf_counter()
        have = list(self._steps) if self._front is not None else [-1] * self._layout.num_shards
        buf = np.empty(self._layout.num_elems, np.float32)

        def one(i: int):
            t0 = time.perf_counter()
            s, out = self._group.clients[i].call(
                ps_service._PSTORE_GET_IF_NEWER, self._name, have[i],
                out=buf[self._layout.slice(i)],
            )
            self.last_pull_ms[i] = (time.perf_counter() - t0) * 1e3
            return s, out.size

        res = self._pool.run({i: (lambda i=i: one(i)) for i in self._active})
        statuses = {i: s for i, (s, _) in res.items()}
        if any(s == -2 for s in statuses.values()):
            # Pre-v2 server on some shard: fall back to full pulls for the
            # life of this store rather than failing the caller.
            self._cache_enabled = False
            return self._gather_full()
        for s in statuses.values():
            ps_service._check(s, "pstore_get_if_newer")
        if any(s < 0 for s in statuses.values()):
            # Some shard never published (PS restart before the chief's
            # reseed landed): status-only overall, nothing cached —
            # callers gate on step < 0 and poll, per the await contract.
            return min(statuses.values()), np.empty((0,), np.float32)
        changed = {i for i, (s, size) in res.items() if size != 0}
        stale = {
            i for i in self._active
            if i not in changed and statuses[i] != have[i]
        }
        if stale:
            # A shard's step moved without a payload (republished at a
            # lower step — a reseed this client never saw as a reconnect):
            # distrust that mirror and refetch the shard in full.
            def refetch(i: int):
                s, _ = self._group.clients[i].call(
                    ps_service._PSTORE_GET, self._name,
                    out=buf[self._layout.slice(i)],
                )
                return ps_service._check(s, "pstore_get")

            rres = self._pool.run({i: (lambda i=i: refetch(i)) for i in stale})
            statuses.update(rres)
            changed |= stale
        if not changed:
            # All shards unchanged: N header-sized round trips, zero data
            # movement — the sharded analog of the r7 if-newer fast path.
            _OBS_PULLS.inc()
            _OBS_PULL_HITS.inc()
            _OBS_PULL_MS.observe((time.perf_counter() - t_all) * 1e3)
            return min(statuses.values()), self._front
        if len(changed) < len(self._active) and self._front is not None:
            # Mixed: the unchanged shards' bytes live in the previous
            # buffer — copy them across (rare; see class docstring).
            for i in self._active:
                if i not in changed:
                    buf[self._layout.slice(i)] = self._front[self._layout.slice(i)]
        for i, s in statuses.items():
            self._steps[i] = int(s)
        self._front = buf
        _OBS_PULLS.inc()
        _OBS_PULL_MS.observe((time.perf_counter() - t_all) * 1e3)
        return min(statuses.values()), buf


class ShardedAccumulator:
    """Sync-mode gradient aggregation over per-shard accumulators:
    ``apply`` scatters the flat gradient's slices concurrently (dedup-
    tagged per shard connection when the client carries a ``worker_tag``);
    ``take`` gathers the per-shard averages back into one flat vector.

    A ``take`` that times out on SOME shards retains the shards that DID
    answer (``_partial``) and re-takes only the missing ones on the next
    call — the drain is at-most-once, so retrying an already-drained
    shard would lose its average and deadlock the chief's stall-repush
    loop.  API-compatible with :class:`ps_service.RemoteAccumulator`;
    N == 1 is a direct pass-through."""

    def __init__(self, group: ShardedPSClients, name: str, layout: ShardLayout):
        if layout.num_shards != group.num_shards:
            raise ValueError(
                f"{layout} does not match {group.num_shards} shard clients"
            )
        self._group, self._name, self._layout = group, name, layout
        self._pool = _pool_for(group, "acc")
        self.last_push_ms = [0.0] * layout.num_shards
        self._active = [i for i in range(layout.num_shards) if layout.sizes[i] > 0]
        self._accs = {
            i: ps_service.RemoteAccumulator(
                group.clients[i], name, layout.sizes[i]
            )
            for i in self._active
        }
        self._partial: dict[int, np.ndarray] = {}

    def apply(self, local_step: int, grad: np.ndarray) -> bool:
        grad = np.ascontiguousarray(grad, np.float32).reshape(-1)
        _OBS_SCATTERS.inc()
        if self._layout.num_shards == 1:
            t0 = time.perf_counter()
            r = self._accs[0].apply(local_step, grad)
            self.last_push_ms[0] = (time.perf_counter() - t0) * 1e3
            return r

        def one(i: int):
            t0 = time.perf_counter()
            r = self._accs[i].apply(local_step, grad[self._layout.slice(i)])
            self.last_push_ms[i] = (time.perf_counter() - t0) * 1e3
            return r

        res = self._pool.run({i: (lambda i=i: one(i)) for i in self._active})
        # Per-shard staleness gating can briefly disagree (the documented
        # tear); report "counted" only when every shard accepted.
        return all(res.values())

    def take(self, num_required: int, timeout_s: float | None = None):
        """Blocking sharded average; None when cancelled, ``TIMED_OUT``
        when ``timeout_s`` expires on any still-missing shard (already-
        gathered shards are retained for the next call)."""
        if self._layout.num_shards == 1:
            return self._accs[0].take(num_required, timeout_s)
        pending = [i for i in self._active if i not in self._partial]
        res = self._pool.run(
            {i: (lambda i=i: self._accs[i].take(num_required, timeout_s))
             for i in pending}
        )
        cancelled = False
        for i, r in res.items():
            if r is None:
                cancelled = True
            elif r is not ps_service.TIMED_OUT:
                self._partial[i] = r
        if cancelled:
            self._partial.clear()
            return None
        if len(self._partial) < len(self._active):
            return ps_service.TIMED_OUT
        out = np.empty(self._layout.num_elems, np.float32)
        for i in self._active:
            out[self._layout.slice(i)] = self._partial[i]
        self._partial.clear()
        _OBS_GATHERS.inc()
        return out

    def set_global_step(self, step: int) -> None:
        if self._layout.num_shards == 1:
            self._accs[0].set_global_step(step)
            return
        self._pool.run(
            {i: (lambda i=i: self._accs[i].set_global_step(step))
             for i in self._active}
        )

    def set_global_step_shard(self, i: int, step: int) -> None:
        """Restore ONE (restarted) shard accumulator's global step — the
        chief's targeted reseed."""
        if i in self._accs:
            self._accs[i].set_global_step(step)

    @property
    def dropped(self) -> int:
        return sum(a.dropped for a in self._accs.values())

    @property
    def deduped(self) -> int:
        return sum(a.deduped for a in self._accs.values())

    def cancel(self) -> None:
        self._group.cancel_all()


class ShardedGradientQueue:
    """Async-mode gradient transport over per-shard queues: ``push``
    scatters the flat gradient's slices concurrently, ``pop`` gathers one
    slice per shard back into a flat vector (head-of-queue per shard —
    see the module docstring's note on cross-shard mixing).  Timed-out
    pops retain the shards that answered, like :class:`ShardedAccumulator`.
    API-compatible with :class:`ps_service.RemoteGradientQueue`; N == 1 is
    a direct pass-through."""

    def __init__(
        self, group: ShardedPSClients, name: str, layout: ShardLayout,
        capacity: int = 16,
    ):
        if layout.num_shards != group.num_shards:
            raise ValueError(
                f"{layout} does not match {group.num_shards} shard clients"
            )
        self._group, self._name, self._layout = group, name, layout
        self._pool = _pool_for(group, "gq")
        self.last_push_ms = [0.0] * layout.num_shards
        self._active = [i for i in range(layout.num_shards) if layout.sizes[i] > 0]
        self._gqs = {
            i: ps_service.RemoteGradientQueue(
                group.clients[i], name, layout.sizes[i], capacity
            )
            for i in self._active
        }
        self._partial: dict[int, tuple[int, np.ndarray]] = {}

    def push(self, local_step: int, grad: np.ndarray) -> bool | None:
        grad = np.ascontiguousarray(grad, np.float32).reshape(-1)
        _OBS_SCATTERS.inc()
        if self._layout.num_shards == 1:
            t0 = time.perf_counter()
            r = self._gqs[0].push(local_step, grad)
            self.last_push_ms[0] = (time.perf_counter() - t0) * 1e3
            return r

        def one(i: int):
            t0 = time.perf_counter()
            r = self._gqs[i].push(local_step, grad[self._layout.slice(i)])
            self.last_push_ms[i] = (time.perf_counter() - t0) * 1e3
            return r

        res = self._pool.run({i: (lambda i=i: one(i)) for i in self._active})
        if any(r is None for r in res.values()):
            return None  # cancelled: the chief is done or failed
        return all(bool(r) for r in res.values())

    def pop(self, timeout_s: float | None = None):
        """Blocking sharded pop; ``(local_step, flat)``, None when
        cancelled+drained, ``TIMED_OUT`` when ``timeout_s`` expires on any
        still-missing shard (gathered shards retained)."""
        if self._layout.num_shards == 1:
            return self._gqs[0].pop(timeout_s)
        pending = [i for i in self._active if i not in self._partial]
        res = self._pool.run(
            {i: (lambda i=i: self._gqs[i].pop(timeout_s)) for i in pending}
        )
        cancelled = False
        for i, r in res.items():
            if r is None:
                cancelled = True
            elif r is not ps_service.TIMED_OUT:
                self._partial[i] = r
        if cancelled:
            self._partial.clear()
            return None
        if len(self._partial) < len(self._active):
            return ps_service.TIMED_OUT
        out = np.empty(self._layout.num_elems, np.float32)
        for i in self._active:
            out[self._layout.slice(i)] = self._partial[i][1]
        # The first active shard's local_step labels the assembled gradient
        # (the chief only uses it for logging/staleness bookkeeping; under
        # mixing the per-shard steps can legitimately differ).
        step = self._partial[self._active[0]][0]
        self._partial.clear()
        _OBS_GATHERS.inc()
        return step, out

    def set_min_step(self, step: int) -> None:
        if self._layout.num_shards == 1:
            self._gqs[0].set_min_step(step)
            return
        self._pool.run(
            {i: (lambda i=i: self._gqs[i].set_min_step(step))
             for i in self._active}
        )

    def set_min_step_shard(self, i: int, step: int) -> None:
        """Restore ONE (restarted) shard queue's staleness floor — the
        chief's targeted reseed."""
        if i in self._gqs:
            self._gqs[i].set_min_step(step)

    @property
    def dropped(self) -> int:
        return sum(g.dropped for g in self._gqs.values())

    @property
    def deduped(self) -> int:
        return sum(g.deduped for g in self._gqs.values())

    def cancel(self) -> None:
        self._group.cancel_all()
