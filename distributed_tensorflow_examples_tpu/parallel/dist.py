"""Multi-host bootstrap: the TPU-native distributed runtime layer.

Replaces the reference's control plane (SURVEY.md section 2b D2/D9/D10):
``tf.train.Server`` starting gRPC master/worker services per process, and
``TFConfigClusterResolver`` reading the ``TF_CONFIG`` env JSON.  On TPU the
control plane is JAX's coordination service (``jax.distributed.initialize``
over DCN); the data plane is XLA collectives over ICI and never touches this
module.  What remains host-side:

- cluster resolution: explicit args > ``TF_CONFIG`` (accepted for CLI/env
  compatibility with reference launchers) > TPU-pod auto-detection (on Cloud
  TPU ``jax.distributed.initialize()`` discovers everything itself),
- process identity helpers (``is_chief`` = process 0, the analog of
  ``task_index == 0`` chief election),
- a cross-host barrier (``sync_global_devices``), the ``wait_for_session``
  analog used around checkpoint save/restore fences.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

import jax

log = logging.getLogger("dtx.dist")


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Resolved multi-host identity (the ClusterSpec + task tuple analog)."""

    coordinator_address: str | None  # host:port of process 0
    num_processes: int | None
    process_id: int | None
    source: str  # "args" | "tf_config" | "auto"
    task_type: str | None = None  # TF_CONFIG task type ("worker", "ps", ...)

    @property
    def is_ps_task(self) -> bool:
        """True for TF_CONFIG roles with no seat in the SPMD world (ps,
        evaluator): the process should exit cleanly, like the legacy
        ``--job_name=ps`` path (SURVEY.md section 5.6)."""
        return self.task_type in ("ps", "evaluator")


def resolve_cluster(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> ClusterConfig:
    """Explicit args win; else ``TF_CONFIG`` (TFConfigClusterResolver analog,
    SURVEY.md D9); else leave everything None for TPU-pod auto-detection."""
    if coordinator_address or num_processes is not None or process_id is not None:
        return ClusterConfig(coordinator_address, num_processes, process_id, "args")

    tf_config = os.environ.get("TF_CONFIG")
    if tf_config:
        try:
            cfg = json.loads(tf_config)
            cluster = cfg.get("cluster", {})
            task = cfg.get("task", {})
            workers = list(cluster.get("chief", [])) + list(cluster.get("worker", []))
            if cluster.get("ps"):
                log.warning(
                    "TF_CONFIG lists %d ps tasks: parameter servers are "
                    "obsolete on TPU (variables are mesh-sharded); counting "
                    "only chief/worker tasks as processes.",
                    len(cluster["ps"]),
                )
            task_type = task.get("type")
            index = int(task.get("index", 0))
            if task_type == "worker" and "chief" in cluster:
                index += len(cluster["chief"])
            if workers:
                if task_type not in (None, "chief", "worker"):
                    # ps/evaluator tasks hold no SPMD process id — giving them
                    # one would collide with a real worker's seat.
                    return ClusterConfig(
                        workers[0], len(workers), None, "tf_config", task_type
                    )
                # Coordinator port: reuse the first task's port on its host.
                return ClusterConfig(
                    workers[0], len(workers), index, "tf_config", task_type
                )
        except (ValueError, KeyError) as e:
            log.warning("ignoring malformed TF_CONFIG: %s", e)
    return ClusterConfig(None, None, None, "auto")


_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> ClusterConfig:
    """Start (or join) the coordination service.  Idempotent; single-process
    runs (no cluster info anywhere, 1 host) skip initialization entirely so
    examples work unchanged on one chip."""
    global _initialized
    cfg = resolve_cluster(coordinator_address, num_processes, process_id)
    if _initialized:
        return cfg
    if cfg.is_ps_task:
        log.warning(
            "TF_CONFIG task type %r has no role under SPMD; not joining the "
            "coordination service (caller should exit 0).",
            cfg.task_type,
        )
        return cfg
    if cfg.source == "auto" and not _on_multihost_tpu():
        return cfg  # plain single-process run
    # NOTE: must run before any other JAX call — touching the backend first
    # (even jax.process_count()) would make initialize() raise.
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    _initialized = True
    log.info(
        "distributed runtime up: process %d/%d (source=%s)",
        jax.process_index(),
        jax.process_count(),
        cfg.source,
    )
    return cfg


def _on_multihost_tpu() -> bool:
    """True when Cloud-TPU env vars indicate a MULTI-host pod slice whose
    topology ``jax.distributed.initialize()`` can self-discover.  A single
    hostname (e.g. ``TPU_WORKER_HOSTNAMES=localhost`` on one-host setups) is
    not a cluster."""
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h.strip()]) > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    """Process 0 — the reference's ``task_index == 0`` chief (SURVEY.md T1).
    Under SPMD the chief's only special duties are host-side: writing metrics
    and directing non-sharded checkpoint metadata."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (the ``SessionManager.wait_for_session`` analog:
    everyone reaches ``name`` before anyone proceeds)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


# ----------------------------------------------------------------------------
# Failure detection: peer-heartbeat watchdog (SURVEY.md section 5.3)
# ----------------------------------------------------------------------------

#: Exit code a process uses when the watchdog declares a peer dead.  The
#: supervisor (utils.supervisor) treats any nonzero exit as "restart me";
#: a distinct code makes the cause greppable in task logs.
EXIT_PEER_LOST = 83

_watchdog_thread = None
_watchdog_stop = None


def start_watchdog(
    *,
    interval_s: float = 2.0,
    grace_s: float = 10.0,
    startup_grace_s: float = 120.0,
    on_failure=None,
    _client=None,
    _idx=None,
    _count=None,
):
    """Detect dead peers and fail FAST instead of hanging in a collective.

    The recovery model is the reference's (SURVEY.md section 5.3): crash-
    restart, not elastic.  A restarted worker cannot rejoin a live
    coordination service (the service and all XLA collectives are formed
    over a fixed process set), so the correct behavior when any peer dies
    is: every surviving process exits promptly (``EXIT_PEER_LOST``), the
    per-task supervisor (``utils.supervisor``) relaunches the whole job with
    the same TF_CONFIG, the coordination service re-forms, and training
    auto-resumes from the last checkpoint (TrainSession auto-restore).
    Without this, survivors block forever in the next all-reduce — the gloo/
    ICI collective has no peer-death signal of its own.

    Mechanism: every process overwrites ``dtx/hb/<idx>`` in the coordination
    service's KV store with a local sequence number every ``interval_s``; a
    monitor thread samples all peers every ``grace_s`` and declares any peer
    whose counter stopped advancing dead.  Threads are daemons: a clean exit
    0 needs no teardown.

    A peer whose heartbeat value is ``"done"`` departed CLEANLY (it called
    ``stop_watchdog()``, as ``Experiment.finish`` does) and is never
    declared dead — without this, end-of-job skew between workers larger
    than ``grace_s`` would kill survivors mid-final-checkpoint.  A peer that
    NEVER publishes a first beat within ``startup_grace_s`` (it died between
    joining the coordination service and its first beat, e.g. an init-time
    OOM) is declared dead too — first-beat silence must not be an unbounded
    blind spot.

    ``on_failure(dead: list[int])`` overrides the default ``os._exit``.
    Returns True if started (multi-process with a live client), else False.
    ``_client``/``_idx``/``_count`` are test seams (fake KV client).
    """
    global _watchdog_thread, _watchdog_stop
    import threading
    import time as _time

    if _watchdog_thread is not None:
        return True
    client = (
        _client
        if _client is not None
        else getattr(jax._src.distributed.global_state, "client", None)
    )
    if client is None:
        return False
    idx = jax.process_index() if _idx is None else _idx
    count = jax.process_count() if _count is None else _count
    if count < 2:
        return False
    if grace_s < 3 * interval_s:
        # A grace below ~3 beats would declare live peers dead whenever two
        # monitor samples land inside one beat interval.
        log.warning(
            "watchdog: grace_s=%.1f < 3x interval_s=%.1f; clamping to %.1f",
            grace_s, interval_s, 3 * interval_s,
        )
        grace_s = 3 * interval_s
    stop = threading.Event()

    def _beat():
        seq = 0
        misses = 0
        while not stop.is_set():
            seq += 1
            try:
                client.key_value_set(f"dtx/hb/{idx}", str(seq), allow_overwrite=True)
                misses = 0
            except Exception as e:
                # NEVER stop beating while the process lives: a silently
                # frozen heartbeat makes every peer declare us dead and
                # kills a healthy job.  A service outage longer than the
                # peers' grace does that anyway — but then the supervisor
                # restart is at least the designed response.  (At clean
                # shutdown the stop event ends this loop; at process exit
                # the daemon thread dies with it.)
                misses += 1
                if misses <= 3 or misses % 30 == 0:
                    log.warning(
                        "watchdog: heartbeat publish failed %dx (%s); retrying",
                        misses, e,
                    )
            stop.wait(interval_s)

    def _fail(dead: list[int]):
        log.critical(
            "watchdog: peer heartbeat lost for process(es) %s; exiting %d "
            "for supervisor restart (a dead peer cannot rejoin a live "
            "coordination service — the whole job restarts and auto-resumes "
            "from the last checkpoint).",
            dead,
            EXIT_PEER_LOST,
        )
        os._exit(EXIT_PEER_LOST)

    fail = on_failure or _fail

    def _monitor():
        last: dict[int, str] = {}
        t0 = _time.monotonic()
        misses = 0
        while not stop.is_set():
            stop.wait(grace_s)
            if stop.is_set():
                return
            try:
                pairs = dict(client.key_value_dir_get("dtx/hb/"))
                misses = 0
            except Exception as e:
                # Retry transient KV errors — exiting here would silently
                # disable failure detection for the rest of the run.  Three
                # consecutive failures = service gone (shutdown teardown).
                misses += 1
                if misses >= 3:
                    log.warning(
                        "watchdog: coordination service unreachable 3x (%s); "
                        "monitor disabled", e,
                    )
                    return
                continue
            now = {p: pairs.get(f"dtx/hb/{p}") for p in range(count) if p != idx}
            dead = [
                p
                for p, seq in now.items()
                if seq != "done"
                and (
                    (seq is not None and last.get(p) == seq)
                    or (seq is None and _time.monotonic() - t0 > startup_grace_s)
                )
            ]
            if dead:
                fail(dead)
                return
            last.update({p: s for p, s in now.items() if s is not None})

    _watchdog_stop = stop
    _watchdog_thread = threading.Thread(target=_monitor, daemon=True, name="dtx-watchdog")
    threading.Thread(target=_beat, daemon=True, name="dtx-heartbeat").start()
    _watchdog_thread.start()
    log.info(
        "watchdog up: %d peers, beat %.1fs, grace %.1fs", count - 1, interval_s, grace_s
    )
    return True


def stop_watchdog(*, _client=None, _idx=None) -> None:
    """Stop heartbeating and announce a CLEAN departure to the peers (they
    must not treat this process's silence as a crash).  ``_client``/``_idx``
    are the same test seams as start_watchdog's."""
    global _watchdog_thread, _watchdog_stop
    if _watchdog_stop is not None:
        _watchdog_stop.set()
        client = (
            _client
            if _client is not None
            else getattr(jax._src.distributed.global_state, "client", None)
        )
        if client is not None:
            try:
                idx = jax.process_index() if _idx is None else _idx
                client.key_value_set(f"dtx/hb/{idx}", "done", allow_overwrite=True)
            except Exception:
                pass  # service already torn down
    _watchdog_thread = None
    _watchdog_stop = None
