"""Multi-host bootstrap: the TPU-native distributed runtime layer.

Replaces the reference's control plane (SURVEY.md section 2b D2/D9/D10):
``tf.train.Server`` starting gRPC master/worker services per process, and
``TFConfigClusterResolver`` reading the ``TF_CONFIG`` env JSON.  On TPU the
control plane is JAX's coordination service (``jax.distributed.initialize``
over DCN); the data plane is XLA collectives over ICI and never touches this
module.  What remains host-side:

- cluster resolution: explicit args > ``TF_CONFIG`` (accepted for CLI/env
  compatibility with reference launchers) > TPU-pod auto-detection (on Cloud
  TPU ``jax.distributed.initialize()`` discovers everything itself),
- process identity helpers (``is_chief`` = process 0, the analog of
  ``task_index == 0`` chief election),
- a cross-host barrier (``sync_global_devices``), the ``wait_for_session``
  analog used around checkpoint save/restore fences.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os

import jax

log = logging.getLogger("dtx.dist")


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Resolved multi-host identity (the ClusterSpec + task tuple analog)."""

    coordinator_address: str | None  # host:port of process 0
    num_processes: int | None
    process_id: int | None
    source: str  # "args" | "tf_config" | "auto"
    task_type: str | None = None  # TF_CONFIG task type ("worker", "ps", ...)

    @property
    def is_ps_task(self) -> bool:
        """True for TF_CONFIG roles with no seat in the SPMD world (ps,
        evaluator): the process should exit cleanly, like the legacy
        ``--job_name=ps`` path (SURVEY.md section 5.6)."""
        return self.task_type in ("ps", "evaluator")


def resolve_cluster(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> ClusterConfig:
    """Explicit args win; else ``TF_CONFIG`` (TFConfigClusterResolver analog,
    SURVEY.md D9); else leave everything None for TPU-pod auto-detection."""
    if coordinator_address or num_processes is not None or process_id is not None:
        return ClusterConfig(coordinator_address, num_processes, process_id, "args")

    tf_config = os.environ.get("TF_CONFIG")
    if tf_config:
        try:
            cfg = json.loads(tf_config)
            cluster = cfg.get("cluster", {})
            task = cfg.get("task", {})
            workers = list(cluster.get("chief", [])) + list(cluster.get("worker", []))
            if cluster.get("ps"):
                log.warning(
                    "TF_CONFIG lists %d ps tasks: parameter servers are "
                    "obsolete on TPU (variables are mesh-sharded); counting "
                    "only chief/worker tasks as processes.",
                    len(cluster["ps"]),
                )
            task_type = task.get("type")
            index = int(task.get("index", 0))
            if task_type == "worker" and "chief" in cluster:
                index += len(cluster["chief"])
            if workers:
                if task_type not in (None, "chief", "worker"):
                    # ps/evaluator tasks hold no SPMD process id — giving them
                    # one would collide with a real worker's seat.
                    return ClusterConfig(
                        workers[0], len(workers), None, "tf_config", task_type
                    )
                # Coordinator port: reuse the first task's port on its host.
                return ClusterConfig(
                    workers[0], len(workers), index, "tf_config", task_type
                )
        except (ValueError, KeyError) as e:
            log.warning("ignoring malformed TF_CONFIG: %s", e)
    return ClusterConfig(None, None, None, "auto")


_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> ClusterConfig:
    """Start (or join) the coordination service.  Idempotent; single-process
    runs (no cluster info anywhere, 1 host) skip initialization entirely so
    examples work unchanged on one chip."""
    global _initialized
    cfg = resolve_cluster(coordinator_address, num_processes, process_id)
    if _initialized:
        return cfg
    if cfg.is_ps_task:
        log.warning(
            "TF_CONFIG task type %r has no role under SPMD; not joining the "
            "coordination service (caller should exit 0).",
            cfg.task_type,
        )
        return cfg
    if cfg.source == "auto" and not _on_multihost_tpu():
        return cfg  # plain single-process run
    # NOTE: must run before any other JAX call — touching the backend first
    # (even jax.process_count()) would make initialize() raise.
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator_address,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )
    _initialized = True
    log.info(
        "distributed runtime up: process %d/%d (source=%s)",
        jax.process_index(),
        jax.process_count(),
        cfg.source,
    )
    return cfg


def _on_multihost_tpu() -> bool:
    """True when Cloud-TPU env vars indicate a MULTI-host pod slice whose
    topology ``jax.distributed.initialize()`` can self-discover.  A single
    hostname (e.g. ``TPU_WORKER_HOSTNAMES=localhost`` on one-host setups) is
    not a cluster."""
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return True
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hostnames.split(",") if h.strip()]) > 1


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_chief() -> bool:
    """Process 0 — the reference's ``task_index == 0`` chief (SURVEY.md T1).
    Under SPMD the chief's only special duties are host-side: writing metrics
    and directing non-sharded checkpoint metadata."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (the ``SessionManager.wait_for_session`` analog:
    everyone reaches ``name`` before anyone proceeds)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
