"""Membership leases: the elastic-cluster live set (r14).

The reference's cluster is STATIC — ``--worker_hosts`` is fixed at launch
and a process set change means a full restart (the TensorFlow paper's
dynamic-cluster story, PAPERS.md arxiv 1605.08695, is the capability this
module adds; the tf.data-service elasticity argument, arxiv 2210.14826,
applies it to every role, not just input workers).  Here the COORDINATOR
PS shard hosts a lease registry (``wire.PS_OPS`` ``LEASE_*`` ops, served
by ``native/ps_server.cc``): every elastic member — async worker, serve
replica — ACQUIREs a lease naming itself and renews it on a heartbeat, so
the chief, the data service and ``tools/dtxtop.py`` learn the live set
from the registry instead of static flags:

- a worker started MID-RUN acquires a lease, pulls the current params and
  contributes gradients with no restart of anything else (its dedup
  stream is announced via the existing ``*_RESET_WORKER`` ops);
- an EXPIRED lease (member died without releasing) is the membership-
  level stale signal: :class:`LeaseWatcher` surfaces it so the data
  service can reassign the member's in-flight splits immediately instead
  of waiting out its own liveness window, while the member's in-flight
  gradient pushes stay dedup-safe/staleness-dropped exactly as before
  (at-most-once, nothing new to clean up);
- a RELEASED lease is the clean-departure signal (``leave`` semantics) —
  counted separately from expiry, so churn dashboards can tell crashes
  from scale-down.

Leases are liveness state, deliberately NOT replicated (not forwarded,
not in the REPL_SYNC blob): after a PS failover the next heartbeat
re-acquires on the survivor within one TTL — the same self-healing
posture as step tokens.

Fault-plan role: membership connections run under ``<role>_lm`` so
``DTX_FAULT_PLAN`` specs can target the heartbeat/watcher legs without
firing on a process's data-path clients (the ``_pf``/``_ds``/``_sv``
convention; see tests/test_faults.py for the matrix run).  These clients
opt INTO counting control ops as fault points
(``control_ops_are_fault_points=True``): the lease stream is their whole
logical traffic, whereas every other client skips control ops in its op
index (wire.CONTROL_OPS) so plan indices track data-plane ops only.
"""

from __future__ import annotations

import threading
import time

from ..utils import faults, telemetry
from . import ps_service, tenancy

#: LEASE_ACQUIRE statuses (native/ps_server.cc contract).
LEASE_NEW = 1  # newly acquired — fresh member, or re-acquire after expiry
LEASE_RENEWED = 2  # renewal of a live lease

#: Field separator inside the packed member string.  The server treats the
#: whole string as opaque; only this module assigns it structure.
_SEP = "|"

_OBS_RENEWALS = telemetry.REGISTRY.counter("membership/renewals")
_OBS_LAPSES = telemetry.REGISTRY.counter("membership/lapses")
_OBS_HB_ERRORS = telemetry.REGISTRY.counter("membership/heartbeat_errors")


def pack_member(
    member: str, kind: str = "", addr: str = "",
    tenant: str = tenancy.DEFAULT_TENANT,
) -> str:
    """The wire form of a member identity: ``member|kind|addr``.  ``kind``
    is the role family (``worker``, ``serve``, ...); ``addr`` is the
    member's dialable ``host:port`` when it serves one ('' for pure
    clients like workers).  A non-default ``tenant`` (r20) scopes the
    member field itself (``t.<tenant>.<member>`` via tenancy.qualify) —
    the registry stays one flat opaque-string space, tenancy rides the
    identity exactly like PS object keys, and the default tenant's packed
    form is byte-identical to the pre-tenant wire.  Fields must be
    printable ASCII without ``|``/``"``/``\\`` — the server emits the
    string into LEASE_LIST JSON verbatim, so a malformed identity must
    fail HERE, loudly."""
    member = tenancy.qualify(tenant, member)
    for field, what in ((member, "member"), (kind, "kind"), (addr, "addr")):
        # isprintable() additionally rejects control bytes (\n, \t, NUL —
        # e.g. a role leaked from a shell with a trailing newline): the
        # server would refuse them with the same -2 a pre-r14 server
        # answers, and the heartbeat would misdiagnose a version mismatch.
        if (
            any(c in field for c in (_SEP, '"', "\\"))
            or not field.isascii()
            or not field.isprintable()
        ):
            raise ValueError(
                f"lease {what} {field!r} must be printable ASCII without "
                f"{_SEP!r}, quotes or backslashes"
            )
    if not member:
        raise ValueError("lease member id must be non-empty")
    packed = f"{member}{_SEP}{kind}{_SEP}{addr}"
    if len(packed) > 200:
        # The server refuses oversized names with the same -2 a pre-r14
        # server answers — fail HERE instead, with the real reason.
        raise ValueError(
            f"packed member identity is {len(packed)} bytes (> 200): "
            f"{packed[:60]!r}…"
        )
    return packed


def member_index(member: str) -> int | None:
    """The numeric task index off a member id's TRAILING digit run
    (``worker3`` -> 3, ``w2-worker13`` -> 13; None without one) — the ONE
    member-id-to-worker-index inverse every consumer (the data service's
    lease watcher, loadsim's join scheduler) uses."""
    i = len(member)
    while i > 0 and member[i - 1].isdigit():
        i -= 1
    return int(member[i:]) if i < len(member) else None


def unpack_addr(addr: str) -> tuple[str, int] | None:
    """Decode a member's dialable ``host:port`` into an address tuple
    (None when the member carries no valid address) — the ONE inverse of
    the ``addr`` field every discovery consumer uses."""
    host, _, port_s = addr.rpartition(":")
    if host and port_s.isdigit():
        return host, int(port_s)
    return None


def coordinator_addrs(
    ps_addrs, num_shards: int, num_replicas: int = 1,
) -> list[tuple[str, int]]:
    """The COORDINATOR shard's replica address list out of a replica-major
    ``--ps_hosts`` list (replica r of shard 0 = entry ``r * num_shards``)
    — the only servers that host the lease registry."""
    ps_addrs = list(ps_addrs)
    n = max(1, int(num_shards))
    return [
        ps_addrs[r * n]
        for r in range(max(1, int(num_replicas)))
        if r * n < len(ps_addrs)
    ]


def unpack_member(name: str) -> dict:
    """Inverse of :func:`pack_member`; tolerates a bare (unstructured)
    member string from foreign acquirers.  The tenant scope (r20) is
    split back off the member field: ``member`` is always the BARE id
    (trailing-digit ``member_index`` and split-reassignment consumers
    never see the prefix) and ``tenant`` names its namespace."""
    parts = name.split(_SEP)
    tenant, member = tenancy.split_qualified(parts[0])
    return {
        "member": member,
        "tenant": tenant,
        "kind": parts[1] if len(parts) > 1 else "",
        "addr": parts[2] if len(parts) > 2 else "",
    }


def parse_leases(
    doc: dict, kind: str | None = None, tenant: str | None = None,
) -> list[dict]:
    """The parsed live set from a ``PSClient.lease_list()`` document:
    member identity fields plus the registry's ttl/age/renewal numbers,
    optionally filtered to one role family and/or one tenant (None = all
    tenants — the observability scrape; a tenant-scoped consumer passes
    its own so another tenant's members are invisible to it)."""
    out = []
    for entry in doc.get("leases", []):
        m = unpack_member(entry.get("m", ""))
        if kind is not None and m["kind"] != kind:
            continue
        if tenant is not None and m["tenant"] != tenant:
            continue
        m.update(
            ttl_ms=int(entry.get("ttl_ms", 0)),
            age_ms=int(entry.get("age_ms", 0)),
            renewals=int(entry.get("renewals", 0)),
        )
        out.append(m)
    return out


def live_members(
    client: ps_service.PSClient, kind: str | None = None,
    tenant: str | None = None,
) -> list[dict]:
    """One registry scrape over an existing client."""
    return parse_leases(client.lease_list(), kind, tenant)


def membership_role(role: str | None = None) -> str:
    """The fault role membership connections run under: ``<role>_lm``."""
    return (role or faults.current_role() or "member") + "_lm"


class LeaseHeartbeat:
    """Owns one membership connection to the coordinator shard and renews
    this member's lease every ``ttl_s / 3`` (so two missed heartbeats
    still keep the lease alive).

    Contract:

    - the FIRST acquire runs in the constructor (bounded by the client's
      own deadlines), so a member is visible in the registry before it
      starts contributing;
    - a pre-r14 coordinator (LEASE ops answer -2) DISABLES the heartbeat
      loudly (one log line; ``enabled`` False) instead of failing the
      member — elasticity degrades to the static posture, nothing else
      changes;
    - a renewal answered ``LEASE_NEW`` means the lease LAPSED between
      heartbeats (PS outage past the TTL, or a failover that lost the
      volatile registry): counted in ``lapses`` and re-acquired — the
      member may have been treated as departed meanwhile (splits
      reassigned), which the dedup/staleness machinery makes harmless;
    - transient transport faults heal inside the owned ``PSClient``;
      terminal errors (budget exhausted) are counted and retried next
      tick — membership must never take the member down;
    - ``close()`` RELEASES the lease (best effort, fail-fast): the clean
      ``leave`` signal, distinguishable from expiry in the registry's
      churn counters.
    """

    def __init__(
        self,
        addrs: list[tuple[str, int]],
        member: str,
        *,
        kind: str = "",
        addr: str = "",
        ttl_s: float = 10.0,
        role: str | None = None,
        op_timeout_s: float | None = 5.0,
        reconnect_deadline_s: float = 30.0,
        tenant: str = tenancy.DEFAULT_TENANT,
    ):
        self.name = pack_member(member, kind, addr, tenant=tenant)
        self.member = member
        self.tenant = tenant
        self.ttl_s = max(0.3, float(ttl_s))
        self.role = membership_role(role)
        self.enabled = True
        self.renewals = 0
        self.lapses = 0
        self.errors = 0
        self._stop = threading.Event()
        self._client = ps_service.PSClient(
            addrs[0][0], addrs[0][1], op_timeout_s=op_timeout_s,
            reconnect_deadline_s=reconnect_deadline_s, role=self.role,
            addrs=list(addrs) if len(addrs) > 1 else None,
            control_ops_are_fault_points=True,
        )
        try:
            self._client.lease_acquire(self.name, self.ttl_s)
        except ps_service.PSDeadlineError:
            # Coordinator merely UNREACHABLE right now (e.g. mid-failover
            # while this member restarts): keep the heartbeat running —
            # the next tick retries and acquires once the PS is back.  A
            # transient outage must never permanently hide the member.
            self.errors += 1
            _OBS_HB_ERRORS.inc()
        except ps_service.PSError:
            # Genuine rejection (-2): pre-r14 coordinator — static
            # membership, loudly.
            self.enabled = False
            faults.log_event(
                "lease_disabled", role=self.role, member=member,
                reason="coordinator_rejects_lease_ops",
            )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"dtx-lease-{member}"
        )
        if self.enabled:
            self._thread.start()

    def retarget(self, addrs: list[tuple[str, int]]) -> None:
        """Move the heartbeat onto a NEW coordinator (r15 live resharding:
        the registry lives on the new layout's shard 0 after a commit).
        The fresh client acquires immediately — the member is visible in
        the new registry before this returns — and the old client is
        closed; an in-flight tick racing the swap fails once on the dead
        client (counted in ``errors``) and renews on the new one next
        period."""
        if not self.enabled:
            return
        new = ps_service.PSClient(
            addrs[0][0], addrs[0][1],
            op_timeout_s=self._client._op_timeout,
            reconnect_deadline_s=self._client._reconnect_deadline,
            role=self.role,
            addrs=list(addrs) if len(addrs) > 1 else None,
            control_ops_are_fault_points=True,
        )
        try:
            new.lease_acquire(self.name, self.ttl_s)
        except (ps_service.PSError, OSError):
            self.errors += 1
            _OBS_HB_ERRORS.inc()  # next tick retries on the new client
        old, self._client = self._client, new
        old.close()
        faults.log_event(
            "lease_retargeted", role=self.role, member=self.member,
            coordinator=f"{addrs[0][0]}:{addrs[0][1]}",
        )

    def _loop(self) -> None:
        period = self.ttl_s / 3.0
        while not self._stop.wait(period):
            try:
                status = self._client.lease_acquire(self.name, self.ttl_s)
            except (ps_service.PSError, OSError):
                self.errors += 1
                _OBS_HB_ERRORS.inc()
                continue
            self.renewals += 1
            _OBS_RENEWALS.inc()
            if status == LEASE_NEW:
                # The lease lapsed between heartbeats — the registry (or
                # the whole coordinator) lost us and we just rejoined.
                self.lapses += 1
                _OBS_LAPSES.inc()
                faults.log_event(
                    "lease_lapsed_reacquired", role=self.role,
                    member=self.member,
                )

    def close(self) -> None:
        """Stop heartbeating and RELEASE the lease (clean departure)."""
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=self.ttl_s)
        self._client.fail_fast()
        if self.enabled:
            try:
                self._client.lease_release(self.name)
            except (ps_service.PSError, OSError):
                pass  # the TTL expires us; departure degrades to a lapse
        self._client.close()


class LeaseWatcher:
    """Polls the lease registry and surfaces membership TRANSITIONS:
    ``on_join(member_dict)`` for a member that appeared, ``on_leave
    (member_dict)`` for one that disappeared (expired OR released).  The
    data service uses the leave edge to reassign a departed worker's
    in-flight splits immediately; dtxtop uses the live set to discover
    dynamically-joined roles.  Scrape failures are tolerated (the
    registry may be failing over): no transition is synthesized from a
    failed poll — a missing answer is not evidence of a missing member.

    ``follow_epoch`` (r15 live resharding): each poll additionally asks
    the coordinator for a newer COMMITTED layout epoch (O(header) while
    unchanged) and re-targets the watcher onto the new topology's
    coordinator when one lands — so a data service (or any registry
    consumer) keeps seeing the live set across an N→M reshard without
    restarting.  No membership transition is synthesized from the swap
    itself: members re-acquire on the new coordinator within one TTL,
    and the watcher's known set carries across."""

    def __init__(
        self,
        addrs: list[tuple[str, int]],
        *,
        kind: str | None = None,
        poll_s: float = 1.0,
        on_join=None,
        on_leave=None,
        role: str | None = None,
        op_timeout_s: float | None = 5.0,
        reconnect_deadline_s: float = 10.0,
        follow_epoch: bool = False,
        layout_version: int = 0,
        tenant: str | None = None,
    ):
        self.kind = kind
        # Tenant scope (r20): None = watch ALL tenants (the observability
        # posture, and the pre-tenant behavior); a tenant id restricts the
        # live set to that namespace — members of other tenants never
        # produce join/leave edges here, which is what keeps one tenant's
        # churn from triggering another tenant's split reassignment.
        self.tenant = tenant
        self.poll_s = max(0.05, float(poll_s))
        self.on_join = on_join
        self.on_leave = on_leave
        self.role = membership_role(role)
        self.joins_seen = 0
        self.leaves_seen = 0
        self.poll_errors = 0
        self.follow_epoch = bool(follow_epoch)
        self.epoch = int(layout_version)
        self.epoch_swaps = 0
        self._op_timeout_s = op_timeout_s
        self._reconnect_deadline_s = max(0.1, reconnect_deadline_s)
        self._known: dict[str, dict] = {}
        self._stop = threading.Event()
        # A positive reconnect budget is load-bearing: a fail-fast client
        # would never redial after the first coordinator drop (a PS
        # restart is routine) and the watcher would silently stop
        # tracking membership for the rest of the run.
        self._client = ps_service.PSClient(
            addrs[0][0], addrs[0][1], op_timeout_s=op_timeout_s,
            reconnect_deadline_s=max(0.1, reconnect_deadline_s),
            role=self.role,
            addrs=list(addrs) if len(addrs) > 1 else None,
            control_ops_are_fault_points=True,
        )
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dtx-lease-watch"
        )
        self._thread.start()

    def members(self) -> list[dict]:
        """The last successfully scraped live set."""
        return list(self._known.values())

    def _follow_epoch_once(self) -> None:
        """One committed-epoch probe; on a bump, re-dial the NEW
        coordinator (the registry moved with the layout)."""
        from . import reshard

        try:
            rec = reshard.poll_committed(self._client, self.epoch)
        except (ps_service.PSError, OSError, ValueError):
            return  # coordinator mid-failover / garbled record: next poll
        if rec is None:
            return
        addrs = reshard.coordinator_addrs_of(rec)
        try:
            new_client = ps_service.PSClient(
                addrs[0][0], addrs[0][1], op_timeout_s=self._op_timeout_s,
                reconnect_deadline_s=self._reconnect_deadline_s,
                role=self.role,
                addrs=list(addrs) if len(addrs) > 1 else None,
                control_ops_are_fault_points=True,
            )
        except (ps_service.PSError, OSError):
            return  # new coordinator not dialable yet: retry next poll
        old, self._client = self._client, new_client
        old.close()
        self.epoch = rec["version"]
        self.epoch_swaps += 1
        faults.log_event(
            "lease_watcher_retargeted", role=self.role, epoch=self.epoch,
            coordinator=f"{addrs[0][0]}:{addrs[0][1]}",
        )

    def poll_once(self) -> None:
        """One scrape + transition dispatch (the loop body; callable from
        tests for deterministic sequencing)."""
        if self.follow_epoch:
            self._follow_epoch_once()
        try:
            # Keyed by (tenant, member): two tenants may both run a
            # "worker0" and must not shadow each other in the known set.
            live = {
                (m["tenant"], m["member"]): m
                for m in live_members(self._client, self.kind, self.tenant)
            }
        except (ps_service.PSError, OSError):
            self.poll_errors += 1
            return
        prev, self._known = self._known, live  # callbacks see the NEW set
        joined = [m for n, m in live.items() if n not in prev]
        left = [m for n, m in prev.items() if n not in live]
        for m in joined:
            self.joins_seen += 1
            faults.log_event(
                "member_joined", role=self.role, member=m["member"],
                kind=m["kind"],
            )
            if self.on_join is not None:
                self.on_join(m)
        for m in left:
            self.leaves_seen += 1
            faults.log_event(
                "member_left", role=self.role, member=m["member"],
                kind=m["kind"],
            )
            if self.on_leave is not None:
                self.on_leave(m)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.poll_once()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._client.close()
