"""Named-axis collectives: the TPU-native replacement for the reference's
native communication layer.

The reference's collective stack is hand-written C++ — ring all-reduce
(``ring_reducer.h``), ring gather, hierarchical broadcast, permuter, NCCL
bindings, plus a gRPC Send/Recv rendezvous data plane (SURVEY.md section 2b,
D10/D11).  On TPU every one of those algorithms is *emitted by XLA* and
scheduled onto ICI links; almost all of the framework therefore never calls a
collective by name — the sharded ``jit`` train step (train/step.py) makes
GSPMD insert the all-reduces/gathers/reduce-scatters that the reference's
C++ performs (verified at the HLO level by tests/test_hlo_sharding.py).

Role mapping (reference C++ -> TPU-native):
- ring_reducer.h / NcclAllReduce   -> GSPMD all-reduce from the sharded step
- ring_gatherer.h                  -> GSPMD all-gather from sharding constraints
- reduce-scatter ring phase        -> GSPMD reduce-scatter likewise
- permuter.h                       -> ``ring_permute`` below (hand-scheduled
                                      ring attention is the one consumer that
                                      genuinely needs an explicit schedule)
- hierarchical_tree_broadcaster.h  -> jax.device_put / GSPMD replication

This module keeps only the vocabulary that hand-scheduled ``shard_map`` code
actually consumes (ops/attention.py ring, models/transformer.py flash
sharding); everything XLA emits automatically was deliberately removed rather
than exporting dead parity shims.
"""

from __future__ import annotations

import jax
from jax import lax


def axis_index(axis_name: str):
    """This device's position along the named mesh axis."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    """Number of devices along the named mesh axis."""
    return lax.axis_size(axis_name)


def ring_permute(x, axis_name: str, *, shift: int = 1):
    """Send to the neighbor ``shift`` hops around the axis ring; the building
    block of ring attention / pipelined collectives (permuter.h role).  XLA
    lowers ``ppermute`` to neighbor ICI transfers."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def shard_map(fn, mesh, *, in_specs, out_specs, check_vma: bool = False):
    """Project-standard wrapper over ``jax.shard_map`` (manual SPMD regions)."""
    return jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
    )
