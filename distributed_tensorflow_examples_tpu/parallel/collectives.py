"""Named-axis collectives: the TPU-native replacement for the reference's
native communication layer.

The reference's collective stack is hand-written C++ — ring all-reduce
(``ring_reducer.h``), ring gather, hierarchical broadcast, permuter, NCCL
bindings, plus a gRPC Send/Recv rendezvous data plane (SURVEY.md section 2b,
D10/D11).  On TPU every one of those algorithms is *emitted by XLA* from a
named-axis primitive and scheduled onto ICI links; this module is the thin,
documented vocabulary used inside ``shard_map``-decorated code.  Outside
``shard_map``, plain ``jit`` over sharded arrays makes XLA insert these
automatically — prefer that; reach for explicit collectives only when
hand-scheduling (ring attention, async-PS emulation).

Mapping (reference C++ -> here):
- ring_reducer.h / NcclAllReduce      -> ``all_reduce`` / ``all_reduce_mean``
- ring_gatherer.h                     -> ``all_gather``
- hierarchical_tree_broadcaster.h     -> ``broadcast``
- permuter.h                          -> ``ring_permute``
- all_to_all.h / NcclAllToAll         -> ``all_to_all``
- reduce-scatter phase of ring        -> ``reduce_scatter``
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(x, axis_name: str):
    """Sum over the named mesh axis (XLA ``cross_replica_sum`` over ICI)."""
    return lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str):
    """Mean over the axis — the gradient-averaging step that the reference's
    ``SyncReplicasOptimizer`` performs on accumulated grads (SURVEY.md D5)."""
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, scatter_axis: int = 0, tiled: bool = True):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=tiled)


def all_to_all(x, axis_name: str, *, split_axis: int, concat_axis: int, tiled: bool = True):
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return lax.axis_size(axis_name)


def broadcast(x, axis_name: str, root: int = 0):
    """Everyone receives ``root``'s value.  XLA lowers this to its tree/ring
    broadcast — the hierarchical_tree_broadcaster.h role."""
    src = lax.axis_index(axis_name) == root
    zeros = jnp.zeros_like(x)
    return lax.psum(jnp.where(src, x, zeros), axis_name)


def ring_permute(x, axis_name: str, *, shift: int = 1):
    """Send to the neighbor ``shift`` hops around the axis ring; the building
    block of ring attention / pipelined collectives (permuter.h role)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def shard_map(fn, mesh, in_specs, out_specs, *, check_vma: bool = False):
    """Project-standard wrapper over ``jax.shard_map`` (manual SPMD regions)."""
    return jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
    )
