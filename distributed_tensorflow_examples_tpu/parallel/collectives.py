"""Named-axis collectives: the TPU-native replacement for the reference's
native communication layer.

The reference's collective stack is hand-written C++ — ring all-reduce
(``ring_reducer.h``), ring gather, hierarchical broadcast, permuter, NCCL
bindings, plus a gRPC Send/Recv rendezvous data plane (SURVEY.md section 2b,
D10/D11).  On TPU every one of those algorithms is *emitted by XLA* and
scheduled onto ICI links; almost all of the framework therefore never calls a
collective by name — the sharded ``jit`` train step (train/step.py) makes
GSPMD insert the all-reduces/gathers/reduce-scatters that the reference's
C++ performs (verified at the HLO level by tests/test_hlo_sharding.py).

Role mapping (reference C++ -> TPU-native):
- ring_reducer.h / NcclAllReduce   -> GSPMD all-reduce from the sharded step
- ring_gatherer.h                  -> GSPMD all-gather from sharding constraints
- reduce-scatter ring phase        -> GSPMD reduce-scatter likewise
- permuter.h                       -> ``ring_permute`` below (hand-scheduled
                                      ring attention is the one consumer that
                                      genuinely needs an explicit schedule)
- hierarchical_tree_broadcaster.h  -> jax.device_put / GSPMD replication

This module keeps only the vocabulary that hand-scheduled ``shard_map`` code
actually consumes (ops/attention.py ring, models/transformer.py flash
sharding); everything XLA emits automatically was deliberately removed rather
than exporting dead parity shims.
"""

from __future__ import annotations

import jax
from jax import lax


def axis_index(axis_name: str):
    """This device's position along the named mesh axis."""
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    """Number of devices along the named mesh axis.

    Version shim: ``lax.axis_size`` is newer jax; older releases use the
    canonical constant-folding idiom ``psum(1, axis)`` (a python-int
    reduction, resolved statically at trace time)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def ring_permute(x, axis_name: str, *, shift: int = 1):
    """Send to the neighbor ``shift`` hops around the axis ring; the building
    block of ring attention / pipelined collectives (permuter.h role).  XLA
    lowers ``ppermute`` to neighbor ICI transfers."""
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


#: Whether this jax ships native partial-manual shard_map
#: (``jax.shard_map`` with ``axis_names``).  False = the experimental API,
#: where :func:`shard_map` lowers partial-manual regions to FULL-manual
#: (see below) — bodies must then skip auto-axis sharding CONSTRAINTS
#: (there are no auto axes left to constrain, and the old API provides no
#: mesh context for bare PartitionSpecs inside the region).
PARTIAL_MANUAL_NATIVE = hasattr(jax, "shard_map")


def shard_map(
    fn, mesh, *, in_specs, out_specs, check_vma: bool = False,
    axis_names=None,
):
    """Project-standard wrapper over ``jax.shard_map`` (manual SPMD regions).

    Version shim: ``jax.shard_map`` (with ``check_vma`` and
    ``axis_names``) graduated from ``jax.experimental.shard_map`` — where
    the same knobs are ``check_rep`` and the COMPLEMENT set ``auto`` —
    so resolve whichever this jax ships.  This wrapper is the ONE place
    that difference lives; nothing else in the project may call the jax
    symbol directly.  ``axis_names``: mesh axes the region is manual
    over (None = all of them)."""
    if hasattr(jax, "shard_map"):
        kw = {} if axis_names is None else {"axis_names": set(axis_names)}
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma, **kw,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    if axis_names is not None and frozenset(axis_names) != frozenset(
        mesh.axis_names
    ):
        # Old jax spells partial-manual as the complement set ``auto=``,
        # but that path hard-ABORTS the process (jaxlib CHECK failure:
        # spmd_partitioner IsManualSubgroup mismatch) on the CPU
        # interpret configs our tests run — so partial-manual lowers to a
        # FULL-manual region instead.  Semantics: the would-be-auto axes
        # become manual with their in/out specs unchanged, i.e. any array
        # not spec-sharded over them is REPLICATED there and each of
        # their mesh coordinates computes the region redundantly (one
        # independent copy per coordinate) — identical results for the
        # deterministic bodies this project writes, at the cost of the
        # GSPMD sharding the auto axes would have inserted inside the
        # body.  The one thing that must not leak through: a spec naming
        # a would-be-auto axis relies on GSPMD resharding semantics this
        # translation cannot reproduce — refuse that loudly.
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)

        def _spec_axes(spec):
            for part in spec:
                if part is None:
                    continue
                yield from (part if isinstance(part, tuple) else (part,))

        named = {
            ax
            for spec in list(in_specs) + [out_specs]
            for ax in _spec_axes(spec)
        }
        if named & auto:
            raise NotImplementedError(
                f"partial-manual shard_map with specs naming auto axes "
                f"{sorted(named & auto)} requires jax.shard_map; this jax "
                "only ships the experimental API"
            )
    return _shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
