"""Shared wire machinery for the cross-process services (r8 satellite).

Both socket services — the PS state service client (``parallel/ps_service.py``
-> ``native/ps_server.cc``) and the disaggregated data service
(``data/data_service.py``) — speak the same frame layout, the same HELLO
version negotiation, and the same zero-copy send/receive discipline.  This
module is the ONE definition of those pieces, factored out of ``ps_service``
so the two services cannot drift:

- **Frame layout** — request: ``<BB`` (op, name_len) + name bytes + ``<qqI``
  (a, b, payload_len); response: ``<qI`` (status, payload_len).  The unit of
  ``payload_len`` is per-service: the PS wire counts ELEMENTS of the
  negotiated dtype (the C++ server's contract), the data wire counts BYTES
  (batches carry mixed-dtype fields).  The layout and the zero-copy paths
  are identical either way.
- **HELLO** (op 26, shared code point) — version+dtype negotiation, sent
  before any payload op can be misparsed.  The data service additionally
  answers a service tag so a client dialing the wrong service fails loudly
  instead of misinterpreting op codes.
- **Zero-copy send** (:func:`send_frames`) — header + payload buffers leave
  via scatter/gather ``sendmsg``; payload bytes are never copied into a
  concatenated request buffer.
- **Zero-copy receive** (:func:`recv_exact`) — ``recv_into`` straight into
  the caller's buffer; no chunk accumulation (the pre-r7 ``bytes +=`` loop
  was O(n²) in payload size), no staging copy.
- **bf16 payload codec** — round-to-nearest-even f32<->bf16 bit-pattern
  conversion, bit-exact with the C++ server's ``f32_to_bf16``.
"""

from __future__ import annotations

import struct

import numpy as np

#: Wire protocol version (must match native/ps_server.cc kWireVersion).
WIRE_VERSION = 2

#: Payload encodings (HELLO dtype codes).  f32 framing is byte-identical
#: to wire v1; bf16 halves payload bytes and REQUIRES a negotiated peer.
WIRE_DTYPES = {"f32": 0, "bf16": 1}

#: The shared HELLO op code (ps_server.cc op 26; the data service reserves
#: the same code point so one negotiation routine serves both wires).
HELLO_OP = 26

# Sharded PS (r9): HELLO's b operand carries the SHARD IDENTITY the client
# expects of the server it dialed — dtype code in bits 0..7, expected shard
# id in bits 8..31, expected shard count in bits 32..55.  A zero count
# means "no expectation" (every pre-r9 client — their b is just the dtype
# code, < 256).  The server answers ``-5 - packed(own identity)`` on a
# mismatch, so a mis-wired dial fails loudly at connect, naming what was
# actually reached, instead of silently serving the wrong slice of the
# parameter vector.
HELLO_SHARD_ID_SHIFT = 8
HELLO_SHARD_COUNT_SHIFT = 32
HELLO_SHARD_MASK = 0xFFFFFF
HELLO_SHARD_MISMATCH = -5


def pack_hello_b(dtype_code: int, shard_id: int = 0, shard_count: int = 0) -> int:
    """HELLO's b operand: dtype + (optional) expected shard identity."""
    return (
        dtype_code
        | ((shard_id & HELLO_SHARD_MASK) << HELLO_SHARD_ID_SHIFT)
        | ((shard_count & HELLO_SHARD_MASK) << HELLO_SHARD_COUNT_SHIFT)
    )


def unpack_shard_mismatch(status: int) -> tuple[int, int]:
    """Decode a ``-5 - packed`` HELLO answer into the SERVER's
    (shard_id, shard_count)."""
    packed = -(status - HELLO_SHARD_MISMATCH)
    return (
        (packed >> HELLO_SHARD_ID_SHIFT) & HELLO_SHARD_MASK,
        (packed >> HELLO_SHARD_COUNT_SHIFT) & HELLO_SHARD_MASK,
    )

#: Request tail after the name bytes: a, b, payload_len.
REQ_TAIL = struct.Struct("<qqI")

#: Response header: status, payload_len.
RESP_HDR = struct.Struct("<qI")


def pack_request(op: int, name: str, a: int, b: int, payload_len: int) -> bytes:
    """The request frame header (everything but the payload)."""
    nm = name.encode()
    return struct.pack("<BB", op, len(nm)) + nm + REQ_TAIL.pack(a, b, payload_len)


def f32_to_bf16(a: np.ndarray) -> np.ndarray:
    """f32 -> bf16 (as uint16 bit patterns), round-to-nearest-even, NaN
    kept quiet — bit-exact with the server's ``f32_to_bf16``.  In-place
    arithmetic plus a cheap ``any()``-guarded NaN fixup: measured ~2x
    faster than a branchless ``np.where`` select, whose extra full-size
    temporaries cost more than the rare-NaN reduction saves."""
    bits = np.ascontiguousarray(a, np.float32).view(np.uint32)
    out32 = bits + np.uint32(0x7FFF)
    out32 += (bits >> np.uint32(16)) & np.uint32(1)
    out32 >>= np.uint32(16)
    out = out32.astype(np.uint16)
    nan = (bits & np.uint32(0x7FFFFFFF)) > np.uint32(0x7F800000)
    if nan.any():
        out[nan] = ((bits[nan] >> np.uint32(16)) | np.uint32(0x0040)).astype(
            np.uint16
        )
    return out


def bf16_to_f32(u16: np.ndarray) -> np.ndarray:
    return (u16.astype(np.uint32) << np.uint32(16)).view(np.float32)


def send_frames(sock, bufs) -> None:
    """Scatter/gather send of a buffer list via ``sendmsg`` — no buffer is
    ever copied into a concatenated message.  Accepts ``bytes``,
    ``memoryview`` and contiguous ndarrays (cast to byte views here;
    ``reshape(-1)`` keeps 0-d scalar arrays — unsized for ``len()`` —
    valid)."""
    out = []
    for b in bufs:
        if isinstance(b, np.ndarray):
            if b.nbytes:
                out.append(memoryview(b.reshape(-1)).cast("B"))
        elif len(b):
            out.append(memoryview(b))
    while out:
        sent = sock.sendmsg(out)
        while out and sent >= len(out[0]):
            sent -= len(out[0])
            out.pop(0)
        if out and sent:
            out[0] = out[0][sent:]


def send_frame(sock, header: bytes, payload: np.ndarray | None) -> None:
    """Header + optional array payload (the PS client's request shape)."""
    if payload is None or payload.size == 0:
        sock.sendall(header)
        return
    send_frames(sock, [header, payload])


def recv_exact(sock, view: memoryview) -> None:
    """Fill ``view`` from the socket via ``recv_into`` — responses land
    directly in their final buffer.  Raises ConnectionError on EOF."""
    pos, n = 0, len(view)
    while pos < n:
        r = sock.recv_into(view[pos:])
        if r == 0:
            raise ConnectionError("peer closed the connection")
        pos += r


def read_request(sock, hdr2: bytearray | None = None):
    """Server-side request parse: returns ``(op, name, a, b, payload_len)``
    with the payload left unread on the socket (the handler decides the
    receive buffer), or None on a clean EOF before a new frame."""
    head = memoryview(hdr2 if hdr2 is not None else bytearray(2))
    try:
        recv_exact(sock, head)
    except ConnectionError:
        return None
    op, nlen = head[0], head[1]
    name = b""
    if nlen:
        nb = bytearray(nlen)
        recv_exact(sock, memoryview(nb))
        name = bytes(nb)
    tail = bytearray(REQ_TAIL.size)
    recv_exact(sock, memoryview(tail))
    a, b, plen = REQ_TAIL.unpack(tail)
    return op, name.decode(), a, b, plen
