"""Shared wire machinery for the cross-process services (r8 satellite).

All three socket services — the PS state service client
(``parallel/ps_service.py`` -> ``native/ps_server.cc``), the disaggregated
data service (``data/data_service.py``) and the model-serving replicas
(``serve/model_server.py``) — speak the same frame layout, the same HELLO
version negotiation, and the same zero-copy send/receive discipline.  This
module is the ONE definition of those pieces, factored out of ``ps_service``
so the services cannot drift:

- **Frame layout** — request: ``<BB`` (op, name_len) + name bytes + ``<qqI``
  (a, b, payload_len); response: ``<qI`` (status, payload_len).  The unit of
  ``payload_len`` is per-service: the PS wire counts ELEMENTS of the
  negotiated dtype (the C++ server's contract), the data and serving wires
  count BYTES (batches carry mixed-dtype fields).  The layout and the
  zero-copy paths are identical either way.
- **HELLO** (op 26, shared code point) — version+dtype negotiation, sent
  before any payload op can be misparsed.  Every service has a SERVICE
  IDENTITY too (r10): clients announce the service they expect in HELLO's
  ``b`` operand (:func:`pack_hello_b` ``service=``), the Python services
  answer through one shared helper (:func:`hello_answer`) that refuses a
  wrong-service dial with a status naming the service actually reached,
  and the shared client-side check (:func:`hello_failure`) turns every
  mismatch into a diagnostic naming BOTH ends.  The native PS server
  ignores the announcement bits (its success answer carries no tag), which
  is itself distinctive: a data/serve client reading a tag-less success
  knows it dialed the PS state service.
- **Zero-copy send** (:func:`send_frames`) — header + payload buffers leave
  via scatter/gather ``sendmsg``; payload bytes are never copied into a
  concatenated request buffer.
- **Zero-copy receive** (:func:`recv_exact`) — ``recv_into`` straight into
  the caller's buffer; no chunk accumulation (the pre-r7 ``bytes +=`` loop
  was O(n²) in payload size), no staging copy.
- **bf16 payload codec** — round-to-nearest-even f32<->bf16 bit-pattern
  conversion, bit-exact with the C++ server's ``f32_to_bf16``.
- **batch codec** (:func:`encode_batch` / :func:`read_batch`) — mixed-dtype
  field dicts as a JSON schema header + raw bytes, scatter/gather out and
  ``recv_into`` straight into the final arrays; shared by the data service
  (training batches) and the serving wire (predict inputs/outputs).
"""

from __future__ import annotations

import json
import struct

import numpy as np

#: Wire protocol version (must match native/ps_server.cc kWireVersion).
#: v3 (r12): the HELLO b-word's shard-identity fields moved (count bits
#: 32..55 -> 20..31, layout version and the repl flag added above them) —
#: the bump makes a v2/v3 HELLO pairing fail loudly (-4) instead of a
#: relocated field silently reading as "no expectation" and disabling the
#: mis-wire guard.  v4 (r18): requests may carry a per-op DEADLINE stamp
#: (op-byte bit 7 = :data:`DEADLINE_FLAG`, a trailing ``<I`` deadline_ms
#: field after the standard tail) and servers may SHED with the
#: :data:`RETRY_LATER_BASE` status band — the bump makes a mixed v3/v4
#: negotiated pairing fail loudly instead of a stamped frame misparsing
#: as an unknown op.  Un-stamped frames stay byte-identical to v3, so
#: HELLO-less connections (plain f32, no expectations) remain
#: version-agnostic, exactly as before.
WIRE_VERSION = 4

#: Payload encodings (HELLO dtype codes).  f32 framing is byte-identical
#: to wire v1; bf16 halves payload bytes and REQUIRES a negotiated peer.
WIRE_DTYPES = {"f32": 0, "bf16": 1}

# ----------------------------------------------------------------------------
# Protocol registries (r11): the ONE Python definition site for every op
# code and service status the three wires speak.  Service modules alias
# these names — they must never restate the numbers.  The native server's
# ``enum Op`` is the C++ mirror of PS_OPS; ``tools/dtxlint``'s
# wire-conformance pass pins the two against each other (names AND
# numbers), checks that every client-sent opcode has a server dispatch case,
# and refuses op/status collisions across services, so a renumbering in
# one place can never silently drift.
# ----------------------------------------------------------------------------

#: PS state-service op codes (native/ps_server.cc ``enum Op``).
PS_OPS: dict[str, int] = {
    "ACC_GET": 1,
    "ACC_APPLY": 2,
    "ACC_TAKE": 3,
    "ACC_SET_STEP": 4,
    "ACC_DROPPED": 5,
    "TQ_GET": 6,
    "TQ_PUSH": 7,
    "TQ_POP": 8,
    "GQ_GET": 9,
    "GQ_PUSH": 10,
    "GQ_POP": 11,
    "GQ_SET_MIN": 12,
    "GQ_DROPPED": 13,
    "CANCEL_ALL": 14,
    "PING": 15,
    "PSTORE_GET_OBJ": 16,
    "PSTORE_SET": 17,
    "PSTORE_GET": 18,
    "INCARNATION": 19,
    "ACC_APPLY_TAGGED": 20,
    "GQ_PUSH_TAGGED": 21,
    "ACC_DEDUPED": 22,
    "GQ_DEDUPED": 23,
    "ACC_RESET_WORKER": 24,
    "GQ_RESET_WORKER": 25,
    "HELLO": 26,
    "PSTORE_GET_IF_NEWER": 27,
    # PS shard replication (r12).  REPL_SYNC: a (re)starting replica pulls
    # its peer's full state (objects, param snapshots, dedup tables,
    # counters, state token) before it starts serving — server-to-server
    # only, over a repl-flagged connection.  REPL_TOKEN: answers the
    # server's STATE TOKEN as the status — the state-lineage id clients
    # compare on reconnect to tell "state intact (failover/resync)" from
    # "state lost (reseed needed)"; a pre-r12 server answers -2 and the
    # client falls back to incarnation-only semantics.
    "REPL_SYNC": 28,
    "REPL_TOKEN": 29,
    # Observability (r13 dtxobs).  STATS: answers the server's whole
    # counter table — shard identity, incarnation/state token, request and
    # connection counts, replication forward/sync/mirror counters, summed
    # dedup/dropped counters — as one raw JSON blob (payload counted in
    # 4-byte units like REPL_SYNC, NEVER dtype-encoded), so one scraper
    # (tools/dtxtop.py) reads a live cluster with zero side channels.
    # All three services carry a STATS op; code points stay disjoint so a
    # mis-wired scrape is refused, never misread.
    "STATS": 30,
    # Membership leases (r14 elasticity).  The coordinator shard hosts a
    # LEASE REGISTRY: every elastic member (async worker, serve replica)
    # ACQUIREs a lease naming itself and renews it on a heartbeat, so the
    # chief, the data service and dtxtop learn the LIVE set from the
    # registry instead of static --worker_hosts.  LEASE_ACQUIRE: name =
    # the member string (``membership.pack_member``), a = ttl_ms; answers
    # 1 (newly acquired — including a re-acquire after the old lease
    # EXPIRED, so a renewing client learns it lapsed) or 2 (renewal of a
    # live lease).  LEASE_RELEASE: the clean-departure signal (1 released
    # / 0 unknown, idempotent).  LEASE_LIST: the live set as one raw JSON
    # blob (4-byte units, dtype-independent, like STATS) — expired
    # entries are pruned at list time and counted.  Leases are liveness
    # state, deliberately NOT replicated (not forwarded, not in the
    # REPL_SYNC blob): after a failover the next heartbeat re-acquires on
    # the survivor within one TTL, the same self-healing posture as
    # tokens.
    "LEASE_ACQUIRE": 31,
    "LEASE_RELEASE": 32,
    "LEASE_LIST": 33,
    # Live resharding (r15).  The COORDINATOR shard stores one RESHARD
    # RECORD per slot — PENDING (a transition being prepared) and
    # COMMITTED (the current layout epoch) — as an opaque raw JSON blob
    # (``parallel/reshard.py`` owns the schema; payloads are raw 4-byte
    # units like STATS, never dtype-encoded).  RESHARD_BEGIN: a = the new
    # epoch version, payload = the record; stores/overwrites the pending
    # slot (idempotent — every joining shard task may announce the same
    # record); refused (-2) for a version not above the committed one.
    # RESHARD_COMMIT: a = version; promotes a matching pending record to
    # committed (idempotent when already committed at that version).
    # RESHARD_GET: a = caller's known version, b = slot (0 committed / 1
    # pending); answers the slot's version as the status (0 = empty) with
    # the record payload only when it is newer than ``a`` — so the
    # steady-state epoch poll every client runs costs O(header), exactly
    # like an unchanged-step PSTORE_GET_IF_NEWER.  RESHARD_ABORT: a =
    # version; clears a matching pending record (1 cleared / 0 nothing) —
    # the loud mid-transition bail-out.  All four are control-plane ops
    # excluded from the request counter (they fire on poll cadence, like
    # STATS/LEASE ops, and must not perturb ``die:after_reqs`` triggers).
    # REPL_SYNC additionally accepts a RANGE (a = start element, b =
    # element count > 0): the slice-ranged state transfer a new-layout
    # shard task assembles its slice from (param-store objects only; see
    # ps_server.cc for the ranged blob layout).
    "RESHARD_BEGIN": 34,
    "RESHARD_COMMIT": 35,
    "RESHARD_GET": 36,
    "RESHARD_ABORT": 37,
}

#: Data-service op codes (data/data_service.py).  Disjoint from the PS
#: range except the shared HELLO code point, so a frame sent to the wrong
#: service is refused, never misinterpreted.
DSVC_OPS: dict[str, int] = {
    "HELLO": 26,
    "REGISTER": 64,
    "GET_SPLIT": 65,
    "CLAIM_SPLIT": 66,
    "GET_BATCH": 67,
    "HEARTBEAT": 68,
    "STATS": 69,
    "GET_EVAL": 70,
    "SHUTDOWN": 71,
}

#: Serving-replica op codes (serve/model_server.py), disjoint from both.
#: DECODE_* (r19) are the STREAM code points of the decode-serving wire:
#: a stateful autoregressive session is OPENed (payload = the prompt
#: batch, ``a`` = max new tokens; the session id answers as the status),
#: then the client PULLS its token stream incrementally — DECODE_NEXT's
#: ``a`` is the session id and ``b`` the client's CURSOR (tokens already
#: received), and the server answers ``emitted[cursor:]`` — so a replayed
#: poll after a reconnect re-reads instead of double-draining (the same
#: replay-safety discipline as pure PREDICT, bought with a cursor instead
#: of purity).  DECODE_CLOSE is idempotent.  All three are DATA-plane ops
#: (counted; a decode session is real served work, not poll cadence).
SRV_OPS: dict[str, int] = {
    "HELLO": 26,
    "PREDICT": 96,
    "STATS": 97,
    "SHUTDOWN": 98,
    "DECODE_OPEN": 99,
    "DECODE_NEXT": 100,
    "DECODE_CLOSE": 101,
}

#: Data-service response statuses.  Positive codes are per-op results
#: (END_OF_SPLIT and CLAIM_DONE deliberately share 1 — they answer
#: different ops); negative codes are the error band and must stay unique.
DSVC_STATUS: dict[str, int] = {
    "OK": 0,
    "END_OF_SPLIT": 1,  # GET_BATCH index past the split; GET_EVAL w/o chunk
    "CLAIM_DONE": 1,  # CLAIM_SPLIT: already completed this epoch
    "CLAIM_TAKEN": 2,  # CLAIM_SPLIT: assigned to another live worker
    "ERR": -2,  # bad op / bad operands / handler failure
    "WAIT": -3,  # GET_SPLIT: nothing pending right now — poll again
    "EPOCH_ROLLED": -4,  # GET_SPLIT: the constrained epoch is over
}

#: Serving-replica response statuses.  PREDICT success answers the served
#: model_step (>= 0) as the status, so only the error band is enumerated.
SRV_STATUS: dict[str, int] = {
    "ERR": -2,  # bad request / failed apply
    "OVERLOAD": -7,  # admission control: queue full, back off / try a peer
    "NO_MODEL": -8,  # replica up but no published snapshot yet (warming)
    "BAD_SESSION": -9,  # DECODE_NEXT/CLOSE: unknown or expired session id
    "NO_DECODER": -10,  # DECODE_OPEN: this replica serves no decode path
}

#: Reserved field name the serving replica stamps into every predict /
#: decode response batch: the REGISTRY MODEL VERSION the answer was served
#: from (r19; 0 = hot-tracking the live training run, no pinned version).
#: The client strips it before handing outputs to the caller, so the
#: version rides next to ``model_step`` with zero schema impact on user
#: fields — pools read it to keep per-version (canary vs stable)
#: latency/error accounting.
SRV_VERSION_FIELD = "__model_version__"

#: msrv HELLO version word (r19): a serving replica's HELLO success answer
#: is its 4-byte service tag PLUS one ``<q`` MODEL VERSION (0 =
#: hot-tracking) — a dialing pool learns which registry version the
#: replica serves before routing a single predict, which is what makes
#: canary-weighted routing work on freshly discovered replicas.  Pre-r19
#: msrv replicas answer the bare tag; clients treat that as version 0.
HELLO_VERSION_TAIL = struct.Struct("<q")


def unpack_hello_tag(payload: bytes | None) -> tuple[bytes | None, int]:
    """Split a Python-service HELLO success payload into ``(tag,
    model_version)``.  A bare 4-byte tag (dsvc, pre-r19 msrv) carries
    version 0; anything else hands the payload back unsplit so
    :func:`hello_failure` names it in the diagnostic."""
    if payload is None:
        return None, 0
    payload = bytes(payload)
    if len(payload) == 4:
        return payload, 0
    if len(payload) == 4 + HELLO_VERSION_TAIL.size:
        return payload[:4], HELLO_VERSION_TAIL.unpack(payload[4:])[0]
    return payload, 0

#: Control-plane ops per service (r16): the ONE definition of which ops
#: are excluded from (a) every server's request counter and (b) the
#: client-side fault-injection op index.  The request counter is the
#: fault layer's deterministic ``die:after_reqs`` trigger and an exported
#: metric; the fault op index is how ``DTX_FAULT_PLAN`` ``op=N`` specs
#: address logical client ops.  Control ops fire on CONNECTION and
#: WALL-CLOCK cadence (handshakes, identity probes, scrapes, heartbeats,
#: epoch polls) — counting them would make both notions drift with dial
#: and poll frequency instead of tracking data-plane progress.  Exclusion
#: sites derive from this dict and NOTHING else: the C++ server's
#: ``kControlOps`` block mirrors CONTROL_OPS["ps"] (pinned both
#: directions by ``tools/dtxlint``'s control pass), the dsvc/msrv counter
#: branches and ``utils/faults``' op-index accounting read it directly.
#: REPL_SYNC is deliberately NOT here: a state transfer is real traffic
#: (one per restart/join), not poll cadence, and it has always counted.
CONTROL_OPS: dict[str, frozenset[str]] = {
    "ps": frozenset({
        "HELLO", "INCARNATION", "REPL_TOKEN", "STATS",
        "LEASE_ACQUIRE", "LEASE_RELEASE", "LEASE_LIST",
        "RESHARD_BEGIN", "RESHARD_COMMIT", "RESHARD_GET", "RESHARD_ABORT",
    }),
    "dsvc": frozenset({"HELLO", "STATS"}),
    "msrv": frozenset({"HELLO", "STATS"}),
}

# Multi-tenancy (r20 dtxtenant): tenancy is a KEY-PREFIX protocol, not a
# new op family — a tenant's PS objects live under ``t.<tenant>.<name>``
# and its lease identities under ``t.<tenant>.<member>``, so v<=4 frames
# from untagged (pre-tenant) clients stay byte-identical and simply land
# in the ``default`` tenant (whose keys carry NO prefix at all).  The
# prefix below is the ONE wire-level definition: ``parallel/tenancy.py``
# builds every qualified key from it, ``native/ps_server.cc`` mirrors it
# as ``kTenantKeyPrefix`` (for the per-tenant STATS breakdown and the
# prefix-filtered CANCEL_ALL), and ``tools/dtxlint``'s tenant pass pins
# the two and refuses prefix construction anywhere else.
TENANT_KEY_PREFIX = "t."

#: PS ops whose ``name`` operand is a TENANT-SCOPED OBJECT KEY — the ops
#: :meth:`ps_service.PSClient.call` qualifies with the caller's tenant
#: prefix.  Everything else (HELLO/STATS/PING/INCARNATION, the lease ops
#: — whose names are member docs, tenant-scoped inside ``pack_member`` —
#: the reshard/replication control surface, and CANCEL_ALL, whose name is
#: a raw prefix FILTER) passes its name through untouched.  Declared as a
#: literal so dtxlint's tenant pass can validate every entry against
#: PS_OPS and pin the qualification site against this set.
TENANT_SCOPED_OPS: dict[str, frozenset[str]] = {
    "ps": frozenset({
        "ACC_GET", "ACC_APPLY", "ACC_TAKE", "ACC_SET_STEP", "ACC_DROPPED",
        "ACC_APPLY_TAGGED", "ACC_DEDUPED", "ACC_RESET_WORKER",
        "TQ_GET", "TQ_PUSH", "TQ_POP",
        "GQ_GET", "GQ_PUSH", "GQ_POP", "GQ_SET_MIN", "GQ_DROPPED",
        "GQ_PUSH_TAGGED", "GQ_DEDUPED", "GQ_RESET_WORKER",
        "PSTORE_GET_OBJ", "PSTORE_SET", "PSTORE_GET", "PSTORE_GET_IF_NEWER",
    }),
}

#: Protocol state machines (r16): the legal op orderings each wire's
#: conversation must respect, declared as pure DATA (dict/list/str
#: literals only) so ``tools/dtxlint``'s protocol pass can both validate
#: the machines (every op real, every state reachable, every transition
#: exercised by some call-site) and lint client call-sites against them.
#: ``aliases`` name the wrapper callables that stand for an op at a
#: call-site (``client.reshard_commit(...)`` IS a RESHARD_COMMIT).
WIRE_PROTOCOLS: dict[str, dict] = {
    # Tagged services: HELLO is the FIRST op on every fresh connection —
    # nothing the peer could misparse may precede the version/service
    # negotiation.  (The native PS accepts HELLO-less f32 connections by
    # design, so "ps" is exempt.)
    "hello-first": {
        "kind": "first_op",
        "services": ["dsvc", "msrv"],
        "op": "HELLO",
    },
    # A reshard transition BEGINs once and then COMMITs or ABORTs — no
    # second BEGIN at the same version, no commit without a pending
    # record.  "pending" self-loops are deliberately absent: a re-BEGIN
    # inside one code block is the half-applied-transition bug class.
    "reshard-transition": {
        "kind": "session",
        "service": "ps",
        "init": "idle",
        "transitions": {
            "idle": {"RESHARD_BEGIN": "pending"},
            "pending": {"RESHARD_COMMIT": "idle", "RESHARD_ABORT": "idle"},
        },
        "aliases": {
            "RESHARD_BEGIN": ["reshard_announce"],
            "RESHARD_COMMIT": ["reshard_commit"],
            "RESHARD_ABORT": ["reshard_abort"],
        },
    },
    # A lease is ACQUIRED (or renewed) before it can be RELEASED.
    "lease-lifecycle": {
        "kind": "session",
        "service": "ps",
        "init": "released",
        "transitions": {
            "released": {"LEASE_ACQUIRE": "held"},
            "held": {"LEASE_ACQUIRE": "held", "LEASE_RELEASE": "released"},
        },
        "aliases": {
            "LEASE_ACQUIRE": ["lease_acquire"],
            "LEASE_RELEASE": ["lease_release"],
        },
    },
    # A layout-epoch joiner assembles its slice from the old tier (ranged
    # REPL_SYNC) BEFORE announcing the pending transition record: a
    # record whose announcer has not synced could be committed against an
    # unassembled shard.
    "sync-before-announce": {
        "kind": "order",
        "service": "ps",
        "first": "REPL_SYNC",
        "then": "RESHARD_BEGIN",
        "aliases": {
            "REPL_SYNC": [
                "ranged_sync", "assemble_slice", "assemble_for_shard",
                "install_assembled", "join_new_shard",
            ],
            "RESHARD_BEGIN": ["reshard_announce"],
        },
    },
}

#: The shared HELLO op code (one code point for every service, so one
#: negotiation routine serves all three wires).
HELLO_OP = PS_OPS["HELLO"]

# Sharded PS (r9, field layout revised r12): HELLO's b operand carries the
# SHARD IDENTITY the client expects of the server it dialed — dtype code in
# bits 0..7, expected shard id in bits 8..19, expected shard count in bits
# 20..31, expected LAYOUT VERSION in bits 32..47 (the shard-topology epoch
# — the plumbing live N->M resharding rides on: mixed-epoch clients fail
# the dial loudly instead of scattering onto the wrong partition), and the
# replication-peer flag at bit 48 (the server-to-server forward/sync
# connection announces itself so mirrors are never re-forwarded and a
# partitioned peer can refuse it by policy).  A zero count/version means
# "no expectation" (every pre-r9 client — their b is just the dtype code,
# < 256 — packs identically).  The server answers ``-5 - packed(own
# identity)`` on a mismatch, so a mis-wired dial fails loudly at connect,
# naming what was actually reached, instead of silently serving the wrong
# slice (or the wrong epoch) of the parameter vector.
HELLO_SHARD_ID_SHIFT = 8
HELLO_SHARD_COUNT_SHIFT = 20
HELLO_SHARD_MASK = 0xFFF
HELLO_LAYOUT_SHIFT = 32
HELLO_LAYOUT_MASK = 0xFFFF
HELLO_REPL_SHIFT = 48
HELLO_SHARD_MISMATCH = -5

# PS replication statuses (r12, native/ps_server.cc parity).  REPL_REFUSED:
# a partitioned server refusing its peer's repl-flagged connection (the
# injected-partition primitive).  REPL_DIVERGED: a replica refusing a
# state-MUTATING client op because it can no longer replicate it (its peer
# refuses the link) — the loud split-brain error; reads still serve.
REPL_REFUSED = -6
REPL_DIVERGED = -7

# Graceful load shedding (r18, native/ps_server.cc parity).  A server that
# ADMISSION-REFUSES a request — dispatch queue full, per-connection
# in-flight cap exceeded, or the request waited past its queue-deadline
# budget — answers a status in the RETRY_LATER band: ``RETRY_LATER_BASE -
# retry_after_ms``, so the shed carries its own backoff HINT with zero
# payload plumbing on any wire (the same pack-into-the-status trick as the
# HELLO shard-mismatch echo).  The band spans ``RETRY_LATER_SPAN`` ms of
# hint below the base; anything below that is NOT a shed (the shard-
# mismatch echoes live around -1M and must never decode as one).  Shed
# answers are RETRYABLE by contract — but only through the shared retry
# budget (``parallel/retry.py``): a client that re-hammers a shedding
# server at line rate is the retry storm admission control exists to
# prevent.  Control-plane ops (wire.CONTROL_OPS) are NEVER shed: under
# saturation the cluster stays observable and leases keep renewing, so
# overload cannot cascade into false member expiry.
RETRY_LATER_BASE = -1000
RETRY_LATER_SPAN = 600_000  # max encodable hint: 10 minutes

#: Request op-byte flag (bit 7; every real op code is < 0x80): the frame's
#: standard tail is followed by one ``<I`` field carrying the caller's
#: REMAINING per-op deadline in ms.  Servers use it to drop work the
#: caller has already abandoned (queue-deadline shed) and to clamp
#: blocking-op waits — a worker never burns on a request whose caller
#: gave up.  Optional per frame: un-stamped frames are byte-identical to
#: the v3 layout.
DEADLINE_FLAG = 0x80
DEADLINE_TAIL = struct.Struct("<I")


def retry_later_status(retry_after_ms: int) -> int:
    """The shed status for a given backoff hint (clamped to the band)."""
    return RETRY_LATER_BASE - max(0, min(int(retry_after_ms), RETRY_LATER_SPAN))


def retry_after_ms(status: int) -> int | None:
    """The backoff hint a RETRY_LATER status carries, or None when
    ``status`` is not a shed (the band check keeps the far-more-negative
    shard-mismatch echoes from ever decoding as one)."""
    if RETRY_LATER_BASE - RETRY_LATER_SPAN <= status <= RETRY_LATER_BASE:
        return RETRY_LATER_BASE - status
    return None

# Service identity (r10): every wire service has an id + a 4-byte tag.  A
# client announces the service it EXPECTS in HELLO's b operand (bits
# 56..62 — above the shard-identity bits, below the sign bit; the native
# PS server masks them out, so announcing is backward-compatible with it);
# the Python services refuse a mismatched announcement with status
# ``WRONG_SERVICE_BASE - own_id`` so the dial fails loudly naming what was
# actually reached.  Successful Python-service HELLOs answer their 4-byte
# tag as payload; the native PS answers tag-less (also distinctive).
SERVICE_IDS = {"ps": 1, "dsvc": 2, "msrv": 3}
SERVICE_TAGS = {"ps": b"psrv", "dsvc": b"dsvc", "msrv": b"msrv"}
SERVICE_NAMES = {
    "ps": "the native PS state service",
    "dsvc": "a data service",
    "msrv": "a model-serving replica",
}
HELLO_SERVICE_SHIFT = 56
HELLO_SERVICE_MASK = 0x7F
WRONG_SERVICE_BASE = -100


def pack_hello_b(
    dtype_code: int, shard_id: int = 0, shard_count: int = 0,
    service: str = "", layout_version: int = 0, repl: bool = False,
) -> int:
    """HELLO's b operand: dtype + (optional) expected shard identity +
    (optional) expected layout version + (optional) replication-peer flag
    + (optional) expected SERVICE identity.  Out-of-range fields are
    REJECTED, never masked: a truncated shard_count/layout_version would
    pack as "no expectation" and silently disable the very guard the
    word exists to enforce."""
    if not 0 <= shard_id <= HELLO_SHARD_MASK or \
            not 0 <= shard_count <= HELLO_SHARD_MASK:
        raise ValueError(
            f"shard identity ({shard_id}/{shard_count}) exceeds the "
            f"{HELLO_SHARD_MASK + 1}-shard HELLO field"
        )
    if not 0 <= layout_version <= HELLO_LAYOUT_MASK:
        raise ValueError(
            f"layout_version {layout_version} exceeds the "
            f"{HELLO_LAYOUT_MASK + 1}-epoch HELLO field"
        )
    return (
        dtype_code
        | (shard_id << HELLO_SHARD_ID_SHIFT)
        | (shard_count << HELLO_SHARD_COUNT_SHIFT)
        | (layout_version << HELLO_LAYOUT_SHIFT)
        | ((1 if repl else 0) << HELLO_REPL_SHIFT)
        | ((SERVICE_IDS[service] if service else 0) << HELLO_SERVICE_SHIFT)
    )


def hello_expected_service(b: int) -> str:
    """The service a HELLO's sender announced it expects ('' = none)."""
    sid = (b >> HELLO_SERVICE_SHIFT) & HELLO_SERVICE_MASK
    for name, i in SERVICE_IDS.items():
        if i == sid:
            return name
    return ""


def wrong_service_status(service: str) -> int:
    return WRONG_SERVICE_BASE - SERVICE_IDS[service]


def unpack_wrong_service(status: int) -> str | None:
    """The service a ``WRONG_SERVICE_BASE``-range HELLO answer names, or
    None when ``status`` is not a wrong-service refusal."""
    sid = WRONG_SERVICE_BASE - status
    for name, i in SERVICE_IDS.items():
        if i == sid:
            return name
    return None


def hello_answer(
    a: int, b: int, *, service: str, accept_dtypes=(0,),
) -> tuple[int, bytes | None]:
    """The shared server-side HELLO answer for the Python services: returns
    ``(status, tag_payload)``.  A client announcing a DIFFERENT service is
    refused with a status naming this one (the wrong-service loud failure);
    a version/dtype mismatch answers -1; success echoes the wire version
    plus this service's 4-byte tag."""
    expected = hello_expected_service(b)
    if expected and expected != service:
        return wrong_service_status(service), None
    if a != WIRE_VERSION or (b & 0xFF) not in accept_dtypes:
        return -1, None
    return WIRE_VERSION, SERVICE_TAGS[service]


def hello_failure(
    status: int, tag: bytes | None, *, service: str, host: str, port: int,
) -> str | None:
    """The shared client-side HELLO verdict: None when ``(status, tag)`` is
    a valid success for ``service``, else a diagnostic naming both ends —
    what this client speaks AND what the peer turned out to be."""
    want = SERVICE_NAMES[service]
    # The success payload is the 4-byte service tag, optionally followed
    # by the msrv HELLO version word (r19) — split before comparing.
    tag4, _version = unpack_hello_tag(tag)
    if status == WIRE_VERSION and tag4 == SERVICE_TAGS[service]:
        return None
    got = unpack_wrong_service(status)
    if got is not None:
        return (
            f"wrong-service dial: {host}:{port} is {SERVICE_NAMES[got]} "
            f"({got!r}), not {want} ({service!r}) — check the host lists "
            "against the running tasks"
        )
    if status == WIRE_VERSION and not tag:
        return (
            f"wrong-service dial: {host}:{port} answered HELLO "
            f"v{WIRE_VERSION} without a service tag — that port hosts the "
            f"native PS state service, not {want} ({service!r})"
        )
    return (
        f"HELLO with {host}:{port} failed: asked v{WIRE_VERSION}/{service}, "
        f"peer answered {status} {tag!r} — not {want}, or an incompatible "
        "version"
    )


def unpack_shard_mismatch(status: int) -> tuple[int, int, int]:
    """Decode a ``-5 - packed`` HELLO answer into the SERVER's
    (shard_id, shard_count, layout_version)."""
    packed = -(status - HELLO_SHARD_MISMATCH)
    return (
        (packed >> HELLO_SHARD_ID_SHIFT) & HELLO_SHARD_MASK,
        (packed >> HELLO_SHARD_COUNT_SHIFT) & HELLO_SHARD_MASK,
        (packed >> HELLO_LAYOUT_SHIFT) & HELLO_LAYOUT_MASK,
    )

#: Request tail after the name bytes: a, b, payload_len.
REQ_TAIL = struct.Struct("<qqI")

#: Response header: status, payload_len.
RESP_HDR = struct.Struct("<qI")


def pack_request(
    op: int, name: str, a: int, b: int, payload_len: int,
    deadline_ms: int = 0,
) -> bytes:
    """The request frame header (everything but the payload).
    ``deadline_ms`` > 0 stamps the caller's remaining per-op deadline
    (r18): the op byte carries :data:`DEADLINE_FLAG` and one ``<I`` field
    follows the standard tail — both ends must speak wire v4."""
    nm = name.encode()
    if deadline_ms > 0:
        return (
            struct.pack("<BB", op | DEADLINE_FLAG, len(nm)) + nm
            + REQ_TAIL.pack(a, b, payload_len)
            + DEADLINE_TAIL.pack(min(int(deadline_ms), RETRY_LATER_SPAN))
        )
    return struct.pack("<BB", op, len(nm)) + nm + REQ_TAIL.pack(a, b, payload_len)


def f32_to_bf16(a: np.ndarray) -> np.ndarray:
    """f32 -> bf16 (as uint16 bit patterns), round-to-nearest-even, NaN
    kept quiet — bit-exact with the server's ``f32_to_bf16``.  In-place
    arithmetic plus a cheap ``any()``-guarded NaN fixup: measured ~2x
    faster than a branchless ``np.where`` select, whose extra full-size
    temporaries cost more than the rare-NaN reduction saves."""
    bits = np.ascontiguousarray(a, np.float32).view(np.uint32)
    out32 = bits + np.uint32(0x7FFF)
    out32 += (bits >> np.uint32(16)) & np.uint32(1)
    out32 >>= np.uint32(16)
    out = out32.astype(np.uint16)
    nan = (bits & np.uint32(0x7FFFFFFF)) > np.uint32(0x7F800000)
    if nan.any():
        out[nan] = ((bits[nan] >> np.uint32(16)) | np.uint32(0x0040)).astype(
            np.uint16
        )
    return out


def bf16_to_f32(u16: np.ndarray) -> np.ndarray:
    return (u16.astype(np.uint32) << np.uint32(16)).view(np.float32)


def _byte_view(a: np.ndarray) -> np.ndarray:
    """Zero-copy uint8 view of a contiguous array.  ``memoryview(...).cast``
    would do for standard dtypes, but PEP 3118 has no format code for
    extension dtypes (ml_dtypes bfloat16 & co. raise ``cannot include
    dtype 'E' in a buffer``) — a uint8 ``view`` moves any itemsize.
    ``reshape(-1)`` keeps 0-d scalar arrays — unsized for ``len()`` —
    valid."""
    return a.reshape(-1).view(np.uint8)


def frames_to_views(bufs) -> list:
    """Normalize a mixed bytes/ndarray buffer list into non-empty byte
    memoryviews — the ONE definition of the wire's outgoing buffer shape
    (extension dtypes included, via :func:`_byte_view`), shared by
    :func:`send_frames` and the server core's buffered reply path."""
    out = []
    for b in bufs:
        if isinstance(b, np.ndarray):
            if b.nbytes:
                out.append(memoryview(_byte_view(b)))
        elif len(b):
            out.append(memoryview(b))
    return out


def send_frames(sock, bufs) -> None:
    """Scatter/gather send of a buffer list via ``sendmsg`` — no buffer is
    ever copied into a concatenated message.  Accepts ``bytes``,
    ``memoryview`` and contiguous ndarrays (cast to byte views here)."""
    out = frames_to_views(bufs)
    while out:
        sent = sock.sendmsg(out)
        while out and sent >= len(out[0]):
            sent -= len(out[0])
            out.pop(0)
        if out and sent:
            out[0] = out[0][sent:]


def send_frame(sock, header: bytes, payload: np.ndarray | None) -> None:
    """Header + optional array payload (the PS client's request shape)."""
    if payload is None or payload.size == 0:
        sock.sendall(header)
        return
    send_frames(sock, [header, payload])


def recv_exact(sock, view: memoryview) -> None:
    """Fill ``view`` from the socket via ``recv_into`` — responses land
    directly in their final buffer.  Raises ConnectionError on EOF."""
    pos, n = 0, len(view)
    while pos < n:
        r = sock.recv_into(view[pos:])
        if r == 0:
            raise ConnectionError("peer closed the connection")
        pos += r


def read_request(sock, hdr2: bytearray | None = None):
    """Server-side request parse: returns ``(op, name, a, b, payload_len)``
    with the payload left unread on the socket (the handler decides the
    receive buffer), or None on a clean EOF before a new frame.  A
    deadline-stamped frame (r18) has its stamp consumed and discarded —
    this blocking helper serves tests and tooling; the server core's
    incremental parser is where the stamp is acted on."""
    head = memoryview(hdr2 if hdr2 is not None else bytearray(2))
    try:
        recv_exact(sock, head)
    except ConnectionError:
        return None
    op, nlen = head[0], head[1]
    name = b""
    if nlen:
        nb = bytearray(nlen)
        recv_exact(sock, memoryview(nb))
        name = bytes(nb)
    tail = bytearray(REQ_TAIL.size)
    recv_exact(sock, memoryview(tail))
    a, b, plen = REQ_TAIL.unpack(tail)
    if op & DEADLINE_FLAG:
        stamp = bytearray(DEADLINE_TAIL.size)
        recv_exact(sock, memoryview(stamp))
        op &= ~DEADLINE_FLAG & 0xFF
    return op, name.decode(), a, b, plen


# ----------------------------------------------------------------------------
# Batch codec: JSON schema header + raw field bytes (zero-copy both ways).
# Shared by the data service (training batches) and the serving wire
# (predict inputs/outputs) — one definition, so the two byte-counting wires
# cannot drift.
# ----------------------------------------------------------------------------


def encode_batch(batch: dict[str, np.ndarray]) -> list:
    """Wire form of a field-dict batch: ``<I`` schema length + JSON schema +
    each field's raw bytes, returned as a BUFFER LIST for scatter/gather
    ``sendmsg`` — field arrays are never copied into a concatenated
    message.  Field order is sorted for determinism."""
    fields, bufs = [], []
    for k in sorted(batch):
        src = np.asarray(batch[k])
        a = np.ascontiguousarray(src)
        # Record the SOURCE shape: ascontiguousarray promotes 0-d scalars
        # to 1-d, and the decode side must reconstruct the original.
        # Extension dtypes (ml_dtypes bfloat16 & co.) stringify to a void
        # '<V2' that would DECODE as raw void — their registered NAME is
        # the round-trippable spelling; .str keeps byte order for the rest.
        spec = a.dtype.name if a.dtype.kind == "V" else a.dtype.str
        fields.append({"name": k, "dtype": spec, "shape": list(src.shape)})
        bufs.append(a)
    meta = json.dumps(fields).encode()
    return [struct.pack("<I", len(meta)) + meta] + bufs


def encoded_nbytes(bufs: list) -> int:
    return sum(
        b.nbytes if isinstance(b, np.ndarray) else len(b) for b in bufs
    )


def _decode_dtype(spec: str) -> np.dtype:
    """Decode a schema dtype spelling.  Extension-dtype names ('bfloat16')
    resolve only once their registering package is imported — numpy knows
    nothing of them on its own."""
    try:
        return np.dtype(spec)
    except TypeError:
        import ml_dtypes  # noqa: F401 — registers bfloat16/float8_* names

        return np.dtype(spec)


def decode_batch_bytes(buf) -> dict[str, np.ndarray]:
    """Inverse of :func:`encode_batch` over an in-memory buffer — the
    server-core shape (r17): the readiness-driven runtime receives whole
    request payloads off the selector, so handlers decode from bytes
    instead of a socket.  Fields are zero-copy views into ``buf``
    (read-only; callers that mutate copy their slice)."""
    mv = memoryview(buf)
    if len(mv) < 4:
        raise ValueError("batch payload shorter than its schema header")
    (mlen,) = struct.unpack("<I", mv[:4])
    if 4 + mlen > len(mv):
        raise ValueError("batch schema exceeds the framed payload")
    consumed = 4 + mlen
    out: dict[str, np.ndarray] = {}
    for f in json.loads(bytes(mv[4:consumed])):
        dt = _decode_dtype(f["dtype"])
        count = int(np.prod(f["shape"], dtype=np.int64))
        nbytes = count * dt.itemsize
        if consumed + nbytes > len(mv):
            raise ValueError("batch field exceeds the framed payload")
        out[f["name"]] = np.frombuffer(
            mv, dtype=dt, count=count, offset=consumed
        ).reshape(f["shape"])
        consumed += nbytes
    if consumed != len(mv):
        raise ValueError(
            f"batch framing mismatch: {consumed} consumed != {len(mv)} framed"
        )
    return out


def read_batch(sock, nbytes: int) -> dict[str, np.ndarray]:
    """Inverse of :func:`encode_batch`, receiving each field via
    ``recv_into`` straight into its final freshly-allocated array — no
    staging buffer, no per-field copy."""
    head = bytearray(4)
    recv_exact(sock, memoryview(head))
    (mlen,) = struct.unpack("<I", head)
    meta = bytearray(mlen)
    recv_exact(sock, memoryview(meta))
    consumed = 4 + mlen
    out: dict[str, np.ndarray] = {}
    for f in json.loads(bytes(meta)):
        a = np.empty(f["shape"], _decode_dtype(f["dtype"]))
        if a.nbytes:
            recv_exact(sock, memoryview(_byte_view(a)))
        out[f["name"]] = a
        consumed += a.nbytes
    if consumed != nbytes:
        raise ConnectionError(
            f"batch framing mismatch: {consumed} consumed != {nbytes} framed"
        )
    return out
