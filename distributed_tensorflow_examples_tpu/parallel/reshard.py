"""Live PS resharding N→M under traffic: coordinator-driven layout epochs
(r15 tentpole).

The PS tier was the last role frozen at process start: replication (r12)
removed its single points of failure and elasticity (r14) let every OTHER
role join/leave mid-run, but the shard COUNT — the thing that sets the
tier's aggregate NIC and memory budget — could only change with a full
cluster restart.  The layout-version word has ridden in every HELLO since
r12 exactly for this moment ("the plumbing live N->M resharding rides
on"); this module builds the actual transition, grounded in the automatic
cross-replica weight-update sharding story (PAPERS.md, arxiv 2004.13336)
and the TensorFlow paper's PS placement rebalancing (arxiv 1605.08695).

Protocol — one epoch bump, four phases, zero reseeds, zero failed ops:

1. **JOIN** — fresh shard tasks for the new :class:`~.ps_shard.ShardLayout`
   (epoch ``V = V_old + 1``) start serving their new identity, ANNOUNCE
   the transition as the coordinator's PENDING record (``RESHARD_BEGIN``,
   idempotent — every joiner may announce the same record), and pull
   their slices from the OLD layout over ranged ``REPL_SYNC`` (param-store
   objects only, sliced to the exact overlap with each old shard — the
   r12 state-transfer machinery extended to ranges).  They heartbeat
   membership leases like every other role (``psv<V>s<j>``, kind "ps")
   and carry data only once synced — clients cannot reach them before the
   commit, and the mixed-epoch HELLO guard makes any stale dial fail
   loudly naming both versions.
2. **VERIFY** — the chief (``RemotePSChief``) observes the pending record
   on its coordinator poll, probes every new shard for a synced snapshot,
   republishes the CURRENT params onto the new layout (so the swap never
   serves a stale step), and seeds the new coordinator's record slots.
   A joiner that dies mid-transition fails the probe: the chief ABORTS
   (``RESHARD_ABORT``) loudly and the old topology serves on — a
   transition either completes or aborts, never half-applies.
3. **COMMIT** — ``RESHARD_COMMIT`` flips the pending record to COMMITTED
   on the old coordinator (and the record is planted committed on the new
   coordinator, so late/restarted members discover the current topology
   from either end).  Every client — worker loops, prefetchers, the
   serve refresher, the data service's lease watcher, dtxtop — polls
   ``RESHARD_GET`` with its known version (O(header) while unchanged,
   the ``PSTORE_GET_IF_NEWER`` discipline) and swaps: new client pool,
   new layout, leases re-targeted at the new coordinator.  In-flight
   at-most-once pushes are preserved by the existing (worker, seq) dedup
   tags RE-SCOPED per epoch: the new servers start with empty dedup
   tables, every swapped client opens a fresh 0-based stream behind a
   ``*_RESET_WORKER`` announce, and a pre-epoch push replayed at the OLD
   server still answers "duplicate" there — the two epochs' tag spaces
   can never collide.
4. **DRAIN** — the chief signals every old-layout task a DRAIN shutdown
   (``ps_shutdown`` token 1): the task flags itself ``draining`` (visible
   in STATS/dtxtop), waits out its remaining connections as the last
   clients swap away, and exits 0.

Record schema (the ``RESHARD_*`` blob; the server stores it opaque):
``{"version", "num_elems", "shards", "replicas", "addrs": ["h:p", ...],
"from": {"version", "shards", "replicas", "addrs"}}``.
"""

from __future__ import annotations

import json
import socket
import struct
import time

import numpy as np

from . import wire

__all__ = [
    "pack_record",
    "parse_record",
    "coordinator_addrs_of",
    "poll_committed",
    "poll_pending",
    "EpochFollower",
    "ranged_sync",
    "discover_old_layout",
    "assemble_slice",
    "assemble_for_shard",
    "install_assembled",
    "join_new_shard",
]

#: Hard cap on record size (mirrors the server's RESHARD_BEGIN bound).
MAX_RECORD_BYTES = 16 << 10


def pack_record(
    version: int, addrs, num_elems: int, *, replicas: int = 1,
    from_version: int = 0, from_addrs=(), from_replicas: int = 1,
) -> bytes:
    """The wire form of a transition record.  ``addrs`` lists the NEW
    topology replica-major (shards = len(addrs) // replicas, the
    ``--ps_hosts`` convention); ``from_*`` names the OLD topology the new
    shards pull from — kept in the record so a restarted joiner (or an
    operator reading dtxtop) can reconstruct the whole transition from
    the coordinator alone."""
    addrs = [f"{h}:{p}" for h, p in addrs]
    if version <= 0 or version > wire.HELLO_LAYOUT_MASK:
        raise ValueError(
            f"reshard version {version} outside the 16-bit HELLO epoch "
            "field (1..65535)"
        )
    if not addrs or len(addrs) % max(1, replicas):
        raise ValueError(
            f"{len(addrs)} addresses do not tile {replicas} replicas"
        )
    blob = json.dumps({
        "version": int(version),
        "num_elems": int(num_elems),
        "shards": len(addrs) // max(1, replicas),
        "replicas": int(replicas),
        "addrs": addrs,
        "from": {
            "version": int(from_version),
            "shards": (
                len(list(from_addrs)) // max(1, from_replicas)
                if from_addrs else 0
            ),
            "replicas": int(from_replicas),
            "addrs": [f"{h}:{p}" for h, p in from_addrs],
        },
    }).encode()
    if len(blob) > MAX_RECORD_BYTES:
        raise ValueError(f"reshard record is {len(blob)} bytes (> 16 KiB)")
    return blob


def _parse_addrs(entries) -> list[tuple[str, int]]:
    out = []
    for e in entries:
        host, _, port_s = str(e).rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError(f"reshard record address {e!r} is not host:port")
        out.append((host, int(port_s)))
    return out


def parse_record(blob: bytes) -> dict:
    """Inverse of :func:`pack_record`; addresses come back as tuples.
    Raises ``ValueError`` on a malformed record — a garbled epoch record
    must fail the poller loudly, never swap clients onto garbage."""
    d = json.loads(blob.decode())
    rec = {
        "version": int(d["version"]),
        "num_elems": int(d["num_elems"]),
        "shards": int(d["shards"]),
        "replicas": int(d.get("replicas", 1)),
        "addrs": _parse_addrs(d["addrs"]),
    }
    f = d.get("from") or {}
    rec["from"] = {
        "version": int(f.get("version", 0)),
        "shards": int(f.get("shards", 0)),
        "replicas": int(f.get("replicas", 1)),
        "addrs": _parse_addrs(f.get("addrs", [])),
    }
    if rec["shards"] < 1 or len(rec["addrs"]) != rec["shards"] * rec["replicas"]:
        raise ValueError(
            f"reshard record v{rec['version']}: {len(rec['addrs'])} addrs "
            f"!= {rec['shards']} shards x {rec['replicas']} replicas"
        )
    return rec


def coordinator_addrs_of(rec: dict) -> list[tuple[str, int]]:
    """The record's coordinator replica addresses (replica-major entry
    ``r * shards`` — the one grouping convention, ps_shard.replica_major)."""
    n = rec["shards"]
    return [
        rec["addrs"][r * n]
        for r in range(rec["replicas"])
        if r * n < len(rec["addrs"])
    ]


def poll_committed(client, have_version: int = 0) -> dict | None:
    """The coordinator's committed record when NEWER than
    ``have_version`` (else None) — the one poll every epoch follower
    runs.  O(header) while unchanged."""
    version, blob = client.reshard_poll(have_version)
    if version <= have_version or not blob:
        return None
    return parse_record(blob)


def poll_pending(client) -> dict | None:
    """The coordinator's pending record, if any — the chief's adoption
    trigger and the joiner's restart-discovery read."""
    version, blob = client.reshard_poll(0, pending=True)
    if version <= 0 or not blob:
        return None
    return parse_record(blob)


class EpochFollower:
    """Time-gated committed-epoch poll over an EXISTING coordinator
    client: ``poll()`` answers a parsed record exactly once per committed
    epoch bump, None otherwise.  The unchanged-epoch steady state costs
    one O(header) round trip per ``min_poll_s`` — cheap enough to ride
    every worker-loop iteration and serve-refresher tick.  Poll errors
    are swallowed (the coordinator may be failing over; a missed poll is
    not a missed epoch — the next one sees the same record)."""

    def __init__(self, client, have_version: int, min_poll_s: float = 0.5):
        self._client = client
        self.version = int(have_version)
        self.min_poll_s = float(min_poll_s)
        self._next_t = 0.0

    def rebind(self, client, version: int) -> None:
        """Follow a swap: poll the NEW coordinator from now on."""
        self._client = client
        self.version = int(version)

    def poll(self, *, force: bool = False) -> dict | None:
        now = time.monotonic()
        if not force and now < self._next_t:
            return None
        self._next_t = now + self.min_poll_s
        try:
            rec = poll_committed(self._client, self.version)
        except Exception:  # noqa: BLE001 — coordinator mid-failover
            return None
        if rec is not None:
            self.version = rec["version"]
        return rec


# ----------------------------------------------------------------------------
# Ranged REPL_SYNC: the slice transfer (raw socket — one-shot pulls need no
# recovery machinery, and the repl-flagged HELLO is not a client-pool leg)
# ----------------------------------------------------------------------------


def _dial_repl(
    addr: tuple[str, int], *, layout_version: int = 0, timeout_s: float = 10.0,
) -> socket.socket:
    """A repl-flagged connection to an old-layout server, epoch-pinned:
    a server on a DIFFERENT epoch (or a partitioned one) refuses the
    HELLO loudly instead of serving the wrong slice."""
    sock = socket.create_connection(addr, timeout=timeout_s)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        b = wire.pack_hello_b(0, layout_version=layout_version, repl=True)
        sock.sendall(
            wire.pack_request(wire.HELLO_OP, "", wire.WIRE_VERSION, b, 0)
        )
        hdr = bytearray(wire.RESP_HDR.size)
        wire.recv_exact(sock, memoryview(hdr))
        status, plen = wire.RESP_HDR.unpack(hdr)
        if plen:
            wire.recv_exact(sock, memoryview(bytearray(plen * 4)))
        if status != wire.WIRE_VERSION:
            if status <= wire.HELLO_SHARD_MISMATCH:
                _, _, got_v = wire.unpack_shard_mismatch(status)
                raise ConnectionError(
                    f"ranged sync refused: {addr[0]}:{addr[1]} serves shard "
                    f"layout EPOCH {got_v} but this puller expected epoch "
                    f"{layout_version} — the old topology moved underneath "
                    "the transition"
                )
            raise ConnectionError(
                f"ranged sync HELLO with {addr[0]}:{addr[1]} failed "
                f"({status}): partitioned peer or pre-r15 server"
            )
        return sock
    except BaseException:
        sock.close()
        raise


def _parse_ranged_blob(blob: bytes) -> dict[str, dict]:
    """``{name: {"total", "start", "count", "step", "data"}}`` out of a
    ranged REPL_SYNC blob (layout: ps_server.cc build_ranged_sync_blob)."""
    out: dict[str, dict] = {}
    at = 8  # skip the state token
    (n_obj,) = struct.unpack_from("<I", blob, at)
    at += 4
    for _ in range(n_obj):
        kind = blob[at]
        (nlen,) = struct.unpack_from("<H", blob, at + 1)
        at += 3
        name = blob[at : at + nlen].decode()
        at += nlen
        if kind != ord("p"):
            raise ValueError(f"ranged sync blob carries non-pstore kind {kind}")
        total, start, count, step = struct.unpack_from("<qqqq", blob, at)
        at += 32
        data = np.frombuffer(blob, np.float32, count, at).copy()
        at += count * 4
        out[name] = {
            "total": total, "start": start, "count": count, "step": step,
            "data": data,
        }
    return out


def ranged_sync(
    addr: tuple[str, int], start: int, count: int, *,
    layout_version: int = 0, timeout_s: float = 10.0,
) -> dict[str, dict]:
    """One ranged state pull from an old-layout server: every param-store
    object's ``[start, start + count)`` LOCAL element range (clamped
    server-side), with each object's total size and published step.
    ``count = 0`` is the metadata probe — object names/sizes/steps with
    zero data bytes — the layout-discovery read."""
    sock = _dial_repl(addr, layout_version=layout_version, timeout_s=timeout_s)
    try:
        # count <= 0 probes metadata: sent as -1 (b == 0 would select the
        # r12 FULL state sync, a different blob the range parser must
        # never see; the server clamps a negative count to zero data).
        sock.sendall(wire.pack_request(
            wire.PS_OPS["REPL_SYNC"], "", start, count if count > 0 else -1, 0
        ))
        hdr = bytearray(wire.RESP_HDR.size)
        wire.recv_exact(sock, memoryview(hdr))
        status, plen = wire.RESP_HDR.unpack(hdr)
        if status < 0:
            raise ConnectionError(
                f"ranged REPL_SYNC at {addr[0]}:{addr[1]} rejected "
                f"({status}) — pre-r15 server?"
            )
        blob = bytearray(plen * 4)
        if plen:
            wire.recv_exact(sock, memoryview(blob))
        return _parse_ranged_blob(bytes(blob))
    finally:
        sock.close()


def _as_replica_list(entry) -> list[tuple[str, int]]:
    """Normalize an old-shard address entry: a bare ``(host, port)`` or a
    replica list ``[(host, port), ...]`` — pulls fall over to the next
    replica of the SAME shard, so a dead old primary never blocks a
    joiner (the r12 failover posture, applied to the transfer)."""
    if entry and isinstance(entry[0], (list, tuple)):
        return [tuple(a) for a in entry]
    return [tuple(entry)]


def _ranged_sync_any(
    replicas: list[tuple[str, int]], start: int, count: int, *,
    layout_version: int = 0, timeout_s: float = 10.0,
) -> dict[str, dict]:
    last: Exception | None = None
    for addr in replicas:
        try:
            return ranged_sync(
                addr, start, count, layout_version=layout_version,
                timeout_s=timeout_s,
            )
        except OSError as e:
            last = e
    raise ConnectionError(
        f"ranged sync failed on every replica of {replicas}: {last!r}"
    )


def discover_old_layout(
    old_addrs, *, old_version: int = 0, timeout_s: float = 10.0,
) -> dict:
    """The old tier's per-shard object sizes, from metadata probes against
    each old shard (entries may be bare primary addresses or replica
    lists): ``{"objects": {name: [n_shard0, ...]}, "steps": {name: [...]},
    "num_elems": {name: total}}``.  A shard carrying no objects yet
    (pre-first-publish) contributes zeros — the caller decides whether
    that is fatal (a reshard needs a published store)."""
    objects: dict[str, list[int]] = {}
    steps: dict[str, list[int]] = {}
    metas = [
        _ranged_sync_any(
            _as_replica_list(a), 0, 0, layout_version=old_version,
            timeout_s=timeout_s,
        )
        for a in old_addrs
    ]
    names = sorted({n for m in metas for n in m})
    for name in names:
        objects[name] = [m[name]["total"] if name in m else 0 for m in metas]
        steps[name] = [m[name]["step"] if name in m else -1 for m in metas]
    return {
        "objects": objects,
        "steps": steps,
        "num_elems": {n: sum(sizes) for n, sizes in objects.items()},
    }


def assemble_slice(
    old_addrs, name: str, lo: int, hi: int, *, old_version: int = 0,
    layout_meta: dict | None = None, timeout_s: float = 10.0,
) -> tuple[int, np.ndarray]:
    """Assemble GLOBAL flat-vector range ``[lo, hi)`` of param-store
    object ``name`` from the old layout: for each old shard whose slice
    overlaps, pull exactly the overlap (ranged REPL_SYNC) and
    concatenate.  Returns ``(step, data)`` with ``step`` the MINIMUM
    across contributing shards (the sharded-store tear convention).
    Byte-exact: the concatenation over any partition of
    ``[0, num_elems)`` reproduces the old tier's stored bytes —
    tests/test_reshard.py pins this for N→M and M→N."""
    meta = layout_meta or discover_old_layout(
        old_addrs, old_version=old_version, timeout_s=timeout_s
    )
    sizes = meta["objects"].get(name)
    if sizes is None:
        raise KeyError(f"old layout carries no param-store object {name!r}")
    total = sum(sizes)
    lo_c, hi_c = max(0, lo), min(hi, total)
    parts: list[np.ndarray] = []
    step = None
    off = 0
    for shard_i, n in enumerate(sizes):
        s_lo, s_hi = off, off + n
        off += n
        olo, ohi = max(lo_c, s_lo), min(hi_c, s_hi)
        if olo >= ohi:
            continue
        pulled = _ranged_sync_any(
            _as_replica_list(old_addrs[shard_i]), olo - s_lo, ohi - olo,
            layout_version=old_version, timeout_s=timeout_s,
        )[name]
        if pulled["count"] != ohi - olo:
            raise ConnectionError(
                f"ranged sync of {name!r} shard {shard_i} answered "
                f"{pulled['count']} elems for a {ohi - olo}-elem ask — "
                "the old layout changed mid-transition"
            )
        parts.append(pulled["data"])
        step = pulled["step"] if step is None else min(step, pulled["step"])
    data = np.concatenate(parts) if parts else np.empty((0,), np.float32)
    return (step if step is not None else -1, data)


def assemble_for_shard(
    old_addrs, shard_id: int, new_shards: int, *, old_version: int = 0,
    layout_meta: dict | None = None, timeout_s: float = 10.0,
) -> dict[str, tuple[int, np.ndarray]]:
    """Every param-store object's slice for NEW shard ``shard_id`` of a
    ``new_shards``-way layout, assembled from the old tier.  Each object
    is partitioned by its OWN deterministic :class:`~.ps_shard.ShardLayout`
    over its own total (the same rule every client derives), so a joiner
    and the clients that will dial it can never disagree about the
    slice."""
    from . import ps_shard

    meta = layout_meta or discover_old_layout(
        old_addrs, old_version=old_version, timeout_s=timeout_s
    )
    out: dict[str, tuple[int, np.ndarray]] = {}
    for name, total in meta["num_elems"].items():
        layout = ps_shard.ShardLayout(total, new_shards)
        rng = layout.slice(shard_id)
        out[name] = assemble_slice(
            old_addrs, name, rng.start, rng.stop, old_version=old_version,
            layout_meta=meta, timeout_s=timeout_s,
        )
    return out


def install_assembled(
    addr: tuple[str, int], objects: dict[str, tuple[int, np.ndarray]], *,
    layout_version: int = 0, timeout_s: float = 10.0,
) -> None:
    """Create-and-fill the assembled param-store slices on a NEW shard
    server (epoch-pinned dial, so installing onto the wrong epoch fails
    loudly).  Zero-size slices (more shards than elements) are skipped —
    the native services reject zero-element objects, exactly the
    empty-shard convention ShardedParamStore handles client-side."""
    from . import ps_service

    c = ps_service.PSClient(
        addr[0], addr[1], timeout_s=timeout_s, expect_layout=layout_version,
    )
    try:
        for name, (step, data) in objects.items():
            if data.size == 0:
                continue
            ps_service._check(
                c.ensure_object(ps_service._PSTORE_GET_OBJ, name, data.size),
                "pstore_get_obj",
            )
            if step >= 0:
                ps_service._check(
                    c.call(
                        ps_service._PSTORE_SET, name, step, payload=data
                    )[0],
                    "pstore_set",
                )
    finally:
        c.close()


def join_new_shard(
    own_addr: tuple[str, int], shard_id: int, new_shards: int,
    new_version: int, old_addrs, *, old_version: int = 0,
    wait_published_s: float = 60.0, timeout_s: float = 10.0,
) -> dict:
    """The whole joiner sync: wait for the old layout to hold a PUBLISHED
    store, assemble this new shard's slices, install them on ``own_addr``.
    Returns the discovered old-layout meta (the joiner announces the
    transition record from its ``num_elems``).  Raises ConnectionError
    when the old tier never publishes within the budget — a joiner
    against an unpublished (or already-drained) old layout must fail
    loudly, not serve zeros."""
    deadline = time.monotonic() + wait_published_s
    while True:
        meta = discover_old_layout(
            old_addrs, old_version=old_version, timeout_s=timeout_s
        )
        published = bool(meta["objects"]) and all(
            step >= 0
            for name, steps in meta["steps"].items()
            for n, step in zip(meta["objects"][name], steps)
            if n > 0
        )
        if published:
            break
        if time.monotonic() >= deadline:
            raise ConnectionError(
                f"old layout v{old_version} at {old_addrs} never presented "
                f"a published store within {wait_published_s}s"
            )
        time.sleep(0.25)
    install_assembled(
        own_addr,
        assemble_for_shard(
            old_addrs, shard_id, new_shards, old_version=old_version,
            layout_meta=meta, timeout_s=timeout_s,
        ),
        layout_version=new_version, timeout_s=timeout_s,
    )
    return meta
