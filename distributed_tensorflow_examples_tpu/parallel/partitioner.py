"""Variable partitioners: API-parity shims for TF's partitioned variables.

The reference shards its word2vec embedding table across parameter servers
with ``tf.fixed_size_partitioner`` (SURVEY.md sections 2b D4 and 3.5).  On a
TPU mesh the same intent — "split this big table over N memory domains" — is a
``PartitionSpec`` over the ``model`` axis, so these helpers return rule
entries rather than device placements.  The forward-pass network hop of the
reference (per-shard gather executed on the owning PS, results sent back over
gRPC) becomes an XLA gather + collective over ICI, fused into the step.
"""

from __future__ import annotations

from jax.sharding import PartitionSpec

P = PartitionSpec


def fixed_size_partitioner(axis_name: str = "model", dim: int = 0):
    """Shard dimension ``dim`` over mesh axis ``axis_name``.

    TF analog: ``tf.fixed_size_partitioner(num_shards, axis=dim)`` — except
    shard count comes from the mesh, not a flag, so the same model code runs
    on any topology.
    Returns a ``PartitionSpec`` usable directly in a sharding rule table.
    """
    entries: list = [None] * dim + [axis_name]
    return P(*entries)


def min_max_variable_partitioner(
    min_slice_bytes: int = 256 << 10,
    axis_name: str = "model",
):
    """TF-analog heuristic partitioner: returns a *function* of
    ``(shape, dtype_bytes, axis_size)`` deciding whether the leading dim is
    worth sharding.  Small variables stay replicated (sharding a tiny bias
    would only add collective latency).  Unlike TF's ``max_partitions`` there
    is no partial shard count: a named mesh axis shards over all its devices
    or not at all, so the only knob is the per-slice byte floor.
    """

    def decide(shape, dtype_bytes: int = 4, axis_size: int = 1) -> PartitionSpec:
        if not shape:
            return P()
        nbytes = dtype_bytes
        for s in shape:
            nbytes *= s
        if nbytes // max(1, axis_size) < min_slice_bytes:
            return P()
        return P(axis_name)

    return decide
