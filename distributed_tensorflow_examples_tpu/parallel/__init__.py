"""Parallelism layer: device meshes, sharding rules, collectives, multi-host.

This package is the TPU-native replacement for the reference's distribution
stack (SURVEY.md section 1, layers L1/L2): ``tf.train.ClusterSpec`` /
``tf.train.Server`` / ``replica_device_setter`` / ``tf.distribute`` strategies
/ gRPC+NCCL collectives all collapse into (mesh, sharding rules, XLA
collectives, jax.distributed bootstrap).
"""

from .mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQ,
    MeshSpec,
    build_mesh,
    local_mesh_for_testing,
)
from .sharding import (  # noqa: F401
    ShardingRules,
    named_sharding,
    shard_pytree,
    sharding_tree,
    spec_for_path,
)
from .partitioner import (  # noqa: F401
    fixed_size_partitioner,
    min_max_variable_partitioner,
)
from . import collectives  # noqa: F401
from . import dist  # noqa: F401
