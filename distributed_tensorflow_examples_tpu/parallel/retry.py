"""Shared client-side retry discipline (r18): budgets, breakers, jitter.

Every resilient client in the stack (``PSClient``, ``DataServiceClient``,
``ServeClient``/``ServePool``) retries: reconnect-and-replay on transport
faults, back-off-and-retry on the server's typed RETRY_LATER shed
answers.  Uncoordinated, those retries are how one blip becomes a
METASTABLE failure — N clients recovering in lockstep re-arrive as a
thundering herd, the herd re-overloads the server, the overload produces
more retries, and the storm outlives the blip that started it.  This
module is the ONE definition of the discipline that prevents it, used by
all three clients (dtxlint's ``retry-discipline`` rule refuses a
reconnect/retry loop in ``parallel/``/``data/``/``serve/`` that does not
consult it):

- :func:`jittered` — equal-jitter exponential backoff.  Deterministic
  backoff synchronizes recovering clients onto the same retry instants;
  the jitter decorrelates them, so the post-blip re-arrival is a ramp,
  not a spike.
- :class:`RetryBudget` — a token bucket that caps RETRIES at a fraction
  of SUCCESSES (plus a burst allowance for cold starts and short blips).
  Healthy traffic keeps the bucket full; a retry STORM — every op
  failing, every failure retried — drains it, and further retries are
  refused until real successes refill it.  Budget exhaustion surfaces as
  the caller's existing typed deadline error plus a flight-recorder
  event, so a storm is attributable, not silent.
- :class:`CircuitBreaker` (per ADDRESS, process-wide registry via
  :func:`breaker_for`) — consecutive transport failures against one
  address open the breaker for a jittered, exponentially growing window;
  while open, dial attempts fail fast (or skip to a replica) instead of
  burning connect timeouts against a dead peer; a half-open probe after
  the window closes it again on the first success.  All clients of one
  process share each address's breaker, so one client's discovery that a
  peer is down spares every other client the same timeout.

Telemetry: ``retry/spent``, ``retry/budget_exhausted``,
``retry/breaker_open`` and ``retry/breaker_fast_fails`` accumulate in the
process registry (scraped by every service's STATS answer and rendered
per role by ``tools/dtxtop``).
"""

from __future__ import annotations

import random
import threading
import time

from ..utils import faults, telemetry

_OBS_SPENT = telemetry.REGISTRY.counter("retry/spent")
_OBS_EXHAUSTED = telemetry.REGISTRY.counter("retry/budget_exhausted")
_OBS_BREAKER_OPEN = telemetry.REGISTRY.counter("retry/breaker_open")
_OBS_FAST_FAILS = telemetry.REGISTRY.counter("retry/breaker_fast_fails")

#: Module-wide jitter source.  Deliberately NOT seeded: cross-process
#: decorrelation is the whole point — reproducing exact retry instants
#: would re-synchronize the herd the jitter exists to break up.  Tests
#: that need determinism pass their own ``rng``.
_rng = random.Random()


def jittered(
    base_s: float, attempt: int = 0, cap_s: float = 2.0,
    rng: random.Random | None = None,
) -> float:
    """Equal-jitter exponential backoff: for retry ``attempt`` (0-based),
    the nominal delay is ``min(cap_s, base_s * 2**attempt)`` and the
    returned delay is uniform in [nominal/2, nominal] — half the wait is
    guaranteed (no hot-loop zero delays), half is decorrelation."""
    nominal = min(float(cap_s), float(base_s) * (2 ** min(int(attempt), 16)))
    r = rng if rng is not None else _rng
    return nominal / 2 + r.uniform(0.0, nominal / 2)


class RetryBudget:
    """Token-bucket retry budget: retries capped at a fraction of
    successes.

    The bucket starts at ``burst`` tokens (cold starts and short blips
    retry freely); every SUCCESS deposits ``ratio`` tokens (capped at
    ``burst``), every retry spends one.  When the bucket is empty,
    :meth:`try_spend` refuses — the caller surfaces its typed deadline
    error instead of feeding the storm.  Thread-safe; one instance per
    client (the budget prices THAT client's retry pressure)."""

    def __init__(self, ratio: float = 0.2, burst: float = 20.0):
        self.ratio = float(ratio)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._lock = threading.Lock()
        self._exhausted_logged = False

    @property
    def tokens(self) -> float:
        return self._tokens

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.ratio)
            self._exhausted_logged = False

    def try_spend(self) -> bool:
        """Spend one retry token; False when the budget is exhausted (the
        first refusal of a dry spell logs a flight-recorder event, so a
        storm leaves evidence without flooding the ring)."""
        log_it = False
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                spent = True
            else:
                spent = False
                if not self._exhausted_logged:
                    self._exhausted_logged = True
                    log_it = True
        if spent:
            _OBS_SPENT.inc()
            return True
        _OBS_EXHAUSTED.inc()
        if log_it:
            faults.log_event(
                "retry_budget_exhausted", role=faults.current_role(),
                ratio=self.ratio, burst=self.burst,
            )
        return False


class ShedRetry:
    """Per-op shed-retry pacing: the ONE spelling of "the server answered
    RETRY_LATER — back off and try again" shared by the wire clients.
    Each backoff is jittered off the server's hint, spends the client's
    :class:`RetryBudget`, and the whole shed-retry spell is bounded by
    the op timeout (``default_s`` when the client has none): a server
    that keeps shedding past it surfaces the caller's typed deadline
    error instead of being polled forever."""

    __slots__ = ("_budget", "_window_s", "_deadline", "_attempt")

    def __init__(
        self, budget: RetryBudget, op_timeout_s: float | None,
        default_s: float = 30.0,
    ):
        self._budget = budget
        self._window_s = float(op_timeout_s) if op_timeout_s else default_s
        self._deadline: float | None = None  # armed on the first shed
        self._attempt = 0

    def backoff(self, hint_ms: int) -> bool:
        """One shed answer: sleep a jittered backoff honoring the
        server's ``hint_ms`` and return True (retry), or return False —
        give up (the shed window or the retry budget is exhausted; the
        caller raises its typed deadline error)."""
        now = time.monotonic()
        if self._deadline is None:
            self._deadline = now + self._window_s
        if now >= self._deadline or not self._budget.try_spend():
            return False
        time.sleep(jittered(max(hint_ms, 10) / 1e3, self._attempt, cap_s=2.0))
        self._attempt += 1
        return True


class CircuitBreaker:
    """Per-address circuit breaker: ``threshold`` CONSECUTIVE transport
    failures open it for a jittered window that doubles per re-open
    (``open_s`` .. ``max_open_s``); while open, :meth:`allow` answers
    False (fail fast / try a replica).  After the window a half-open
    probe is allowed, and one success fully closes it.  Process-wide per
    address (see :func:`breaker_for`): every client sharing the address
    shares the verdict."""

    def __init__(
        self, addr, *, threshold: int = 5, open_s: float = 0.5,
        max_open_s: float = 4.0,
    ):
        self.addr = addr
        self.threshold = int(threshold)
        self.open_s = float(open_s)
        self.max_open_s = float(max_open_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._opens = 0  # consecutive opens since the last success
        self._open_until = 0.0
        self.opened_total = 0

    def allow(self, now: float | None = None) -> bool:
        """Whether a dial attempt may proceed (False while open; True
        again once the window passed — the half-open probe)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            ok = t >= self._open_until
        if not ok:
            _OBS_FAST_FAILS.inc()
        return ok

    def probe_in_s(self, now: float | None = None) -> float:
        """Seconds until the next half-open probe (0 = allowed now)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            return max(0.0, self._open_until - t)

    def wait_for_probe(self, t_end: float) -> None:
        """Sleep toward the next half-open probe — the ONE spelling of
        the open-breaker wait the reconnect loops share: bounded by 0.5 s
        chunks (the breaker may close early on another client's success)
        and by the caller's reconnect deadline ``t_end``.  This wait IS
        the attempt's pacing — callers skip their own backoff sleep for
        the iteration it paced."""
        time.sleep(min(
            self.probe_in_s(), 0.5, max(0.0, t_end - time.monotonic()),
        ))

    def on_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opens = 0
            self._open_until = 0.0

    def on_failure(self, now: float | None = None) -> None:
        t = time.monotonic() if now is None else now
        opened = False
        with self._lock:
            self._failures += 1
            if self._failures >= self.threshold:
                self._failures = 0
                window = jittered(
                    self.open_s, self._opens, cap_s=self.max_open_s
                )
                self._opens += 1
                self._open_until = t + window
                self.opened_total += 1
                opened = True
        if opened:
            _OBS_BREAKER_OPEN.inc()
            faults.log_event(
                "breaker_open", role=faults.current_role(),
                addr=f"{self.addr[0]}:{self.addr[1]}"
                if isinstance(self.addr, tuple) else str(self.addr),
                opens=self.opened_total,
            )


_breakers: dict = {}
_breakers_lock = threading.Lock()


def breaker_for(addr) -> CircuitBreaker:
    """The process-wide breaker for ``addr`` (``(host, port)``), created
    on first use — one shared verdict per address, so N clients pay one
    discovery timeout for a dead peer, not N."""
    with _breakers_lock:
        b = _breakers.get(addr)
        if b is None:
            b = _breakers[addr] = CircuitBreaker(addr)
        return b


def reset_breakers() -> None:
    """Drop every registered breaker (test isolation)."""
    with _breakers_lock:
        _breakers.clear()
