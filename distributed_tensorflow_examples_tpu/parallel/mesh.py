"""Device-mesh construction: the TPU-native cluster topology layer.

The reference describes its cluster as job-name -> host:port lists
(``tf.train.ClusterSpec``, see SURVEY.md section 2b component D1) and starts a
gRPC server per process (D2).  On TPU the topology is instead a named
``jax.sharding.Mesh`` over all addressable chips; "jobs" become *mesh axes*:

- ``data``   — pure data parallelism (the PS/worker "worker" job's role)
- ``model``  — tensor parallelism (the PS-sharded-variable role, D3/D4)
- ``seq``    — sequence/context parallelism (ring attention; no reference
               analog — long-context growth axis)
- ``expert`` — expert parallelism (MoE; no reference analog)
- ``pipe``   — pipeline parallelism

ICI vs DCN: when a mesh spans multiple slices/hosts, the outermost axis
(``data`` by default) is laid across DCN while inner axes stay on ICI — this
is what ``mesh_utils.create_hybrid_device_mesh`` encodes.  Collectives along
inner axes then ride ICI links.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Sequence

import numpy as np

import jax
from jax.experimental import mesh_utils
from jax.sharding import Mesh

AXIS_SLICE = "slice"
AXIS_DATA = "data"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_MODEL = "model"
AXIS_EXPERT = "expert"

#: Canonical axis order, outermost (DCN-friendly, infrequent comms) first and
#: innermost (ICI-hungry, per-layer comms) last.  Tensor-parallel collectives
#: fire most often, so ``model`` sits innermost where ICI is densest.
#: ``slice`` (r4) makes the DCN slice boundary an EXPLICIT outermost axis
#: when a workload wants to scope collectives slice-locally (ghost-batch BN
#: statistics — models/resnet.Config.bn_ghost_slices); batch then shards
#: over ('slice', 'data') jointly.
DEFAULT_AXES: tuple[str, ...] = (
    AXIS_SLICE, AXIS_DATA, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL
)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical parallelism layout.  ``-1`` on exactly one axis means "all
    remaining devices" (like the reference's implicit worker count from
    ``--worker_hosts`` length).

    Replaces: ``ClusterSpec({"ps": [...], "worker": [...]})`` — but instead of
    naming processes it names parallelism dimensions.
    """

    data: int = -1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1
    slice: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            AXIS_SLICE: self.slice,
            AXIS_DATA: self.data,
            AXIS_PIPE: self.pipe,
            AXIS_EXPERT: self.expert,
            AXIS_SEQ: self.seq,
            AXIS_MODEL: self.model,
        }

    def resolved(self, n_devices: int) -> dict[str, int]:
        """Resolve the single ``-1`` axis against the device count."""
        sizes = self.sizes()
        unknown = [a for a, s in sizes.items() if s == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {unknown}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if unknown:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[unknown[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {math.prod(sizes.values())} devices, "
                f"have {n_devices}"
            )
        return sizes

    @staticmethod
    def parse(text: str) -> "MeshSpec":
        """Parse ``"data=8,model=2"`` (axes omitted default to 1, data to -1)."""
        if not text:
            return MeshSpec()
        kwargs: dict[str, int] = {}
        for part in text.split(","):
            name, _, value = part.partition("=")
            name = name.strip()
            if name not in DEFAULT_AXES:
                raise ValueError(f"unknown mesh axis {name!r}; valid: {DEFAULT_AXES}")
            kwargs[name] = int(value)
        return MeshSpec(**kwargs)


def _num_slices(devices: Sequence[jax.Device]) -> int:
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    return len(slice_ids)


def build_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    allow_split_physical_axes: bool = False,
) -> Mesh:
    """Build an ICI-topology-aware ``Mesh`` from a logical spec.

    Single-slice: ``mesh_utils.create_device_mesh`` orders devices so that
    innermost mesh axes map to physically adjacent chips (ring-friendly).
    Multi-slice (v5e-64 = 8 hosts over DCN): a hybrid mesh lays the outermost
    non-trivial axis across slices over DCN, the rest within-slice over ICI —
    the TPU-native analog of the reference's "NCCL within node, gRPC across
    nodes" split (SURVEY.md section 5.8).
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolved(len(devices))
    axis_names = tuple(sizes)
    shape = tuple(sizes[a] for a in axis_names)

    n_slices = _num_slices(devices)
    if n_slices > 1:
        per_slice = len(devices) // n_slices
        # Put the DCN dimension on the outermost axis whose size it divides;
        # typically `data`.
        dcn_shape = [1] * len(shape)
        ici_shape = list(shape)
        for i, s in enumerate(shape):
            if s % n_slices == 0:
                dcn_shape[i] = n_slices
                ici_shape[i] = s // n_slices
                break
        else:
            raise ValueError(
                f"no mesh axis in {sizes} divisible by slice count {n_slices}"
            )
        if math.prod(ici_shape) != per_slice:
            raise ValueError(
                f"per-slice mesh {ici_shape} != {per_slice} devices per slice"
            )
        mesh_devices = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape),
            tuple(dcn_shape),
            devices=devices,
            allow_split_physical_axes=allow_split_physical_axes,
        )
    else:
        try:
            mesh_devices = mesh_utils.create_device_mesh(
                shape,
                devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
        except (ValueError, NotImplementedError):
            # Topology-unaware fallback (e.g. odd CPU device counts in tests).
            mesh_devices = np.asarray(devices).reshape(shape)
    return Mesh(mesh_devices, axis_names)


def local_mesh_for_testing(
    sizes: dict[str, int] | None = None, *, platform: str = "cpu"
) -> Mesh:
    """Fake multi-chip mesh on host devices — the analog of the reference's
    in-process fake cluster (``multi_worker_test_base.create_in_process_cluster``,
    SURVEY.md section 4).  Requires
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``.
    """
    sizes = dict(sizes or {})
    unknown = set(sizes) - set(DEFAULT_AXES)
    if unknown:
        raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {DEFAULT_AXES}")
    devices = jax.devices(platform)
    if not sizes:
        sizes = {AXIS_DATA: len(devices)}
    for axis in DEFAULT_AXES:
        sizes.setdefault(axis, 1)
    ordered = {a: sizes[a] for a in DEFAULT_AXES}
    n = math.prod(ordered.values())
    if n > len(devices):
        raise ValueError(f"need {n} {platform} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(tuple(ordered.values()))
    return Mesh(arr, tuple(ordered))
