"""dtxcore — the unified async server runtime (r17 tentpole).

Before this module every host service ran its own hand-rolled
thread-per-connection server: the native PS (``native/ps_server.cc``), the
data service (``data/data_service.py``) and the serving replicas
(``serve/model_server.py``) each re-implemented accept loops, HELLO
answer/reject paths, STATS plumbing, request-counter exclusion and
graceful stop — and every idle connection pinned a handler thread.  The
TensorFlow architecture paper (PAPERS.md, arxiv 1605.08695) runs every
session type on ONE runtime; ``parallel/wire.py`` already unified the
framing half of that story.  This module finishes the server half for the
Python services:

- **Readiness-driven I/O** — one selector thread (epoll/kqueue via
  :mod:`selectors`) owns every socket: it accepts, reads request frames
  incrementally (the shared ``wire.py`` frame layout, parsed by an
  allocation-light state machine instead of blocking ``recv_exact``
  calls), and flushes buffered responses.  256 idle connections cost 256
  file descriptors and nothing else — no thread, no stack, no scheduler
  pressure.
- **Connection registry** — every live connection is a :class:`CoreConn`
  with its own parse state and write buffer; ``live_conns`` is a real
  count, not a best-effort list the handler threads race to maintain.
- **Bounded handler pool** — complete frames dispatch to a fixed worker
  pool (``workers=``).  Handlers return the reply (or go async via
  :data:`ASYNC` + :meth:`CoreConn.reply` for work that completes on
  another thread, e.g. the serve micro-batcher), so concurrency is
  bounded by the pool, never by the connection count.
- **Per-connection write buffering** — replies are queued on the
  connection and flushed by the selector as the peer drains them.  A
  slow or stalled reader accumulates bytes, it never wedges a handler
  thread in ``sendall``; a peer holding more than
  ``max_buffered_bytes`` that has also drained NOTHING for
  ``slow_reader_grace_s`` is dropped (progress-gated, so one
  legitimately large reply streaming to a healthy reader is never cut).
- **Per-service handler table keyed off the HELLO service tag** — a core
  hosts one or more services; the client's announced service identity
  (``wire.pack_hello_b(service=...)``) routes the connection, and every
  wrong-service dial is refused through the one shared
  ``wire.hello_answer`` path, naming what was actually reached.
- **Uniform accounting** — the request counter (the ``die:after_reqs``
  fault trigger and an exported metric) lives HERE, excluding
  control-plane ops from the one ``wire.CONTROL_OPS`` registry (each
  service passes its derived frozenset), plus an optional per-service
  ``counts_fn`` for rules an op code alone cannot carry (the dsvc
  negative-id REGISTER probe).  One STATS shape: every service folds
  :meth:`ServerCore.core_stats` into its scrape, so ``requests`` /
  ``live_conns`` mean the same thing on every wire (the native PS keeps
  its C++ loop but answers the same shape — asserted by test).
- **Hardened accept path** — transient ``ECONNABORTED`` is skipped;
  descriptor exhaustion (``EMFILE``/``ENFILE``) logs, backs off and
  resumes — it never kills the listener.
- **Graceful drain** — :meth:`drain` stops accepting, lets dispatched
  handlers finish and write buffers flush, then :meth:`stop` closes;
  zero in-flight requests are dropped on a clean shutdown.
- **Admission control** (r18) — the request plane degrades GRACEFULLY
  instead of collapsing.  The dispatch queue is BOUNDED
  (``max_dispatch_depth``): past it, new data-plane frames are answered
  the typed ``wire.RETRY_LATER_BASE`` shed status (backoff hint packed
  into the status) instead of queueing unboundedly.  Each request
  carries a QUEUE DEADLINE — the smaller of the service's
  ``queue_deadline_s`` policy and the deadline the CALLER stamped into
  the frame (``wire.DEADLINE_FLAG``, the r18 deadline-propagation wire) —
  and a request that waited past it is shed before a worker touches it
  (checked at dequeue AND swept ~1/s by the selector loop, so wedged
  workers cannot strand queued requests unanswered).  Each connection
  holds at most ``max_inflight_per_conn`` dispatched-unanswered
  requests; pipelined excess is shed, with per-connection response
  ORDER preserved by sequence-parked replies.  PRIORITY CLASSES:
  control/observability ops (the service's ``control_ops``, derived
  from ``wire.CONTROL_OPS`` — HELLO, STATS, LEASE_*, ...) are NEVER
  shed: they bypass every admission bound, ride a priority queue the
  workers prefer, and one DEDICATED control worker serves them even
  when every regular worker is wedged — under saturation the cluster
  stays observable and leases keep renewing, so overload cannot cascade
  into false member expiry.  Shed counters (``shed_total``,
  ``queue_deadline_drops``, ``shed_dispatch_full``,
  ``shed_inflight_cap``) fold into :meth:`core_stats`.

The native PS keeps its C++ thread-per-connection loop (its handlers are
microseconds of mutex-guarded C++, not milliseconds of Python, so the
thread count is a non-issue there); this module is the single Python
definition of server behavior, and the cross-service tests pin the C++
side to the same observable semantics.
"""

from __future__ import annotations

import errno
import logging
import queue
import selectors
import socket
import struct
import threading
import time
from collections import deque
from typing import Callable

from . import tenancy, wire

log = logging.getLogger("dtx.server_core")

#: Sentinel a handler returns when it will reply later (from another
#: thread) via :meth:`CoreConn.reply` — the batcher-callback shape.
ASYNC = object()

#: accept() errnos that are per-connection transients: the aborted peer is
#: gone, the listener is fine — skip and keep accepting.
_ACCEPT_TRANSIENT = {errno.ECONNABORTED, errno.EINTR, errno.EPROTO, errno.EPERM}

#: Upper bound on one request frame (name + payload); a frame announcing
#: more than this is a corrupt/malicious peer and the connection drops.
MAX_FRAME_BYTES = 1 << 30


class Service:
    """One entry in the core's handler table.

    ``handler(conn, op, name, a, b, payload) -> (status, bufs) | ASYNC``
    runs on a pool worker; ``payload`` is the request's raw payload as a
    bytes-like buffer (empty when none; treat it as read-only).
    Returning :data:`ASYNC` means the handler handed the frame to
    another thread which will call ``conn.reply`` exactly once.

    ``control_ops``   op codes excluded from the request counter — derive
                      it from ``wire.CONTROL_OPS`` (the one registry; the
                      dtxlint control pass pins the derivation sites).
    ``counts_fn``     optional extra exclusion an op code cannot express
                      (``fn(op, name, a, b) -> bool``; False = uncounted).
    ``error_status``  the status replied when a handler raises.
    ``accept_dtypes`` HELLO dtype codes this service negotiates.
    ``max_payload``   per-service request-payload bound, checked the
                      moment a frame HEADER completes — an announced
                      payload past it drops the connection BEFORE any
                      byte of it is buffered, so a bogus length costs
                      nothing (size it to the service's real needs:
                      small for payload-less wires like dsvc, batch-
                      sized for predict).

    Admission policy (r18; control ops are exempt from all three):

    ``queue_deadline_s``      how long a dispatched request may WAIT for
                              a worker before it is shed with
                              RETRY_LATER (None = only the caller's
                              stamped deadline applies; the effective
                              budget is the min of the two).
    ``max_inflight_per_conn`` dispatched-unanswered requests one
                              connection may hold; pipelined excess is
                              shed (order-preserving), so one aggressive
                              peer cannot monopolize the dispatch queue.
    ``retry_after_ms``        the backoff hint shed answers carry
                              (``wire.retry_later_status``).
    ``tenant_of``             multi-tenancy (r20): ``fn(op, name, a, b)
                              -> tenant`` attributes each data-plane
                              frame to its tenant (off the key prefix /
                              name tag the service's wire carries); None
                              = every frame is the default tenant.  The
                              tenant keys the core's weighted-fair
                              dispatch and per-tenant quotas.
    """

    __slots__ = (
        "name", "handler", "control_ops", "counts_fn", "error_status",
        "accept_dtypes", "max_payload", "on_disconnect",
        "queue_deadline_s", "max_inflight_per_conn", "retry_after_ms",
        "hello_extra", "tenant_of",
    )

    def __init__(
        self, name: str, handler: Callable, *,
        control_ops: frozenset[int] = frozenset(),
        counts_fn: Callable | None = None, error_status: int = -2,
        accept_dtypes: tuple[int, ...] = (0,),
        max_payload: int = MAX_FRAME_BYTES,
        on_disconnect: Callable | None = None,
        queue_deadline_s: float | None = None,
        max_inflight_per_conn: int = 16,
        retry_after_ms: int = 50,
        hello_extra: Callable | None = None,
        tenant_of: Callable | None = None,
    ):
        if name not in wire.SERVICE_IDS:
            raise ValueError(
                f"unknown service {name!r} (wire.SERVICE_IDS has "
                f"{sorted(wire.SERVICE_IDS)})"
            )
        self.name = name
        self.handler = handler
        self.control_ops = frozenset(control_ops)
        self.counts_fn = counts_fn
        self.error_status = error_status
        self.accept_dtypes = tuple(accept_dtypes)
        self.max_payload = min(int(max_payload), MAX_FRAME_BYTES)
        self.on_disconnect = on_disconnect
        self.queue_deadline_s = (
            None if queue_deadline_s is None else float(queue_deadline_s)
        )
        self.max_inflight_per_conn = max(1, int(max_inflight_per_conn))
        self.retry_after_ms = max(0, int(retry_after_ms))
        # Extra bytes appended to the HELLO success tag (the msrv model-
        # version word, r19): called per HELLO on the selector thread, so
        # it must be cheap and never raise.
        self.hello_extra = hello_extra
        self.tenant_of = tenant_of


class CoreConn:
    """One live connection: parse state + write buffer + identity.

    Responses are SEQUENCE-ORDERED (r18): every parsed frame gets the
    connection's next sequence number, replies park in ``parked`` until
    every earlier sequence has answered, and only then flush into the
    write buffer — so concurrent handlers (up to the per-connection
    in-flight cap) and immediate shed answers can never reorder the
    response stream of a pipelining peer."""

    __slots__ = (
        "core", "sock", "fd", "service", "rbuf", "pending", "pbuf", "pfill",
        "out", "out_bytes", "inflight", "next_seq", "next_out", "parked",
        "closed", "events", "peer", "last_progress",
    )

    def __init__(self, core: "ServerCore", sock: socket.socket, service):
        self.core = core
        self.sock = sock
        self.fd = sock.fileno()
        self.service = service  # Service | None (resolved at HELLO)
        self.rbuf = bytearray()
        # Mid-payload parse state: once a frame HEADER completes, the
        # payload fills a dedicated preallocated buffer — the bulk is
        # recv_into'd straight into it (one copy, no rbuf growth, no
        # re-copy on the selector thread).
        self.pending = None  # (op, name, a, b, deadline_ms) awaiting payload
        self.pbuf: bytearray | None = None
        self.pfill = 0
        self.out: deque = deque()  # memoryviews awaiting the selector flush
        self.out_bytes = 0
        self.inflight = 0  # dispatched frames awaiting their replies
        self.next_seq = 0  # sequence assigned to the next parsed frame
        self.next_out = 0  # next sequence allowed onto the wire
        self.parked: dict[int, list] = {}  # seq -> encoded reply views
        self.closed = False
        self.events = 0  # selector interest currently registered
        self.last_progress = time.monotonic()  # last byte the peer drained
        try:
            self.peer = sock.getpeername()
        except OSError:
            self.peer = ("?", 0)


class _ReplyHandle:
    """The per-request ``conn`` a handler receives: :meth:`reply` is bound
    to that request's response SLOT in the connection's ordered stream
    (thread-safe, callable from any thread — the async batcher-callback
    shape), and everything else delegates to the underlying
    :class:`CoreConn`.  A second reply to the same slot is a no-op, so a
    timeout sweep racing the genuine resolution stays safe."""

    __slots__ = ("_conn", "_seq")

    def __init__(self, conn: CoreConn, seq: int):
        self._conn = conn
        self._seq = seq

    def reply(self, status: int, bufs: list | None = None) -> None:
        """Queue this request's response frame.  The selector thread
        flushes it (in sequence order) as the peer drains — the caller
        NEVER blocks on the peer's read speed."""
        self._conn.core._queue_reply(
            self._conn, self._seq, status, bufs, dispatched=True
        )

    def __getattr__(self, item):
        return getattr(self._conn, item)


class ServerCore:
    """The selector-driven server runtime.  Construct, :meth:`add_service`,
    :meth:`start`; tear down with :meth:`stop` (drains first)."""

    def __init__(
        self, *, port: int = 0, loopback_only: bool = True,
        workers: int = 8, backlog: int = 128, name: str = "core",
        accept_backoff_s: float = 0.2, max_buffered_bytes: int = 256 << 20,
        slow_reader_grace_s: float = 30.0, bind_retry_s: float = 5.0,
        max_dispatch_depth: int = 512,
        tenant_quotas: dict[str, tenancy.TenantQuota] | None = None,
    ):
        self.name = name
        self._services: dict[str, Service] = {}
        self._default: Service | None = None
        self._n_workers = max(1, int(workers))
        self._accept_backoff_s = accept_backoff_s
        self._max_buffered = int(max_buffered_bytes)
        self._slow_grace_s = float(slow_reader_grace_s)
        self._max_dispatch_depth = max(1, int(max_dispatch_depth))
        self._next_slow_sweep = 0.0
        self._next_deadline_sweep = 0.0
        self._lock = threading.Lock()
        self._requests = 0
        self._accepts = 0
        self._accept_errors = 0
        self._dispatched = 0
        self._handler_errors = 0
        self._dropped_slow = 0
        # Shed accounting (r18): every admission refusal, by cause.
        self._shed_total = 0
        self._shed_dispatch_full = 0
        self._shed_inflight_cap = 0
        self._shed_quota = 0
        self._queue_deadline_drops = 0
        self._conns: dict[int, CoreConn] = {}
        self._dirty: queue.SimpleQueue = queue.SimpleQueue()
        # Two dispatch lanes under one condition: control-plane frames ride
        # the PRIORITY deque (never shed, preferred by every worker, owned
        # outright by the dedicated control worker); data-plane frames ride
        # PER-TENANT deques (r20) drained by STRIDE scheduling — each pop
        # advances the winning tenant's virtual time by 1/weight, so under
        # contention a weight-2 tenant drains twice as fast as a weight-1
        # tenant, an idle tenant costs nothing, and a newly-busy tenant
        # re-enters at the current virtual clock (no burst catch-up).  The
        # core-wide dispatch bound (``max_dispatch_depth``) spans ALL
        # tenant deques; ``tenant_quotas`` layers per-tenant in-flight /
        # queued caps on top (a tenant at quota is shed RETRY_LATER while
        # other tenants' traffic flows).  Pre-tenant posture is exactly
        # one "default" deque — byte-identical behavior.
        self._tasks_cond = threading.Condition()
        self._tenant_tasks: dict[str, deque] = {}
        self._tenant_vtime: dict[str, float] = {}
        self._vclock = 0.0
        self._ntasks = 0  # queued data-plane frames across all tenants
        self._ptasks: deque = deque()
        self._tenant_quotas = dict(tenant_quotas or {})
        # Per-tenant accounting (guarded by self._lock): request/shed
        # counters + live in-flight, keyed lazily as tenants appear.
        self._tenant_counters: dict[str, dict] = {}
        # (conn.fd, seq) -> tenant for every admitted-undispatched or
        # dispatched-unanswered frame, so the reply path can decrement
        # the right tenant's in-flight count.
        self._task_tenant: dict[tuple[int, int], str] = {}
        self._workers_stop = False
        self._stop_flag = False
        self._draining = False
        self._listener_retired = False
        self._accept_paused_until: float | None = None
        self._started = False
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # A supervised restart rebinds the dead incarnation's FIXED port;
        # lingering sockets can hold it briefly — retry within a short
        # window instead of failing the healing restart (the same posture
        # every pre-core server took).
        bind_deadline = time.monotonic() + (bind_retry_s if port else 0.0)
        while True:
            try:
                self._listener.bind(("127.0.0.1" if loopback_only else "", port))
                break
            except OSError:
                if time.monotonic() >= bind_deadline:
                    self._listener.close()
                    self._wake_r.close()
                    self._wake_w.close()
                    raise
                time.sleep(0.2)
        self._listener.listen(backlog)
        self._listener.setblocking(False)
        self.port = self._listener.getsockname()[1]
        self._threads: list[threading.Thread] = []

    # -- wiring ---------------------------------------------------------------

    def add_service(self, service: Service, *, default: bool = False) -> None:
        if self._started:
            raise RuntimeError("add_service before start()")
        self._services[service.name] = service
        if default or self._default is None:
            self._default = service

    def start(self) -> "ServerCore":
        if not self._services:
            raise RuntimeError("ServerCore needs at least one service")
        self._started = True
        self._sel.register(self._listener, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        t = threading.Thread(
            target=self._select_loop, daemon=True, name=f"dtx-{self.name}-io"
        )
        t.start()
        self._threads.append(t)
        for i in range(self._n_workers):
            w = threading.Thread(
                target=self._worker, daemon=True,
                name=f"dtx-{self.name}-w{i}",
            )
            w.start()
            self._threads.append(w)
        # The dedicated control worker (r18): serves ONLY the priority
        # lane, so control/observability ops are answered even when every
        # regular worker is wedged inside a slow handler — the cluster
        # stays observable at exactly the moment that matters.
        ctl = threading.Thread(
            target=self._worker, kwargs={"control_only": True}, daemon=True,
            name=f"dtx-{self.name}-ctl",
        )
        ctl.start()
        self._threads.append(ctl)
        log.info(
            "%s core on port %d (%d services, %d workers)",
            self.name, self.port, len(self._services), self._n_workers,
        )
        return self

    # -- accounting -----------------------------------------------------------

    def request_count(self) -> int:
        """Counted (data-plane) requests so far — the ``die:after_reqs``
        fault trigger, same contract as the native PS server's counter."""
        with self._lock:
            return self._requests

    def live_conns(self) -> int:
        with self._lock:
            return len(self._conns)

    def _tenant_counter_locked(self, tenant: str) -> dict:
        """The per-tenant counter row (created on first sight); caller
        holds ``self._lock``."""
        tc = self._tenant_counters.get(tenant)
        if tc is None:
            tc = self._tenant_counters[tenant] = {
                "requests": 0,
                "inflight": 0,
                "shed_total": 0,
                "shed_inflight_cap": 0,
                "shed_dispatch_full": 0,
                "shed_quota": 0,
                "queue_deadline_drops": 0,
            }
        return tc

    def core_stats(self) -> dict:
        """The uniform runtime-accounting shape every service's STATS
        answer folds in (one definition of what the counters mean)."""
        with self._lock:
            tenants = {}
            for t, tc in self._tenant_counters.items():
                row = dict(tc)
                dq = self._tenant_tasks.get(t)
                row["queued"] = len(dq) if dq else 0
                q = self._tenant_quotas.get(t)
                row["weight"] = q.weight if q else 1.0
                row["max_inflight"] = q.max_inflight if q else 0
                row["max_dispatch"] = q.max_dispatch if q else 0
                tenants[t] = row
            return {
                "requests": self._requests,
                "live_conns": len(self._conns),
                "accepts": self._accepts,
                "accept_errors": self._accept_errors,
                "dispatched": self._dispatched,
                "handler_errors": self._handler_errors,
                "dropped_slow_readers": self._dropped_slow,
                "worker_threads": self._n_workers,
                "dispatch_depth": self._ntasks + len(self._ptasks),
                "max_dispatch_depth": self._max_dispatch_depth,
                # Admission-control sheds (r18), by cause; shed_total is
                # their sum — the externally gated "requests answered
                # RETRY_LATER instead of served" counter.
                "shed_total": self._shed_total,
                "shed_dispatch_full": self._shed_dispatch_full,
                "shed_inflight_cap": self._shed_inflight_cap,
                "shed_quota": self._shed_quota,
                "queue_deadline_drops": self._queue_deadline_drops,
                "draining": 1 if self._draining else 0,
                # Per-tenant breakdown (r20): the same shed vocabulary,
                # per namespace — what dtxtop's tenants section renders.
                "tenants": tenants,
            }

    # -- lifecycle ------------------------------------------------------------

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except (BlockingIOError, OSError):
            pass  # pipe already full: the selector is waking anyway

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Stop accepting, let dispatched handlers finish and response
        buffers flush.  True when everything in flight completed inside
        the window — the zero-dropped-requests graceful half of stop."""
        self._draining = True
        self._wake()
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with self._lock:
                busy = any(
                    c.inflight or c.out or c.parked
                    for c in self._conns.values()
                )
            if (
                not busy
                and not self._ntasks
                and not self._ptasks
                and (self._listener_retired or not self._started)
            ):
                return True
            time.sleep(0.01)
        return False

    def stop(self, drain_s: float = 5.0) -> None:
        """Drain (bounded), then tear the runtime down and release the
        port before returning."""
        if self._started:
            self.drain(drain_s)
        self._stop_flag = True
        self._draining = True
        self._wake()
        io_thread = self._threads[0] if self._threads else None
        if io_thread is not None:
            io_thread.join(timeout=5.0)
        with self._tasks_cond:
            self._workers_stop = True
            self._tasks_cond.notify_all()
        for t in self._threads[1:]:
            t.join(timeout=5.0)
        # Single-threaded from here: close every socket and the listener.
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.closed = True
            try:
                c.sock.close()
            except OSError:
                pass
        # shutdown() BEFORE close(): close alone does not free the kernel
        # socket while another thread is mid-syscall on it, which would
        # leave the port unavailable to a same-port supervised restart.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            self._sel.close()
        except OSError:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    # -- the selector loop ----------------------------------------------------

    def _select_loop(self) -> None:
        while not self._stop_flag:
            timeout = 0.5
            if self._accept_paused_until is not None:
                now = time.monotonic()
                if now >= self._accept_paused_until:
                    self._accept_paused_until = None
                    if not self._draining:
                        try:
                            self._sel.register(
                                self._listener, selectors.EVENT_READ, "accept"
                            )
                        except (KeyError, ValueError, OSError):
                            pass
                else:
                    timeout = min(timeout, self._accept_paused_until - now)
            try:
                events = self._sel.select(timeout)
            except OSError:
                continue
            for key, mask in events:
                tag = key.data
                if tag == "accept":
                    if self._draining:
                        self._retire_listener()
                    else:
                        self._do_accept()
                elif tag == "wake":
                    self._drain_wake()
                else:
                    conn: CoreConn = tag
                    if mask & selectors.EVENT_READ:
                        self._do_read(conn)
                    if mask & selectors.EVENT_WRITE and not conn.closed:
                        self._do_write(conn)
            self._process_dirty()
            self._sweep_slow_readers()
            self._sweep_queue_deadlines()
            if self._draining:
                self._retire_listener()

    def _unregister_listener(self) -> None:
        try:
            self._sel.unregister(self._listener)
        except (KeyError, ValueError, OSError):
            pass

    def _retire_listener(self) -> None:
        """Drain half of shutdown: actually CLOSE the listener (an
        unregister alone leaves the kernel completing handshakes into the
        backlog), so new connections are refused while in-flight work
        finishes.  Idempotent; runs on the selector thread."""
        if self._listener_retired:
            return
        self._listener_retired = True
        self._unregister_listener()
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _process_dirty(self) -> None:
        """Connections whose reply() landed since the last pass: flush
        eagerly, update interest, and parse any already-buffered next
        frame (the peer may have pipelined)."""
        while True:
            try:
                conn = self._dirty.get_nowait()
            except queue.Empty:
                return
            if conn.closed:
                continue
            self._do_write(conn)
            if not conn.closed:
                self._pump(conn)

    # -- accept ---------------------------------------------------------------

    def _do_accept(self) -> None:
        for _ in range(64):  # bounded per event: reads must not starve
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError as e:
                if self._stop_flag or self._draining:
                    return
                with self._lock:
                    self._accept_errors += 1
                if e.errno in _ACCEPT_TRANSIENT:
                    # The aborted peer is gone; the listener is fine.
                    continue
                # EMFILE/ENFILE/ENOBUFS/ENOMEM (or anything unexpected):
                # resource exhaustion.  Back off and resume — the one
                # thing the accept path must never do is die and leave a
                # healthy service unreachable forever.
                log.warning(
                    "%s core: accept failed (%s) — backing off %.1fs, "
                    "listener stays up",
                    self.name, e, self._accept_backoff_s,
                )
                self._unregister_listener()
                self._accept_paused_until = (
                    time.monotonic() + self._accept_backoff_s
                )
                return
            try:
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                sock.close()
                continue
            conn = CoreConn(
                self, sock,
                self._default if len(self._services) == 1 else None,
            )
            with self._lock:
                self._conns[conn.fd] = conn
                self._accepts += 1
            self._sel.register(sock, selectors.EVENT_READ, conn)
            conn.events = selectors.EVENT_READ

    # -- read / parse / dispatch ----------------------------------------------

    def _do_read(self, conn: CoreConn) -> None:
        if conn.pbuf is not None and conn.pfill < len(conn.pbuf):
            # Bulk payload path: straight into the frame's preallocated
            # buffer — one kernel-to-user copy, nothing staged in rbuf,
            # trailing pipelined bytes stay in the kernel for later.
            try:
                n = conn.sock.recv_into(memoryview(conn.pbuf)[conn.pfill :])
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._close_conn(conn)
                return
            if not n:
                self._close_conn(conn)
                return
            conn.pfill += n
            self._pump(conn)
            return
        try:
            data = conn.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        conn.rbuf += data
        self._pump(conn)

    @staticmethod
    def _parse_header(buf: bytearray, max_payload: int = MAX_FRAME_BYTES):
        """One complete request HEADER from ``buf``, or None.  Returns
        ``((op, name, a, b, plen, deadline_ms), consumed)`` — the
        incremental twin of ``wire.read_request``'s header half (r18:
        a ``wire.DEADLINE_FLAG``-stamped frame carries the caller's
        remaining per-op deadline after the standard tail; 0 = none).
        The payload bound is enforced HERE, the moment the header
        completes, before any payload byte would be buffered — an absurd
        announced length never costs memory."""
        if len(buf) < 2:
            return None
        nlen = buf[1]
        stamped = bool(buf[0] & wire.DEADLINE_FLAG)
        hdr_end = 2 + nlen + wire.REQ_TAIL.size
        if stamped:
            hdr_end += wire.DEADLINE_TAIL.size
        if len(buf) < hdr_end:
            return None
        a, b, plen = wire.REQ_TAIL.unpack_from(buf, 2 + nlen)
        deadline_ms = 0
        if stamped:
            (deadline_ms,) = wire.DEADLINE_TAIL.unpack_from(
                buf, 2 + nlen + wire.REQ_TAIL.size
            )
        if plen > max_payload:
            raise ValueError(
                f"frame announces {plen} payload bytes (bound {max_payload})"
            )
        name = bytes(buf[2 : 2 + nlen]).decode()
        return (
            (buf[0] & ~wire.DEADLINE_FLAG, name, a, b, plen, deadline_ms),
            hdr_end,
        )

    def _pump(self, conn: CoreConn) -> None:
        """Parse + ADMIT frames from the connection's read buffer (r18).
        Every parsed frame gets the connection's next response sequence;
        admission then either dispatches it (within the per-connection
        in-flight cap and the core-wide dispatch bound) or sheds it with
        the typed RETRY_LATER answer — which parks in sequence order, so
        a pipelining peer's response stream never reorders."""
        while not conn.closed:
            svc = conn.service or self._default
            if conn.pending is None:
                if self._parse_paused(conn):
                    break  # flood guard: stop parsing until replies flush
                try:
                    got = self._parse_header(conn.rbuf, svc.max_payload)
                except (ValueError, struct.error, UnicodeDecodeError):
                    self._close_conn(conn)
                    return
                if got is None:
                    break
                (op, name, a, b, plen, deadline_ms), consumed = got
                del conn.rbuf[:consumed]
                conn.pending = (op, name, a, b, deadline_ms)
                conn.pbuf = bytearray(plen)
                conn.pfill = 0
            # Whatever payload prefix already sits in rbuf moves over;
            # the rest arrives via the direct recv_into path above.
            need = len(conn.pbuf) - conn.pfill
            if need and conn.rbuf:
                take = min(need, len(conn.rbuf))
                conn.pbuf[conn.pfill : conn.pfill + take] = conn.rbuf[:take]
                del conn.rbuf[:take]
                conn.pfill += take
            if conn.pfill < len(conn.pbuf):
                break  # payload still in flight
            op, name, a, b, deadline_ms = conn.pending
            payload = conn.pbuf
            conn.pending, conn.pbuf, conn.pfill = None, None, 0
            seq = conn.next_seq
            conn.next_seq += 1
            if op == wire.HELLO_OP:
                self._handle_hello(conn, seq, a, b)
                continue
            control = op in svc.control_ops
            counted = not control and (
                svc.counts_fn is None or svc.counts_fn(op, name, a, b)
            )
            # Tenant attribution (r20): the service's tenant_of reads the
            # tenant off the frame (key prefix / name tag); anything it
            # cannot attribute — including a buggy hook — is the default
            # tenant, never a dropped frame.
            tenant = tenancy.DEFAULT_TENANT
            if not control and svc.tenant_of is not None:
                try:
                    tenant = svc.tenant_of(op, name, a, b) or tenant
                except Exception:  # noqa: BLE001 — attribution must not kill I/O
                    pass
            shed = None
            with self._lock:
                tc = self._tenant_counter_locked(tenant) if not control else None
                if counted:
                    self._requests += 1
                    tc["requests"] += 1
                if not control:
                    # Admission: control ops bypass every bound (priority
                    # class — never shed), data-plane frames must fit the
                    # per-connection in-flight cap, the core-wide dispatch
                    # bound, and the tenant's own quotas (r20) — a tenant
                    # at quota sheds while other tenants' traffic flows.
                    quota = self._tenant_quotas.get(tenant)
                    dq = self._tenant_tasks.get(tenant)
                    if conn.inflight >= svc.max_inflight_per_conn:
                        self._shed_inflight_cap += 1
                        self._shed_total += 1
                        tc["shed_inflight_cap"] += 1
                        tc["shed_total"] += 1
                        shed = svc.retry_after_ms
                    elif self._ntasks >= self._max_dispatch_depth:
                        self._shed_dispatch_full += 1
                        self._shed_total += 1
                        tc["shed_dispatch_full"] += 1
                        tc["shed_total"] += 1
                        shed = svc.retry_after_ms
                    elif quota is not None and (
                        (
                            quota.max_inflight
                            and tc["inflight"] >= quota.max_inflight
                        )
                        or (
                            quota.max_dispatch
                            and dq is not None
                            and len(dq) >= quota.max_dispatch
                        )
                    ):
                        self._shed_quota += 1
                        self._shed_total += 1
                        tc["shed_quota"] += 1
                        tc["shed_total"] += 1
                        shed = svc.retry_after_ms
                if shed is None:
                    self._dispatched += 1
                    conn.inflight += 1
                    if tc is not None:
                        tc["inflight"] += 1
                        self._task_tenant[(conn.fd, seq)] = tenant
            if shed is not None:
                self._queue_reply(
                    conn, seq, wire.retry_later_status(shed), None,
                    dispatched=False,
                )
                continue
            # The queue-deadline budget: the smaller of the service's
            # policy and the deadline the caller stamped on the wire —
            # a request that waits past it is shed before a worker
            # touches it (dequeue check + the selector's ~1/s sweep).
            budget = svc.queue_deadline_s
            if deadline_ms:
                stamped_s = deadline_ms / 1e3
                budget = stamped_s if budget is None else min(budget, stamped_s)
            t_shed = None if budget is None else time.monotonic() + budget
            task = (conn, svc, seq, t_shed, tenant, (op, name, a, b, payload))
            with self._tasks_cond:
                if control:
                    self._ptasks.append(task)
                else:
                    dq = self._tenant_tasks.get(tenant)
                    if dq is None:
                        dq = self._tenant_tasks[tenant] = deque()
                        self._tenant_vtime.setdefault(tenant, 0.0)
                    if not dq:
                        # Re-entering tenant starts at the current virtual
                        # clock: idle time earns no burst credit.
                        self._tenant_vtime[tenant] = max(
                            self._tenant_vtime[tenant], self._vclock
                        )
                    dq.append(task)
                    self._ntasks += 1
                # notify_all, not notify: a single notify can be consumed
                # by the CONTROL-ONLY worker, which cannot take a regular
                # task and would strand it until the 0.5s wait timeout.
                self._tasks_cond.notify_all()
        self._update_interest(conn)

    def _handle_hello(self, conn: CoreConn, seq: int, a: int, b: int) -> None:
        """HELLO answered inline on the selector thread (no payload, no
        handler work): the announced service identity routes the
        connection through the handler table; every mismatch goes
        through the one shared ``wire.hello_answer`` refusal."""
        expected = wire.hello_expected_service(b)
        svc = self._services.get(expected) or conn.service or self._default
        status, tag = wire.hello_answer(
            a, b, service=svc.name, accept_dtypes=svc.accept_dtypes
        )
        if status == wire.WIRE_VERSION:
            conn.service = svc
            if tag and svc.hello_extra is not None:
                tag = tag + svc.hello_extra()
        self._queue_reply(
            conn, seq, status, [tag] if tag else None, dispatched=False
        )

    def _queue_reply(
        self, conn: CoreConn, seq: int, status: int, bufs: list | None, *,
        dispatched: bool,
    ) -> None:
        """Park one response at its sequence slot and flush every
        now-contiguous reply into the write buffer (thread-safe; the one
        reply path for sync returns, async callbacks, HELLO and sheds).
        Encoding happens BEFORE any state changes, so a buffer the wire
        cannot encode raises to the caller with the slot still open —
        the caller's error reply is then the slot's first (and only)
        frame.  A second reply to an answered slot is a no-op."""
        views = wire.frames_to_views([
            wire.RESP_HDR.pack(status, wire.encoded_nbytes(bufs or [])),
            *(bufs or []),
        ])
        total = sum(len(v) for v in views)
        with self._lock:
            if conn.closed:
                return
            if seq < conn.next_out or seq in conn.parked:
                return  # already answered (idempotent late resolve)
            conn.parked[seq] = views
            # Parked bytes count toward the slow-reader bound: they are
            # committed response memory whether or not flushable yet.
            conn.out_bytes += total
            if dispatched:
                conn.inflight -= 1
                t = self._task_tenant.pop((conn.fd, seq), None)
                if t is not None:
                    tc = self._tenant_counters.get(t)
                    if tc is not None and tc["inflight"] > 0:
                        tc["inflight"] -= 1
            while conn.next_out in conn.parked:
                conn.out.extend(conn.parked.pop(conn.next_out))
                conn.next_out += 1
        self._dirty.put(conn)
        self._wake()

    def _shed_task(self, task, *, cause: str) -> None:
        """Answer one queued task RETRY_LATER without running its handler
        (the queue-deadline drop path; counted by cause, globally and on
        the owning tenant's row)."""
        conn, svc, seq, _t_shed, tenant, _req = task
        with self._lock:
            self._shed_total += 1
            tc = self._tenant_counter_locked(tenant)
            tc["shed_total"] += 1
            if cause == "queue_deadline":
                self._queue_deadline_drops += 1
                tc["queue_deadline_drops"] += 1
        self._queue_reply(
            conn, seq, wire.retry_later_status(svc.retry_after_ms), None,
            dispatched=True,
        )

    def _sweep_queue_deadlines(self) -> None:
        """Shed queued data-plane requests whose deadline budget expired
        while they WAITED (~1/s, on the selector thread): even with every
        worker wedged, an abandoned request gets its RETRY_LATER answer
        instead of silently aging in the queue.  The dequeue-time check
        in the worker covers the fast path; this sweep covers the
        pathological one."""
        now = time.monotonic()
        if now < self._next_deadline_sweep:
            return
        self._next_deadline_sweep = now + 1.0
        expired: list = []
        with self._tasks_cond:
            if not self._ntasks:
                return
            for tenant, dq in self._tenant_tasks.items():
                if not dq:
                    continue
                keep: deque = deque()
                for task in dq:
                    t_shed = task[3]
                    if t_shed is not None and now > t_shed:
                        expired.append(task)
                    else:
                        keep.append(task)
                if len(keep) != len(dq):
                    self._tenant_tasks[tenant] = keep
            self._ntasks -= len(expired)
        for task in expired:
            self._shed_task(task, cause="queue_deadline")

    # -- write ----------------------------------------------------------------

    def _do_write(self, conn: CoreConn) -> None:
        while conn.out:
            head = conn.out[0]
            try:
                n = conn.sock.send(head)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_conn(conn)
                return
            if n:
                conn.last_progress = time.monotonic()
            with self._lock:
                conn.out_bytes -= n
            if n < len(head):
                conn.out[0] = head[n:]
                break
            conn.out.popleft()
        self._update_interest(conn)

    def _sweep_slow_readers(self) -> None:
        """Drop peers that hold more than ``max_buffered_bytes`` of
        undelivered response AND have drained nothing for
        ``slow_reader_grace_s`` — a stalled scraper must not hold server
        memory hostage (resilient clients reconnect).  The progress
        condition is what distinguishes a stall from one legitimately
        large reply streaming to a healthy reader: size alone must never
        drop a connection the peer is actively draining."""
        now = time.monotonic()
        if now < self._next_slow_sweep:
            return
        self._next_slow_sweep = now + 1.0
        with self._lock:
            over = [
                c for c in self._conns.values()
                if c.out_bytes > self._max_buffered
                and now - c.last_progress > self._slow_grace_s
            ]
        for conn in over:
            log.warning(
                "%s core: dropping %s — %d bytes buffered past the "
                "%d-byte bound with no read progress for %.0fs",
                self.name, conn.peer, conn.out_bytes, self._max_buffered,
                now - conn.last_progress,
            )
            with self._lock:
                self._dropped_slow += 1
            self._close_conn(conn)

    @staticmethod
    def _parse_paused(conn: CoreConn) -> bool:
        """Whether this connection's parse is paused (kernel
        backpressure): too many replies parked out-of-order, or too many
        frames in flight.  The in-flight bound matters for CONTROL ops —
        they are never shed, so a peer pipelining STATS/LEASE_* at line
        rate must be slowed by the socket, not grow the priority lane
        unboundedly.  Data-plane frames hit the (much smaller) admission
        caps first; this is the outer memory bound."""
        return len(conn.parked) >= 256 or conn.inflight >= 256

    def _update_interest(self, conn: CoreConn) -> None:
        if conn.closed:
            return
        want = 0
        # Reading stays on even at the data-plane in-flight cap — excess
        # frames are SHED (admission control), not kernel-back-pressured;
        # only the parse-pause flood bounds (parked replies / total
        # in-flight frames) stop the read.
        if not self._parse_paused(conn):
            want |= selectors.EVENT_READ
        if conn.out:
            want |= selectors.EVENT_WRITE
        if want == conn.events:
            return
        try:
            if conn.events == 0 and want:
                self._sel.register(conn.sock, want, conn)
            elif want == 0:
                self._sel.unregister(conn.sock)
            else:
                self._sel.modify(conn.sock, want, conn)
            conn.events = want
        except (KeyError, ValueError, OSError):
            self._close_conn(conn)

    def _close_conn(self, conn: CoreConn) -> None:
        if conn.closed:
            return
        conn.closed = True
        with self._lock:
            self._conns.pop(conn.fd, None)
            conn.out.clear()
            conn.parked.clear()
            conn.out_bytes = 0
            # Release the dead connection's per-tenant in-flight slots —
            # its replies will never come back through _queue_reply (and
            # the fd may be reused by a future connection's key space).
            stale = [k for k in self._task_tenant if k[0] == conn.fd]
            for k in stale:
                tc = self._tenant_counters.get(self._task_tenant.pop(k))
                if tc is not None and tc["inflight"] > 0:
                    tc["inflight"] -= 1
        if conn.events:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
            conn.events = 0
        try:
            conn.sock.close()
        except OSError:
            pass
        svc = conn.service or self._default
        if svc is not None and svc.on_disconnect is not None:
            try:
                svc.on_disconnect(conn)
            except Exception:  # noqa: BLE001 — a cleanup hook never kills I/O
                log.exception("%s core: on_disconnect hook failed", self.name)

    # -- the worker pool ------------------------------------------------------

    def _pop_fair_locked(self):
        """Stride-scheduled pop across the tenant deques (caller holds
        ``_tasks_cond``): the non-empty tenant with the smallest virtual
        time wins, and its clock advances by 1/weight — proportional
        share under contention, zero cost while idle.  None = no
        data-plane work queued."""
        best = None
        for t, dq in self._tenant_tasks.items():
            if dq and (
                best is None or self._tenant_vtime[t] < self._tenant_vtime[best]
            ):
                best = t
        if best is None:
            return None
        quota = self._tenant_quotas.get(best)
        self._tenant_vtime[best] += 1.0 / (quota.weight if quota else 1.0)
        self._vclock = self._tenant_vtime[best]
        self._ntasks -= 1
        return self._tenant_tasks[best].popleft()

    def _next_task(self, control_only: bool):
        """Pop the next task: the priority lane first (every worker), the
        weighted-fair tenant lanes only for regular workers.  None =
        shutting down."""
        with self._tasks_cond:
            while True:
                if self._workers_stop:
                    return None
                if self._ptasks:
                    return self._ptasks.popleft()
                if not control_only:
                    task = self._pop_fair_locked()
                    if task is not None:
                        return task
                self._tasks_cond.wait(timeout=0.5)

    def _worker(self, control_only: bool = False) -> None:
        while True:
            item = self._next_task(control_only)
            if item is None:
                return
            conn, svc, seq, t_shed, _tenant, (op, name, a, b, payload) = item
            if conn.closed:
                continue
            if t_shed is not None and time.monotonic() > t_shed:
                # The request waited past its queue-deadline budget: the
                # caller has (or is about to have) abandoned it — shed
                # BEFORE the handler burns a worker on dead work.
                self._shed_task(item, cause="queue_deadline")
                continue
            handle = _ReplyHandle(conn, seq)
            try:
                # The unpack and the reply encode stay INSIDE the guard:
                # a malformed handler return (or a buffer reply() cannot
                # encode) must answer the same loud per-op error — an
                # escape here would kill the pool worker and wedge the
                # connection in flight forever.
                out = svc.handler(handle, op, name, a, b, payload)
                if out is ASYNC:
                    continue
                status, bufs = out
                handle.reply(status, bufs)
            except Exception:
                # A handler bug must surface as a LOUD per-op error on
                # the client, not a silent connection close the client
                # burns its reconnect budget retrying (the shared posture
                # all pre-core servers converged on).
                log.exception(
                    "%s core: %s op %d (%s) failed server-side",
                    self.name, svc.name, op, name,
                )
                with self._lock:
                    self._handler_errors += 1
                handle.reply(svc.error_status, None)
