"""TPU-native distributed-training framework.

A brand-new JAX/XLA/pjit/Pallas framework providing the capabilities of the
reference example suite ``Xingskcs/Distributed-TensorFlow-Examples`` (five
distributed-training workloads: MNIST MLP sync data-parallel, CIFAR-10 CNN
async parameter-server, ResNet-50 ImageNet, word2vec with a PS-sharded
embedding table, PTB LSTM multi-worker) — re-designed TPU-first:

- PS/worker gRPC topology       -> single-controller SPMD over a named ``Mesh``
- ``replica_device_setter``     -> ``NamedSharding`` placement rules
- ``SyncReplicasOptimizer``     -> ``psum`` over ICI inside the compiled step
- ``MirroredStrategy``/NCCL     -> XLA collectives emitted by ``jit``
- ``MonitoredTrainingSession``  -> ``train.TrainSession`` + hook system
- ``tf.data`` input pipelines   -> per-host sharded pipelines + device infeed

Reference capability map: see ``SURVEY.md`` (repo root) sections 1-3; the
blueprint for this layout is ``SURVEY.md`` section 7.
"""

__version__ = "0.1.0"

from . import parallel  # noqa: F401
from . import data  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from . import serve  # noqa: F401
from . import train  # noqa: F401
from . import utils  # noqa: F401
