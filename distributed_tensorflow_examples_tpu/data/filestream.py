"""File-backed out-of-core streaming input (SURVEY.md section 2c T7, section 7
hard-part #3).

``tf.data``'s real job in the reference stack is streaming datasets that do
not fit in host RAM: interleaved shard-file reads, parallel decode/augment,
and prefetch ahead of the accelerator.  ``InMemoryPipeline`` covers the
reference workloads whose datasets fit in RAM; this module is the on-disk
path:

- **Shard files** — a directory of ``shard-NNNNN.npz`` chunk files (or
  pickle chunks), each holding a slice of every field.  Only ONE chunk (plus
  the decode/prefetch queues) is resident per host at any time, so dataset
  size is bounded by disk, not RAM — the ``Dataset.interleave`` role.
- **Host sharding** — each host reads only ``files[pidx::pcount]`` (the
  ``Dataset.shard`` analog at file granularity: no host ever downloads rows
  it will not feed).
- **Reader thread** — loads the next chunk while the current one is being
  batched (``num_parallel_reads`` role).
- **Decode pool** — a thread pool maps ``decode_fn`` (decode / normalise /
  augment; NumPy releases the GIL for the bulk work) over batches, keeping
  several batches in flight while preserving order (``map(...,
  num_parallel_calls)`` role).
- Downstream, ``pipeline.prefetch_to_mesh`` overlaps the host->HBM transfer
  (the ``prefetch``/host-infeed role).

Shuffle follows the standard tf.data recipe for streamed data: shuffle the
FILE order per epoch + shuffle rows WITHIN each chunk, both from
deterministic per-epoch seeds every host agrees on (section 5.2 determinism).
This is approximate global shuffle (exact global shuffle would need the whole
epoch in RAM, which is the thing being avoided).
"""

from __future__ import annotations

import concurrent.futures
import glob as glob_lib
import os
import pickle
import queue
import threading
import time
from collections import deque
from typing import Callable, Iterator

import numpy as np

import jax

_SHARD_FMT = "shard-{:05d}.npz"


def write_array_shards(
    directory: str,
    arrays: dict[str, np.ndarray],
    *,
    rows_per_shard: int,
    compress: bool = False,
) -> list[str]:
    """Split field arrays into ``shard-NNNNN.npz`` chunk files under
    ``directory`` (the fixture writer / dataset converter)."""
    lengths = {k: len(v) for k, v in arrays.items()}
    if len(set(lengths.values())) != 1:
        raise ValueError(f"mismatched field lengths {lengths}")
    n = next(iter(lengths.values()))
    os.makedirs(directory, exist_ok=True)
    save = np.savez_compressed if compress else np.savez
    paths = []
    for i, start in enumerate(range(0, n, rows_per_shard)):
        path = os.path.join(directory, _SHARD_FMT.format(i))
        save(path, **{k: v[start : start + rows_per_shard] for k, v in arrays.items()})
        paths.append(path)
    return paths


def list_shards(directory: str, pattern: str = "shard-*") -> list[str]:
    """Sorted shard files under ``directory`` (npz or pickle chunks)."""
    files = sorted(glob_lib.glob(os.path.join(directory, pattern)))
    return [f for f in files if f.endswith((".npz", ".npy", ".pkl", ".pickle"))]


def load_chunk(path: str) -> dict[str, np.ndarray]:
    """Load one shard file fully into RAM (public: CLIs use it to hold out
    an eval shard)."""
    return _load_chunk(path)


def _load_chunk(path: str) -> dict[str, np.ndarray]:
    if path.endswith(".npz"):
        with np.load(path) as d:
            return {k: d[k] for k in d.files}
    if path.endswith((".pkl", ".pickle")):
        with open(path, "rb") as f:
            d = pickle.load(f)
        return {k: np.asarray(v) for k, v in d.items()}
    raise ValueError(f"unsupported shard format: {path}")


class FileStreamPipeline:
    """Out-of-core batch stream over shard files.

    Yields local (per-host) ``{field: np.ndarray}`` batches forever (or one
    epoch when ``repeat=False``); feed through ``pipeline.prefetch_to_mesh``
    for the device infeed.  ``batch_size`` is GLOBAL (divided by host count,
    like ``InMemoryPipeline``).

    ``stats`` counters (read anytime): ``chunks_loaded``, ``batches``,
    ``consumer_waits`` — the number of times the consumer found no decoded
    batch ready (prefetch starvation; the no-starvation test asserts this
    stays at ~0 when decode keeps up), and ``read_wait_s`` — time the batcher
    spent blocked on disk reads.
    """

    def __init__(
        self,
        files: list[str] | str,
        *,
        batch_size: int,
        decode_fn: Callable[[dict[str, np.ndarray]], dict[str, np.ndarray]] | None = None,
        shuffle: bool = True,
        seed: int = 0,
        repeat: bool = True,
        drop_remainder: bool = True,
        num_decode_workers: int = 2,
        read_ahead: int = 2,
        process_index: int | None = None,
        process_count: int | None = None,
    ):
        self.files = list_shards(files) if isinstance(files, str) else list(files)
        if not self.files:
            raise ValueError(f"no shard files in {files!r}")
        self.pidx = jax.process_index() if process_index is None else process_index
        self.pcount = jax.process_count() if process_count is None else process_count
        if batch_size % self.pcount:
            raise ValueError(
                f"global batch {batch_size} not divisible by {self.pcount} hosts"
            )
        self.local_batch = batch_size // self.pcount
        self.decode_fn = decode_fn
        self.shuffle = shuffle
        self.seed = seed
        self.repeat = repeat
        self.drop_remainder = drop_remainder
        self.num_decode_workers = max(1, num_decode_workers)
        self.read_ahead = max(1, read_ahead)
        self.stats = {
            "chunks_loaded": 0,
            "batches": 0,
            "consumer_waits": 0,
            "read_wait_s": 0.0,
        }

    # -- epoch plumbing ------------------------------------------------------

    def _epoch_files(self, epoch: int) -> list[str]:
        """This host's file list for ``epoch`` (deterministic shuffle all
        hosts agree on, then stride-shard by host)."""
        order = np.arange(len(self.files))
        if self.shuffle:
            order = np.random.default_rng((self.seed, epoch)).permutation(order)
        elif len(order) % self.pcount:
            # Unshuffled + uneven file count: rotate per epoch so the
            # truncated tail file CYCLES instead of the same file being
            # dropped forever (silent permanent data loss otherwise).
            order = np.roll(order, -(epoch % len(order)))
        if len(self.files) >= self.pcount:
            order = order[: len(order) - (len(order) % self.pcount)]
            mine = order[self.pidx :: self.pcount]
        else:
            # Fewer files than hosts: every host reads all files and strides
            # ROWS instead (handled in _chunk_rows) — correct, just no IO win.
            mine = order
        return [self.files[i] for i in mine]

    def _chunk_rows(self, chunk: dict[str, np.ndarray], epoch: int, ci: int):
        n = len(next(iter(chunk.values())))
        order = np.arange(n)
        if self.shuffle:
            order = np.random.default_rng((self.seed, epoch, ci)).permutation(order)
        if len(self.files) < self.pcount:
            order = order[: n - (n % self.pcount)][self.pidx :: self.pcount]
        return {k: v[order] for k, v in chunk.items()}

    def _reader(self, epoch: int, out: queue.Queue, stop: threading.Event):
        """Loads this epoch's chunks into ``out`` ahead of the batcher.

        Every put polls ``stop`` so an abandoned consumer (break mid-epoch)
        can never leave this thread blocked on a full queue."""

        def _put(item) -> bool:
            while True:
                try:
                    out.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    if stop.is_set():
                        return False

        try:
            for ci, path in enumerate(self._epoch_files(epoch)):
                if stop.is_set():
                    return
                chunk = self._chunk_rows(_load_chunk(path), epoch, ci)
                self.stats["chunks_loaded"] += 1
                if not _put(chunk):
                    return
        except Exception as e:  # surfaced by the batcher
            _put(e)
        finally:
            _put(None)

    def _epoch_batches(self, epoch: int) -> Iterator[dict[str, np.ndarray]]:
        """Undecoded local batches for one epoch; carries remainder rows
        across chunk boundaries so only the epoch tail is ever dropped."""
        q: queue.Queue = queue.Queue(maxsize=self.read_ahead)
        stop = threading.Event()
        t = threading.Thread(
            target=self._reader, args=(epoch, q, stop), daemon=True,
            name="filestream-reader",
        )
        t.start()
        carry: dict[str, np.ndarray] | None = None
        try:
            while True:
                t0 = time.perf_counter()
                chunk = q.get()
                self.stats["read_wait_s"] += time.perf_counter() - t0
                if chunk is None:
                    break
                if isinstance(chunk, Exception):
                    raise chunk
                if carry is not None:
                    chunk = {
                        k: np.concatenate([carry[k], v]) for k, v in chunk.items()
                    }
                n = len(next(iter(chunk.values())))
                b = self.local_batch
                for s in range(n // b):
                    yield {k: v[s * b : (s + 1) * b] for k, v in chunk.items()}
                rem = n % b
                carry = {k: v[n - rem :] for k, v in chunk.items()} if rem else None
            if carry is not None and not self.drop_remainder:
                yield carry
        finally:
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    # -- public iterator -----------------------------------------------------

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.num_decode_workers, thread_name_prefix="filestream-decode"
        )
        decode = self.decode_fn or (lambda b: b)
        in_flight: deque = deque()
        depth = self.num_decode_workers + 2
        try:
            epoch = 0
            while True:
                for raw in self._epoch_batches(epoch):
                    in_flight.append(pool.submit(decode, raw))
                    if len(in_flight) >= depth:
                        fut = in_flight.popleft()
                        if not fut.done():
                            self.stats["consumer_waits"] += 1
                        self.stats["batches"] += 1
                        yield fut.result()
                epoch += 1
                if not self.repeat:
                    break
            while in_flight:
                fut = in_flight.popleft()
                if not fut.done():
                    self.stats["consumer_waits"] += 1
                self.stats["batches"] += 1
                yield fut.result()
        finally:
            for fut in in_flight:
                fut.cancel()
            pool.shutdown(wait=False, cancel_futures=True)


# ----------------------------------------------------------------------------
# Standard decoders (the `.map()` bodies of the reference's input fns)
# ----------------------------------------------------------------------------


def image_decode_fn(
    *,
    augment: bool = False,
    seed: int = 0,
    dtype=np.float32,
    scale: float = 1.0 / 255.0,
    mean: float = 0.5,
):
    """uint8 image chunks -> normalised float batches, with optional random
    horizontal-flip augmentation (the CIFAR/ImageNet train-time map).

    Decode runs on a thread pool, so each call derives its own Generator —
    numpy Generators are not thread-safe — seeded from (seed, batch content):
    deterministic for a given batch no matter which worker thread runs it or
    in what order."""
    import zlib

    def decode(batch: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
        tag = zlib.adler32(batch["image"][:4].tobytes())
        rng = np.random.default_rng((seed, tag))
        out = dict(batch)
        img = batch["image"]
        if img.dtype == np.uint8:
            img = img.astype(dtype) * scale - mean
        else:
            img = img.astype(dtype)
        if augment:
            flip = rng.random(len(img)) < 0.5
            img[flip] = img[flip, :, ::-1]
        out["image"] = img
        if "label" in out:
            out["label"] = out["label"].astype(np.int32)
        return out

    return decode


# ----------------------------------------------------------------------------
# Streamed tokenised text (W4/W5 corpora too large for RAM)
# ----------------------------------------------------------------------------


def stream_token_ids(
    paths: list[str] | str,
    *,
    vocab: dict[str, int],
    chunk_words: int = 1 << 20,
) -> Iterator[np.ndarray]:
    """Tokenise text file(s) incrementally: yields int32 id chunks without
    ever holding the whole corpus (the TextLineDataset -> lookup-table map).
    Words absent from ``vocab`` map to id 0 (<unk>)."""
    if isinstance(paths, str):
        paths = [paths]
    buf: list[str] = []
    for path in paths:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            tail = ""
            while True:
                text = f.read(1 << 22)  # 4 MB of characters at a time
                if not text:
                    break
                text = tail + text
                # Keep a possibly-split trailing word for the next read.
                cut = len(text)
                while cut > 0 and not text[cut - 1].isspace():
                    cut -= 1
                tail = text[cut:]
                buf.extend(text[:cut].split())
                while len(buf) >= chunk_words:
                    yield np.asarray(
                        [vocab.get(w, 0) for w in buf[:chunk_words]], np.int32
                    )
                    del buf[:chunk_words]
            if tail:
                buf.append(tail)
    if buf:
        yield np.asarray([vocab.get(w, 0) for w in buf], np.int32)


def streamed_skipgram_batches(
    id_chunks,
    *,
    batch_size: int,
    window: int = 5,
    seed: int = 0,
) -> Iterator[dict[str, np.ndarray]]:
    """Skip-gram pair stream over token-id chunks (out-of-core analog of
    ``datasets.skipgram_batches``): samples pairs within each chunk, chaining
    chunks forever.

    Pass a CALLABLE returning a fresh chunk iterator (e.g. ``lambda:
    stream_token_ids(path, vocab=v)``) to stay out-of-core across epochs —
    the corpus is re-streamed per epoch with only one chunk resident.  A
    plain iterator is accepted but gets buffered in RAM for the repeat
    (fine for corpora that fit; defeats out-of-core otherwise).
    """
    rng = np.random.default_rng(seed)
    if callable(id_chunks):
        while True:  # re-stream the corpus each epoch: one chunk resident
            produced = False
            for chunk in id_chunks():
                produced = True
                yield from _skipgram_from(chunk, batch_size, window, rng)
            if not produced:
                raise ValueError(
                    "empty token stream — the factory must return a FRESH "
                    "iterator each call (a reused exhausted generator yields "
                    "nothing on re-iteration)"
                )
    else:
        chunks = []
        for chunk in id_chunks:
            chunks.append(chunk)
            yield from _skipgram_from(chunk, batch_size, window, rng)
        if not chunks:
            raise ValueError("empty token stream")
        while True:  # corpus exhausted: cycle the buffered chunks
            for chunk in chunks:
                yield from _skipgram_from(chunk, batch_size, window, rng)


def _skipgram_from(ids: np.ndarray, batch_size: int, window: int, rng):
    n = len(ids)
    if n < 2 * window + 1:
        return
    # One pass worth of pairs: ~1 batch per batch_size tokens keeps epoch
    # cost linear in corpus size.
    for _ in range(max(1, n // batch_size)):
        centers = rng.integers(window, n - window, size=batch_size)
        offsets = rng.integers(1, window + 1, size=batch_size)
        signs = rng.choice([-1, 1], size=batch_size)
        yield {
            "center": ids[centers].astype(np.int32),
            "context": ids[centers + offsets * signs].astype(np.int32),
        }
