"""Input layer: the ``tf.data`` replacement (SURVEY.md section 1, L0).

Per-host sharding + batching + shuffling + device infeed with background
prefetch.  The reference's pipeline machinery (``Dataset.shard/batch/prefetch``,
``DistributedDataset`` per-replica iterators — SURVEY.md T7/D14) maps to:

- ``datasets``  — workload datasets (real files if present in ``--data_dir``,
  deterministic synthetic fallback otherwise, since this environment has no
  network egress).
- ``pipeline``  — ``InMemoryPipeline``/``prefetch_to_mesh``: every host loads
  only its shard, batches are assembled into *global* sharded ``jax.Array``s
  via ``make_array_from_process_local_data``, with a depth-2 background
  prefetcher overlapping host->HBM transfer with the running step.
- ``filestream`` — ``FileStreamPipeline``: the out-of-core path (datasets
  larger than host RAM stream from shard files with a reader thread + decode
  worker pool — tf.data's interleave/map/shard roles).
- ``data_service`` — the DISAGGREGATED path (tf.data service analog):
  dedicated input-worker processes own shards, decode and split assignment,
  and stream ready batches to training workers over the PS wire
  (``--data_dir=dsvc://host:port``).
"""

from .pipeline import InMemoryPipeline, prefetch_to_mesh  # noqa: F401
from .filestream import FileStreamPipeline  # noqa: F401
from . import data_service, datasets, filestream, native_loader, streams  # noqa: F401
