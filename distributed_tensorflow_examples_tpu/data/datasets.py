"""Workload datasets for the five reference configs (SURVEY.md section 2a).

Each loader looks for the standard on-disk format under ``data_dir`` and
falls back to a *deterministic synthetic* dataset with the same shapes/dtypes
and a learnable signal (so loss curves fall and accuracy targets are
meaningful in tests/benchmarks even with zero network egress).  The synthetic
fallback is clearly reported via the returned ``source`` field.

Formats accepted when real data is present:
- MNIST:   ``mnist.npz`` (keras layout: x_train/y_train/x_test/y_test)
- CIFAR10: ``cifar10.npz`` (same layout) or the python pickle batches dir
- PTB:     ``ptb.train.txt`` / ``ptb.valid.txt`` (word-level, <eos> per line)
- word2vec corpus: ``text8`` or any whitespace-tokenised text file
- ImageNet: not expected on disk; synthetic 224x224 stream at ResNet-50
  shapes (standard practice for infeed/throughput benchmarking).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
from typing import Iterator

import numpy as np


@dataclasses.dataclass
class ArrayDataset:
    train: dict[str, np.ndarray]
    test: dict[str, np.ndarray]
    source: str  # "file:<path>" or "synthetic"
    num_classes: int = 0
    vocab: dict | None = None


def _synth_image_splits(rng: np.random.Generator, n_train, n_test, h, w, c, num_classes):
    """Class-conditional Gaussian blobs: linearly separable enough that a
    correct model's accuracy rises quickly, while staying image-shaped.
    Train and test share the class prototypes (same distribution), so test
    accuracy is a meaningful generalisation signal."""
    protos = rng.normal(0.0, 1.0, size=(num_classes, h, w, c)).astype(np.float32)

    def draw(n):
        y = rng.integers(0, num_classes, size=n).astype(np.int32)
        x = 0.5 * protos[y] + rng.normal(0.0, 1.0, size=(n, h, w, c)).astype(np.float32)
        return x, y

    return draw(n_train), draw(n_test)


def mnist(data_dir: str | None = None, *, seed: int = 0) -> ArrayDataset:
    path = os.path.join(data_dir or "", "mnist.npz")
    if data_dir and os.path.exists(path):
        with np.load(path) as d:
            xt = (d["x_train"].astype(np.float32) / 255.0).reshape(-1, 28, 28, 1)
            xe = (d["x_test"].astype(np.float32) / 255.0).reshape(-1, 28, 28, 1)
            return ArrayDataset(
                {"image": xt, "label": d["y_train"].astype(np.int32)},
                {"image": xe, "label": d["y_test"].astype(np.int32)},
                f"file:{path}",
                num_classes=10,
            )
    rng = np.random.default_rng(seed)
    (xt, yt), (xe, ye) = _synth_image_splits(rng, 8192, 1024, 28, 28, 1, 10)
    return ArrayDataset(
        {"image": xt, "label": yt}, {"image": xe, "label": ye}, "synthetic", 10
    )


def cifar10(data_dir: str | None = None, *, seed: int = 0) -> ArrayDataset:
    if data_dir:
        npz = os.path.join(data_dir, "cifar10.npz")
        if os.path.exists(npz):
            with np.load(npz) as d:
                return ArrayDataset(
                    {
                        "image": d["x_train"].astype(np.float32) / 255.0,
                        "label": d["y_train"].reshape(-1).astype(np.int32),
                    },
                    {
                        "image": d["x_test"].astype(np.float32) / 255.0,
                        "label": d["y_test"].reshape(-1).astype(np.int32),
                    },
                    f"file:{npz}",
                    10,
                )
        batches = os.path.join(data_dir, "cifar-10-batches-py")
        if os.path.isdir(batches):
            xs, ys = [], []
            for i in range(1, 6):
                with open(os.path.join(batches, f"data_batch_{i}"), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(d[b"data"]), ys.append(d[b"labels"])
            x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            with open(os.path.join(batches, "test_batch"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xe = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
            return ArrayDataset(
                {
                    "image": x.astype(np.float32) / 255.0,
                    "label": np.concatenate(ys).astype(np.int32),
                },
                {
                    "image": xe.astype(np.float32) / 255.0,
                    "label": np.asarray(d[b"labels"], np.int32),
                },
                f"file:{batches}",
                10,
            )
    rng = np.random.default_rng(seed)
    (xt, yt), (xe, ye) = _synth_image_splits(rng, 8192, 1024, 32, 32, 3, 10)
    return ArrayDataset(
        {"image": xt, "label": yt}, {"image": xe, "label": ye}, "synthetic", 10
    )


def imagenet_synthetic(
    *,
    image_size: int = 224,
    n_train: int = 2048,
    n_test: int = 256,
    num_classes: int = 1000,
    seed: int = 0,
) -> ArrayDataset:
    """Synthetic ImageNet-shaped stream (W3 ResNet-50 throughput workload)."""
    rng = np.random.default_rng(seed)
    (xt, yt), (xe, ye) = _synth_image_splits(
        rng, n_train, n_test, image_size, image_size, 3, num_classes
    )
    return ArrayDataset(
        {"image": xt, "label": yt}, {"image": xe, "label": ye}, "synthetic", num_classes
    )


# ----------------------------------------------------------------------------
# Text corpora (W4 word2vec, W5 PTB LSTM)
# ----------------------------------------------------------------------------


def _tokenize_corpus(words: list[str], vocab_size: int):
    from collections import Counter

    counts = Counter(words)
    keep = [w for w, _ in counts.most_common(vocab_size - 1)]
    vocab = {w: i + 1 for i, w in enumerate(keep)}  # 0 = <unk>
    ids = np.asarray([vocab.get(w, 0) for w in words], dtype=np.int32)
    return ids, {"<unk>": 0, **vocab}


def _synthetic_token_stream(n: int, vocab_size: int, seed: int) -> np.ndarray:
    """Zipf-distributed token stream with bigram structure (so both skip-gram
    co-occurrence and LSTM next-token prediction have learnable signal)."""
    rng = np.random.default_rng(seed)
    # Markov chain: each token prefers a fixed successor half the time.
    succ = rng.permutation(vocab_size)
    zipf = rng.zipf(1.3, size=n).astype(np.int64) % vocab_size
    out = np.empty(n, dtype=np.int32)
    out[0] = zipf[0]
    follow = rng.random(n) < 0.5
    for i in range(1, n):
        out[i] = succ[out[i - 1]] if follow[i] else zipf[i]
    return out


def text_corpus(
    data_dir: str | None = None,
    *,
    filename_candidates=("text8", "corpus.txt"),
    vocab_size: int = 10000,
    synth_tokens: int = 200_000,
    seed: int = 0,
):
    """Token-id stream + vocab for word2vec (W4)."""
    if data_dir:
        for name in filename_candidates:
            path = os.path.join(data_dir, name)
            if os.path.exists(path):
                with open(path, "r", encoding="utf-8", errors="replace") as f:
                    words = f.read().split()
                ids, vocab = _tokenize_corpus(words, vocab_size)
                return ids, vocab, f"file:{path}"
    ids = _synthetic_token_stream(synth_tokens, vocab_size, seed)
    vocab = {f"tok{i}": i for i in range(vocab_size)}
    return ids, vocab, "synthetic"


def ptb(data_dir: str | None = None, *, vocab_size: int = 10000, seed: int = 0):
    """PTB word-level LM streams (W5): (train_ids, valid_ids, vocab, source)."""
    if data_dir:
        tr = os.path.join(data_dir, "ptb.train.txt")
        va = os.path.join(data_dir, "ptb.valid.txt")
        if os.path.exists(tr):
            with open(tr) as f:
                train_words = f.read().replace("\n", " <eos> ").split()
            valid_words = []
            if os.path.exists(va):
                with open(va) as f:
                    valid_words = f.read().replace("\n", " <eos> ").split()
            ids, vocab = _tokenize_corpus(train_words, vocab_size)
            vids = np.asarray([vocab.get(w, 0) for w in valid_words], np.int32)
            return ids, vids, vocab, f"file:{tr}"
    ids = _synthetic_token_stream(120_000, vocab_size, seed)
    vids = _synthetic_token_stream(12_000, vocab_size, seed + 1)
    return ids, vids, {f"tok{i}": i for i in range(vocab_size)}, "synthetic"


def lm_batches(
    ids: np.ndarray, *, batch_size: int, seq_len: int
) -> Iterator[dict[str, np.ndarray]]:
    """Truncated-BPTT batching: contiguous streams per batch row (the PTB
    convention), yielding {"x": [B,T], "y": [B,T]} forever.  Fully
    deterministic from the token array (no shuffling — PTB keeps corpus
    order)."""
    n = len(ids)
    rows = batch_size
    per_row = n // rows
    if per_row < seq_len + 1:
        raise ValueError(
            f"token stream too short: {n} ids over {rows} rows gives "
            f"{per_row} tokens/row, need seq_len+1={seq_len + 1}"
        )
    data = ids[: rows * per_row].reshape(rows, per_row)
    pos = 0
    while True:
        if pos + seq_len + 1 > per_row:
            pos = 0
        x = data[:, pos : pos + seq_len]
        y = data[:, pos + 1 : pos + seq_len + 1]
        pos += seq_len
        yield {"x": x.astype(np.int32), "y": y.astype(np.int32)}


def skipgram_batches(
    ids: np.ndarray,
    *,
    batch_size: int,
    window: int = 5,
    seed: int = 0,
) -> Iterator[dict[str, np.ndarray]]:
    """Skip-gram (center, context) pair stream for word2vec (W4)."""
    rng = np.random.default_rng(seed)
    n = len(ids)
    while True:
        centers = rng.integers(window, n - window, size=batch_size)
        offsets = rng.integers(1, window + 1, size=batch_size)
        signs = rng.choice([-1, 1], size=batch_size)
        contexts = centers + offsets * signs
        yield {
            "center": ids[centers].astype(np.int32),
            "context": ids[contexts].astype(np.int32),
        }
