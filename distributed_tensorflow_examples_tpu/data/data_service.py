"""Disaggregated data service: remote input workers streaming ready batches
over the PS wire (r8 tentpole).

The reference stack does ALL input processing in-process on each training
worker, which caps accelerator utilization the moment preprocessing outruns
one host — the problem tf.data service solves by moving the input pipeline
onto separate serving processes ("tf.data service: A Case for Disaggregating
ML Input Data Processing").  This module is that third leg of the training
stack (PS compute / transport / INPUT):

- :class:`DataServiceServer` — a dispatcher+worker-style data server: it
  owns **shard assignment** (first-come-first-served splits, per-epoch
  at-least-once visitation tracking) and streams decoded, batched data to
  training workers over the PR 2 wire machinery (``parallel/wire.py``:
  same framing, HELLO version negotiation, scatter/gather ``sendmsg`` out,
  ``recv_into`` straight into the destination arrays on the client).
- :class:`DataServiceClient` — the resilient transport: per-op deadlines,
  exponential-backoff reconnect bounded by ``reconnect_deadline_s`` (PR 1
  semantics extended to input), fault injection via ``DTX_FAULT_PLAN``
  (client role ``<role>_ds``), and incarnation tracking so a RESTARTED data
  server is detected and healed.
- :class:`RemoteDatasetSource` — the ``dsvc://host:port`` source that plugs
  into ``data/streams.py``'s resolution (fourth branch next to
  ``.dtxr``/``.npz``/fallback), with double-buffered prefetch modeled on
  ``async_ps.ParamPrefetcher`` and split re-claim on reconnect, so a data
  server kill+restart heals mid-epoch.

Wire notes (vs the PS wire): frame layout and HELLO are shared
(``parallel/wire.py``), but payload lengths count **bytes**, not elements —
batches carry mixed-dtype fields (uint8 images, int32 labels, f32 floats)
as raw bytes after a small JSON schema header, so the bf16 payload encoding
is unsound here and HELLO accepts only the f32 code.  The HELLO answer
carries a ``dsvc`` service tag so a client dialing the wrong service fails
loudly instead of misparsing op codes.

Split protocol (the dispatcher role):

- A **split** is one shard file (or in-RAM chunk): the unit of assignment.
  Batches within a split are deterministic in ``(seed, split)`` — NOT the
  epoch — so a worker resuming a re-claimed split after a server restart
  gets byte-identical batches at the same indices.
- ``GET_SPLIT(worker, ack)`` first acknowledges the worker's previous split
  (idempotent), then assigns the next pending split first-come-first-served.
  The op is **replay-safe**: a worker that already holds an unacknowledged
  split is handed THAT split again, so a response lost to a connection drop
  cannot strand an assignment.  ``-3`` = nothing pending right now (peers
  still draining) — poll; ``-4`` = the requested epoch is over.
- ``CLAIM_SPLIT(worker, split)`` re-requests a specific split after a
  reconnect lands on a new server incarnation (assignment state lost):
  answered claimed / already-completed / taken-by-another-worker.
- The epoch **rolls only when every split is acknowledged** — per-epoch
  at-least-once visitation by construction.  In steady state a split is
  never assigned to two workers; assignments are reassigned only when the
  assignee's liveness (any op naming it) goes stale, and duplicates can
  occur only across a failover (at-least-once, like the PS path's token
  re-push).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Iterator, Sequence

import numpy as np

from ..parallel import retry, server_core, tenancy, wire
from ..utils import faults, telemetry
from . import filestream

log = logging.getLogger("dtx.data_service")

# Op codes (DSVC_*) — aliases into the ONE registry (wire.DSVC_OPS).
# Disjoint from the PS server's 1..27 range except the shared HELLO code
# point, so a frame sent to the wrong service is refused, never
# misinterpreted.  tools/dtxlint enforces the disjointness and refuses a
# restated numeric literal outside parallel/wire.py.
DSVC_HELLO = wire.DSVC_OPS["HELLO"]
DSVC_REGISTER = wire.DSVC_OPS["REGISTER"]
DSVC_GET_SPLIT = wire.DSVC_OPS["GET_SPLIT"]
DSVC_CLAIM_SPLIT = wire.DSVC_OPS["CLAIM_SPLIT"]
DSVC_GET_BATCH = wire.DSVC_OPS["GET_BATCH"]
DSVC_HEARTBEAT = wire.DSVC_OPS["HEARTBEAT"]
DSVC_STATS = wire.DSVC_OPS["STATS"]
DSVC_GET_EVAL = wire.DSVC_OPS["GET_EVAL"]
DSVC_SHUTDOWN = wire.DSVC_OPS["SHUTDOWN"]

#: Ops excluded from the request counter — derived from the one
#: control-plane registry (wire.CONTROL_OPS; dtxlint pins this site).
_DSVC_CONTROL_OPS = frozenset(
    wire.DSVC_OPS[n] for n in wire.CONTROL_OPS["dsvc"]
)

#: HELLO answer payload: the service tag a client must verify (one shared
#: registry in parallel/wire.py — r10).
SERVICE_TAG = wire.SERVICE_TAGS["dsvc"]

# Response statuses (non-assignment ops: 0 ok, >0 op-specific, <0 error) —
# aliases into wire.DSVC_STATUS, the one definition site.
OK = wire.DSVC_STATUS["OK"]
END_OF_SPLIT = wire.DSVC_STATUS["END_OF_SPLIT"]
CLAIM_DONE = wire.DSVC_STATUS["CLAIM_DONE"]
CLAIM_TAKEN = wire.DSVC_STATUS["CLAIM_TAKEN"]
WAIT = wire.DSVC_STATUS["WAIT"]
EPOCH_ROLLED = wire.DSVC_STATUS["EPOCH_ROLLED"]
ERR = wire.DSVC_STATUS["ERR"]


def _tenant_of_request(op: int, name: str, a: int, b: int) -> str:
    """The server core's per-tenant admission attribution (r20): the
    tenant rides the ``name`` operand as a ``,t=<tenant>`` tag — absent
    (= the default tenant) on every untagged client's frames."""
    return tenancy.untag_name(name)[1]


class DSVCError(RuntimeError):
    """A data-service op failed terminally (transport unrecoverable or the
    server rejected the request)."""


class DSVCDeadlineError(DSVCError):
    """Reconnect budget exhausted: the data server stayed unreachable past
    ``reconnect_deadline_s``."""


class DSVCRejectedError(DSVCError):
    """The server ANSWERED and rejected the op (r17): the transport is
    fine, and retrying the same request buys nothing.  ``ERR`` is the
    server core's loud handler-failure status — a handler exception lands
    here instead of a silent connection close the client would burn its
    reconnect budget retrying."""


def parse_spec(spec: str) -> tuple[str, int]:
    """``dsvc://host:port`` -> (host, port)."""
    if not spec.startswith("dsvc://"):
        raise ValueError(f"not a data-service spec: {spec!r}")
    host, _, port = spec[len("dsvc://"):].rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"bad data-service spec {spec!r} (want dsvc://host:port)")
    return host, int(port)


# ----------------------------------------------------------------------------
# Batch codec: JSON schema header + raw field bytes (zero-copy both ways).
# One shared definition in parallel/wire.py (r10: the serving wire carries
# the same field-dict payloads); these names stay as the stable import
# point for tests and hosting code.
# ----------------------------------------------------------------------------

encode_batch = wire.encode_batch
encoded_nbytes = wire.encoded_nbytes
read_batch = wire.read_batch


# ----------------------------------------------------------------------------
# Server — dispatcher (split assignment) + worker (batch serving) in one
# ----------------------------------------------------------------------------


class _TenantJob:
    """One tenant's dispatcher state machine over the SHARED split set
    (r20 multi-tenancy): each tenant consumes the same splits as its own
    independent job — its own epoch, pending order, assignments,
    visitation and liveness tables — so two training runs can draw from
    one data service without ever seeing each other's assignment state
    (the tf.data-service sharing argument: input workers exist to be
    shared across jobs).  The split CONTENT (decode cache, batch bytes)
    stays shared on the server; only the assignment plane is per-tenant.
    All fields are guarded by the owning server's ``_lock``."""

    def __init__(self, n_splits: int, order: list[int]):
        self.epoch = 0
        self.pending: deque[int] = deque(order)
        self.assigned: dict[int, tuple[int, float]] = {}  # split -> (worker, t)
        self.worker_split: dict[int, int] = {}  # worker -> unacked split
        self.completed: set[int] = set()
        self.visits = {i: 0 for i in range(n_splits)}
        self.last_seen: dict[int, float] = {}
        self.stale_members: set[int] = set()
        self.stale_marked = 0
        self.batches_served = 0
        self.splits_completed = 0
        self.assigned_total = 0
        self.acks = 0
        self.reassigned = 0
        self.epochs_completed = 0
        self.last_epoch_min_visits = 0
        self.registered: set[int] = set()

    def counters(self) -> dict:
        """The per-tenant stats row (caller holds the server lock)."""
        return {
            "epoch": self.epoch,
            "pending": len(self.pending),
            "assigned": len(self.assigned),
            "completed": len(self.completed),
            "registered_workers": len(self.registered),
            "batches_served": self.batches_served,
            "splits_completed": self.splits_completed,
            "assigned_total": self.assigned_total,
            "acks": self.acks,
            "reassigned": self.reassigned,
            "stale_marked": self.stale_marked,
            "epochs_completed": self.epochs_completed,
            "last_epoch_min_visits": self.last_epoch_min_visits,
        }


class DataServiceServer:
    """TCP data server on the unified server core (r17): one dispatcher
    state machine registered as a handler on ``parallel/server_core.py``
    (selector-driven I/O, bounded worker pool — idle connections cost no
    threads), batches decoded server-side (the disaggregation point —
    preprocessing cost lives HERE, not on the training host).

    ``splits``           shard file paths (``filestream`` formats) or in-RAM
                         ``{field: array}`` chunks; one split per entry.
    ``batch_size``       rows per served batch (the TRAINING worker's local
                         batch).
    ``decode_fn``        applied to every batch before serving ("ready
                         batches": decode/normalize/augment run on the data
                         server's cores).
    ``shuffle``          shuffle rows within a split, keyed on ``(seed,
                         split)`` only — deterministic across epochs AND
                         server restarts, so a re-claimed split resumes
                         byte-identically.  Epoch-to-epoch variation comes
                         from the per-epoch split ORDER, keyed on ``(seed,
                         epoch)``.
    ``eval_chunk``       optional held-out chunk served raw via GET_EVAL.
    ``reassign_after_s`` liveness window: an assigned split whose worker has
                         issued no op for this long may be handed to another
                         worker (at-least-once beats a lost worker wedging
                         the epoch).
    """

    def __init__(
        self,
        splits: Sequence,
        *,
        batch_size: int,
        decode_fn: Callable | None = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_remainder: bool = True,
        eval_chunk: dict[str, np.ndarray] | None = None,
        port: int = 0,
        loopback_only: bool = True,
        reassign_after_s: float = 60.0,
        cache_splits: int = 4,
        info_extra: dict | None = None,
        handler_workers: int = 8,
        tenant_quotas: dict | None = None,
    ):
        if not splits:
            raise ValueError("data service needs at least one split")
        # Extra fields merged into the REGISTER answer — how hosting code
        # advertises pipeline settings clients should sanity-check (e.g.
        # serve_from_dir's seed/augment).
        self._info_extra = dict(info_extra or {})
        self._splits = list(splits)
        self._batch = batch_size
        self._decode = decode_fn
        self._shuffle = shuffle
        self._seed = seed
        self._drop_remainder = drop_remainder
        self._eval_chunk = eval_chunk
        self._reassign_after_s = reassign_after_s
        # Distinct per process start: a client comparing incarnations across
        # a reconnect detects a restarted (assignment-state-lost) server.
        self._incarnation = int.from_bytes(os.urandom(4), "little") | 1
        self._lock = threading.Lock()
        # Per-tenant dispatcher jobs (r20): each tenant iterates the SHARED
        # split set as its own job — own epoch/pending/assignment/liveness
        # state (including the r14 stale-member marks, so one tenant's
        # membership churn can never trigger another tenant's
        # reassignment).  The default job always exists: untagged frames
        # are the default tenant by construction, and single-tenant
        # behavior is byte-identical to pre-tenant servers.
        self._jobs: dict[str, _TenantJob] = {
            tenancy.DEFAULT_TENANT: _TenantJob(
                len(self._splits), self._epoch_order(0)
            ),
        }
        self._cache: OrderedDict[int, list] = OrderedDict()
        self._cache_cap = max(1, cache_splits)
        self.shutdown_requested = threading.Event()
        # The shared server runtime (r17): selector-driven I/O, a bounded
        # handler pool, per-connection write buffering and the request
        # counter all live in parallel/server_core.py — this class is the
        # dispatcher state machine plus one registered handler.
        self._core = server_core.ServerCore(
            port=port, loopback_only=loopback_only, name="dsvc",
            workers=handler_workers, tenant_quotas=tenant_quotas,
        )
        self._core.add_service(server_core.Service(
            "dsvc", self._handle,
            control_ops=_DSVC_CONTROL_OPS,
            counts_fn=self._counts_request,
            tenant_of=_tenant_of_request,
            error_status=ERR,
            # No DSVC request carries a payload: a frame announcing more
            # than this is a corrupt/hostile peer and drops at header
            # time, before a byte of it would be buffered.
            max_payload=1 << 20,
        ))
        self._core.start()
        self.port = self._core.port
        log.info(
            "data service serving %d splits on port %d (incarnation %d)",
            len(self._splits), self.port, self._incarnation,
        )

    # -- lifecycle -----------------------------------------------------------

    def request_count(self) -> int:
        """Requests handled so far — the ``die:after_reqs`` fault trigger
        for a data-service task (same contract as the PS server's).  The
        counter lives in the server core, which excludes the control-plane
        ops (wire.CONTROL_OPS) and the scraper's metadata-only probe."""
        return self._core.request_count()

    @staticmethod
    def _counts_request(op: int, name: str, a: int, b: int) -> bool:
        # The scraper's metadata-only REGISTER probe (negative worker id)
        # is uncounted — an op-level rule cannot carry it, so it stays
        # spelled out here as the core's per-service counts hook.
        return not (op == DSVC_REGISTER and a < 0)

    def stop(self) -> None:
        # The core drains (in-flight handlers finish, buffers flush) and
        # releases the port before returning — the same contract the old
        # hand-rolled accept loop kept for supervised same-port restarts.
        self._core.stop()

    # -- split plumbing ------------------------------------------------------

    def _epoch_order(self, epoch: int) -> list[int]:
        order = np.arange(len(self._splits))
        if self._shuffle:
            order = np.random.default_rng(
                (self._seed, epoch)
            ).permutation(order)
        return [int(i) for i in order]

    def _split_batches(self, si: int) -> list:
        """Ready (decoded, batch-sliced) batches of split ``si``, each
        pre-encoded as a wire buffer list; LRU-cached so the per-connection
        handlers share the decode work."""
        with self._lock:
            cached = self._cache.get(si)
            if cached is not None:
                self._cache.move_to_end(si)
                return cached
        src = self._splits[si]
        chunk = filestream.load_chunk(src) if isinstance(src, str) else {
            k: np.asarray(v) for k, v in src.items()
        }
        n = len(next(iter(chunk.values())))
        if self._shuffle:
            order = np.random.default_rng((self._seed, si)).permutation(n)
            chunk = {k: v[order] for k, v in chunk.items()}
        b = self._batch
        nb = n // b if self._drop_remainder else -(-n // b)
        batches = []
        for s in range(nb):
            raw = {k: v[s * b : (s + 1) * b] for k, v in chunk.items()}
            batches.append(encode_batch(self._decode(raw) if self._decode else raw))
        with self._lock:
            self._cache[si] = batches
            # Capacity adapts to the number of splits concurrently ASSIGNED
            # (across every tenant's job — the decode cache is the shared
            # resource): with more active workers than the configured
            # floor, a fixed cap would thrash — every interleaved
            # GET_BATCH re-decoding a whole shard to serve one batch.
            live = sum(len(j.assigned) for j in self._jobs.values())
            cap = max(self._cache_cap, live + 1)
            while len(self._cache) > cap:
                self._cache.popitem(last=False)
        return batches

    def _num_batches(self, si: int) -> int:
        return len(self._split_batches(si))

    # -- dispatcher state machine (all under self._lock; one _TenantJob
    # per tenant — the shared-split multiplexing point, r20) ------------------

    def _job_locked(self, tenant: str) -> _TenantJob:
        """The tenant's dispatcher job, created on first touch (caller
        holds ``self._lock``).  Every tenant iterates the same split set
        from epoch 0 with the same deterministic per-epoch order."""
        j = self._jobs.get(tenant)
        if j is None:
            j = self._jobs[tenant] = _TenantJob(
                len(self._splits), self._epoch_order(0)
            )
            log.info("data service: new tenant job %r", tenant)
        return j

    def _ack_locked(self, j: _TenantJob, worker: int, split: int) -> None:
        """Idempotent completion mark.  Also honors acks a RESTARTED server
        never assigned (the old incarnation did): the split is pulled out of
        pending so visited work is not re-served."""
        if not (0 <= split < len(self._splits)) or split in j.completed:
            return
        holder = j.assigned.get(split)
        if holder is not None and holder[0] != worker:
            return  # someone else owns it now (post-failover): their ack counts
        j.assigned.pop(split, None)
        if j.worker_split.get(worker) == split:
            del j.worker_split[worker]
        try:
            j.pending.remove(split)
        except ValueError:
            pass
        j.completed.add(split)
        j.visits[split] = max(j.visits[split], 1)
        j.splits_completed += 1
        j.acks += 1
        self._maybe_roll_locked(j)

    def _maybe_roll_locked(self, j: _TenantJob) -> None:
        if len(j.completed) < len(self._splits):
            return
        j.last_epoch_min_visits = min(j.visits.values())
        j.epochs_completed += 1
        j.epoch += 1
        j.completed.clear()
        j.assigned.clear()
        j.worker_split.clear()
        j.visits = {i: 0 for i in range(len(self._splits))}
        j.pending = deque(self._epoch_order(j.epoch))
        log.info("data service: epoch rolled to %d", j.epoch)

    def _assign_locked(self, j: _TenantJob, worker: int, split: int) -> None:
        j.assigned[split] = (worker, time.monotonic())
        j.worker_split[worker] = split
        j.visits[split] += 1
        j.assigned_total += 1

    def _handle_get_split(
        self, tenant: str, worker: int, ack: int,
        client_epoch: int | None, strict: bool,
    ):
        now = time.monotonic()
        with self._lock:
            j = self._job_locked(tenant)
            j.last_seen[worker] = now
            j.stale_members.discard(worker)  # it's back: unmark
            if ack >= 0 and (client_epoch is None or client_epoch == j.epoch):
                # Epoch-tagged acks: an ack for a split assigned in a
                # PREVIOUS epoch (a worker that stalled past reassignment
                # while the epoch rolled) must not mark the NEW epoch's
                # pending copy completed with zero deliveries — ignoring it
                # re-serves the split instead (at-least-once preserved).
                self._ack_locked(j, worker, ack)
            if strict and client_epoch != j.epoch:
                return EPOCH_ROLLED, {"epoch": j.epoch}
            # Replay safety: an unacked assignment is re-answered, so a
            # response lost mid-drop cannot strand a split on this worker.
            held = j.worker_split.get(worker)
            if held is not None and held not in j.completed:
                return held, {"epoch": j.epoch, "num_batches": None, "split": held}
            if j.pending:
                s = j.pending.popleft()
                self._assign_locked(j, worker, s)
                return s, {"epoch": j.epoch, "num_batches": None, "split": s}
            # Nothing pending: reassign only a STALE assignee's split (a
            # lost worker must not wedge the epoch); otherwise wait.  A
            # worker the membership layer declared departed (expired
            # lease, r14) is stale IMMEDIATELY — the elastic leave path
            # skips the liveness window entirely.  Staleness is scoped to
            # THIS tenant's job: another tenant's membership churn cannot
            # reassign here (r20 isolation).
            for s, (w, t0) in j.assigned.items():
                if w in j.stale_members or now - max(
                    j.last_seen.get(w, 0.0), t0
                ) > self._reassign_after_s:
                    if j.worker_split.get(w) == s:
                        # The stale worker no longer holds it: were it to
                        # come back, its GET_SPLIT must not re-answer s.
                        del j.worker_split[w]
                    self._assign_locked(j, worker, s)
                    j.reassigned += 1
                    faults.log_event(
                        "dsvc_reassign", split=s, from_worker=w, to_worker=worker,
                    )
                    return s, {"epoch": j.epoch, "num_batches": None, "split": s}
            return WAIT, {"epoch": j.epoch}

    def mark_worker_stale(self, worker: int, tenant: str | None = None) -> None:
        """Membership hook (r14): a worker whose lease EXPIRED is departed
        NOW — its assigned splits become reassignable on the next
        GET_SPLIT, without waiting out ``reassign_after_s``.  Idempotent;
        any later op from the worker clears the mark.  ``tenant`` scopes
        the mark to one tenant's job (r20: a tenant-tagged lease expiry
        must never reassign another tenant's splits); ``None`` — the
        pre-tenant signature — marks the worker in every job."""
        with self._lock:
            jobs = (
                list(self._jobs.values()) if tenant is None
                else [self._job_locked(tenant)]
            )
            for j in jobs:
                if worker not in j.stale_members:
                    j.stale_members.add(worker)
                    j.stale_marked += 1
        faults.log_event("dsvc_member_stale", worker=worker, tenant=tenant)

    def _handle_claim(self, tenant: str, worker: int, split: int):
        with self._lock:
            j = self._job_locked(tenant)
            j.last_seen[worker] = time.monotonic()
            j.stale_members.discard(worker)
            if not (0 <= split < len(self._splits)):
                return ERR, {}
            if split in j.completed:
                return CLAIM_DONE, {"epoch": j.epoch}
            holder = j.assigned.get(split)
            if holder is not None and holder[0] != worker:
                return CLAIM_TAKEN, {"epoch": j.epoch}
            try:
                j.pending.remove(split)
            except ValueError:
                pass
            if holder is None:
                self._assign_locked(j, worker, split)
            return OK, {"epoch": j.epoch, "num_batches": None, "split": split}

    def stats(self) -> dict:
        with self._lock:
            # Top-level dispatcher counters are the DEFAULT tenant's job —
            # the pre-tenant shape every existing consumer (tests, dtxtop,
            # loadsim verdicts) reads; a single-tenant server reports
            # exactly what it always did.  The per-tenant breakdown rides
            # in "tenants" (every job, default included).
            out = {
                "service": "dsvc",
                "role": faults.current_role(),
                "incarnation": self._incarnation,
                "num_splits": len(self._splits),
                **self._jobs[tenancy.DEFAULT_TENANT].counters(),
                "tenants": {t: j.counters() for t, j in self._jobs.items()},
            }
        # The uniform runtime-accounting shape (r17): requests/live_conns
        # come from the shared server core, so the counters mean the same
        # thing on every service's STATS answer.  The admission-control
        # shed counters (r18) surface top-level too, so dtxtop and the
        # loadsim overload verdict read one shape across all three
        # services (the native PS exports the same two keys).
        core = self._core.core_stats()
        out["requests"] = core["requests"]
        out["live_conns"] = core["live_conns"]
        out["shed_total"] = core["shed_total"]
        out["queue_deadline_drops"] = core["queue_deadline_drops"]
        out["core"] = core
        # Process-wide registry + flight-recorder depth ride along (r13):
        # one STATS scrape reads the server's dispatcher counters AND the
        # host process's client-side instruments in one round trip.
        out["registry"] = telemetry.snapshot()
        out["flight_events"] = len(telemetry.RECORDER)
        return out

    # -- the core handler ----------------------------------------------------
    # One registered handler on the shared server core (r17): the core
    # owns accept/read/write/HELLO/counting; this method is pure
    # request->response.  HELLO never reaches it (answered in the core
    # through the shared wire.hello_answer path), and a raised exception
    # becomes a LOUD per-op ERR on the client (the core's posture).

    def _handle(self, conn, op: int, name: str, a: int, b: int, payload):
        # The tenant rides the name operand (",t=<tenant>", r20) — absent
        # on untagged (pre-tenant) clients, which land on the default
        # tenant's job with byte-identical frames.
        name, tenant = tenancy.untag_name(name)
        if op == DSVC_REGISTER:
            with self._lock:
                j = self._job_locked(tenant)
                if a >= 0:
                    # Negative worker ids are metadata-only probes (source
                    # resolution, tooling): they must not count as training
                    # workers in the dispatcher's liveness/stats tables.
                    j.registered.add(a)
                    j.last_seen[a] = time.monotonic()
                    j.stale_members.discard(a)
                epoch = j.epoch
            info = {
                "incarnation": self._incarnation,
                "epoch": epoch,
                "num_splits": len(self._splits),
                "batch_size": self._batch,
                **self._info_extra,
            }
            return OK, [json.dumps(info).encode()]
        if op == DSVC_GET_SPLIT:
            # name: "epoch=<n>[,strict]" — <n> is the epoch the CLIENT is
            # in (the epoch its ack's split was assigned in); ",strict"
            # additionally constrains assignment to that epoch
            # (single-epoch iteration).
            client_epoch, strict = None, False
            if name.startswith("epoch="):
                tail = name[len("epoch="):]
                strict = tail.endswith(",strict")
                client_epoch = int(tail[: -len(",strict")] if strict else tail)
            status, info = self._handle_get_split(
                tenant, a, b, client_epoch, strict
            )
            if status >= 0 and info.get("num_batches") is None:
                info["num_batches"] = self._num_batches(status)
            return status, [json.dumps(info).encode()]
        if op == DSVC_CLAIM_SPLIT:
            status, info = self._handle_claim(tenant, a, b)
            if status == OK and info.get("num_batches") is None:
                info["num_batches"] = self._num_batches(b)
            return status, [json.dumps(info).encode()]
        if op == DSVC_GET_BATCH:
            if not (0 <= a < len(self._splits)):
                return ERR, None
            with self._lock:
                j = self._job_locked(tenant)
                if name:
                    j.last_seen[int(name)] = time.monotonic()
                    j.stale_members.discard(int(name))
            batches = self._split_batches(a)
            if b >= len(batches) or b < 0:
                return END_OF_SPLIT, None
            with self._lock:
                j.batches_served += 1
            return OK, batches[b]
        if op == DSVC_HEARTBEAT:
            with self._lock:
                j = self._job_locked(tenant)
                j.last_seen[a] = time.monotonic()
                j.stale_members.discard(a)
                epoch = j.epoch
            return epoch, None
        if op == DSVC_STATS:
            return OK, [json.dumps(self.stats()).encode()]
        if op == DSVC_GET_EVAL:
            if self._eval_chunk is None:
                return END_OF_SPLIT, None
            return OK, encode_batch(self._eval_chunk)
        if op == DSVC_SHUTDOWN:
            self.shutdown_requested.set()
            return OK, None
        return ERR, None


# ----------------------------------------------------------------------------
# Client transport — deadlines, backoff reconnect, incarnation healing
# ----------------------------------------------------------------------------


class DataServiceClient:
    """One TCP connection to a data server (requests serialized on it).

    The PR 1 fault posture, extended to input: every op takes the
    ``op_timeout_s`` deadline; a transport failure triggers
    exponential-backoff reconnect bounded by ``reconnect_deadline_s``
    (``DSVCDeadlineError`` past it) and the op is replayed — every DSVC op
    is idempotent or replay-safe by protocol (see the module docstring's
    GET_SPLIT note).  On reconnect the client re-negotiates HELLO,
    re-registers, and compares the server's incarnation: a change means a
    RESTARTED server lost all assignment state, so registered
    ``on_reincarnation`` callbacks run (the dataset source re-claims its
    in-flight split there).

    Fault-plan role: ``<process role>_ds`` by default, so ``DTX_FAULT_PLAN``
    specs can target data connections specifically (``role=worker0_ds``)
    while broad globs like ``worker0*`` still match both PS and data
    clients of a worker.
    """

    def __init__(
        self, host: str, port: int, *, worker_id: int = 0,
        op_timeout_s: float | None = 30.0, reconnect_deadline_s: float = 60.0,
        backoff_s: float = 0.25, role: str | None = None,
        tenant: str = tenancy.DEFAULT_TENANT,
    ):
        self._host, self._port = host, port
        self.worker_id = worker_id
        # The tenant every request of this client is tagged with (r20):
        # the default tenant tags nothing, so a pre-tenant server sees
        # byte-identical frames.
        self.tenant = (
            tenant if tenant == tenancy.DEFAULT_TENANT
            else tenancy.check_tenant(tenant)
        )
        self._op_timeout = op_timeout_s
        self._reconnect_deadline = reconnect_deadline_s
        self._backoff = backoff_s
        self.role = role if role is not None else (
            (faults.current_role() or "client") + "_ds"
        )
        self._injector = faults.client_injector(self.role)
        # Shared retry discipline (r18): replays and shed retries spend
        # this budget; exhaustion surfaces as DSVCDeadlineError plus a
        # flight-recorder event (parallel/retry.py).
        self._budget = retry.RetryBudget()
        self._lock = threading.RLock()
        self._in_recovery = False
        self._callbacks: list = []
        self._sock: socket.socket | None = None
        self._hdr = bytearray(wire.RESP_HDR.size)
        self.incarnation: int | None = None
        self.server_info: dict = {}
        try:
            self._connect()
            self._register()
        except OSError:
            if self._reconnect_deadline <= 0:
                raise
            self._recover(time.monotonic() + self._reconnect_deadline)

    # -- transport -----------------------------------------------------------

    def _connect(self) -> None:
        sock = socket.create_connection(
            (self._host, self._port), timeout=self._op_timeout
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        status, tag = self._attempt(
            DSVC_HELLO, a=wire.WIRE_VERSION,
            b=wire.pack_hello_b(wire.WIRE_DTYPES["f32"], service="dsvc"),
        )
        err = wire.hello_failure(
            status, tag, service="dsvc", host=self._host, port=self._port
        )
        if err is not None:
            self._sever()
            raise DSVCError(err)

    def _register(self) -> None:
        """REGISTER on the live socket (single attempt); detects a new
        server incarnation and runs the reincarnation callbacks."""
        status, raw = self._attempt(DSVC_REGISTER, name=self.role, a=self.worker_id)
        if status != OK:
            raise self.rejected_error("register", status)
        info = json.loads(raw)
        changed = (
            self.incarnation is not None
            and info["incarnation"] != self.incarnation
        )
        self.server_info = info
        if changed:
            faults.log_event(
                "dsvc_reincarnation", role=self.role, epoch=info["epoch"],
            )
            self._in_recovery = True
            try:
                for fn in list(self._callbacks):
                    fn(info)
            finally:
                self._in_recovery = False
        # Adopt the new incarnation only AFTER the callbacks completed: a
        # transport fault inside a callback (e.g. a second drop during the
        # re-claim) sends the recover loop around again, and the retried
        # register must still see the incarnation as CHANGED so the
        # callbacks re-run — callbacks are idempotent (claim re-claims).
        self.incarnation = info["incarnation"]

    def on_reincarnation(self, fn) -> None:
        """Register ``fn(server_info)`` to run whenever a reconnect lands on
        a NEW server incarnation (assignment state lost).  Callbacks may use
        this client; their ops run single-attempt (no nested recovery)."""
        self._callbacks.append(fn)

    def _sever(self) -> None:
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._reconnect_deadline = 0.0
        self._sever()

    def _attempt(
        self, op: int, name: str = "", a: int = 0, b: int = 0, *,
        batch: bool = False, deadline_s: float | None = None,
    ):
        """One send/recv round trip; severs the socket on ANY transport
        failure (framing broken mid-stream).  Returns ``(status, payload)``
        where payload is raw bytes, a decoded batch dict (``batch=True``),
        or None when the response carries none."""
        if self._sock is None:
            raise ConnectionError("not connected")
        # The ONE client-side tagging point (r20): every data-plane op of
        # a non-default tenant carries its tenant in the name operand.
        # Never HELLO — the tag is a v5 construct and HELLO is the frame
        # that discovers the peer's version (same reasoning as the
        # deadline stamp below).
        if self.tenant != tenancy.DEFAULT_TENANT and op != DSVC_HELLO:
            name = tenancy.tag_name(name, self.tenant)
        try:
            eff_deadline = (
                deadline_s if deadline_s is not None else self._op_timeout
            )
            self._sock.settimeout(eff_deadline)
            # Deadline propagation (r18): the remaining per-op budget rides
            # in the frame header, so the server core sheds this request —
            # instead of dispatching it to a worker — once this client has
            # already abandoned it.  NEVER on HELLO itself: the stamp is a
            # v4 construct and HELLO is the frame that DISCOVERS the
            # peer's version — a stamped HELLO against a pre-v4 server
            # would misparse instead of answering the loud version
            # mismatch (every later op follows a v4-confirmed HELLO).
            self._sock.sendall(wire.pack_request(
                op, name, a, b, 0,
                deadline_ms=(
                    0 if eff_deadline is None or op == DSVC_HELLO
                    else max(1, int(eff_deadline * 1000))
                ),
            ))
            hdr = memoryview(self._hdr)
            wire.recv_exact(self._sock, hdr)
            status, nbytes = wire.RESP_HDR.unpack(self._hdr)
            if not nbytes:
                return status, None
            if batch:
                return status, read_batch(self._sock, nbytes)
            buf = bytearray(nbytes)
            wire.recv_exact(self._sock, memoryview(buf))
            return status, bytes(buf)
        except OSError:
            self._sever()
            raise

    def _recover(self, t_end: float) -> None:
        attempt = 0
        immediate = False
        while True:
            if attempt and not immediate:
                # Jittered backoff (r18): recovering peers decorrelate
                # their re-dials instead of re-arriving in lockstep.
                delay = retry.jittered(self._backoff, attempt - 1, cap_s=2.0)
                time.sleep(min(delay, max(0.0, t_end - time.monotonic())))
            immediate = False
            if time.monotonic() >= t_end:
                faults.log_event(
                    "reconnect_gave_up", role=self.role, host=self._host,
                    port=self._port, attempts=attempt,
                )
                telemetry.dump_flight_recorder("reconnect_gave_up")
                raise DSVCDeadlineError(
                    f"data service at {self._host}:{self._port} unreachable "
                    f"for {self._reconnect_deadline:.0f}s ({attempt} attempts)"
                )
            attempt += 1
            # Per-address circuit breaker (r18): a freshly-proven-dead
            # address fails fast for its open window instead of burning
            # another connect timeout (shared process-wide, so every
            # client of this server pays ONE discovery).
            breaker = retry.breaker_for((self._host, self._port))
            if not breaker.allow():
                breaker.wait_for_probe(t_end)
                immediate = True  # the wait was this attempt's pacing
                continue
            try:
                self._connect()
                self._register()
            except OSError:
                breaker.on_failure()
                self._sever()
                continue
            except DSVCRejectedError:
                # The server ANSWERED and refused (a deterministic
                # register rejection): the transport is healthy and
                # every retry would be refused the same way — re-raise
                # instead of burning the whole reconnect budget to
                # report the service "unreachable" (the exact failure
                # mode the typed rejection exists to prevent).
                breaker.on_success()  # the address answered: not dead
                raise
            except DSVCError:
                # A callback's single-attempt op hit a transport fault: same
                # as a raw drop — sever, retry, same deadline.  (A HELLO
                # version/tag mismatch also lands here; retrying it is
                # harmless and bounded by the deadline.)
                self._sever()
                continue
            breaker.on_success()
            faults.log_event("reconnected", role=self.role, attempts=attempt)
            return

    def call(
        self, op: int, name: str = "", a: int = 0, b: int = 0, *,
        batch: bool = False,
    ):
        """One request/response; recovers + replays on transport failure
        (every DSVC op is replay-safe — see class docstring).  A server
        SHED (the RETRY_LATER band, r18 admission control) is retried
        with jittered backoff through the shared retry budget, bounded
        by the op deadline — never at line rate."""
        with self._lock:
            if self._injector is not None and self._injector.before_op(op):
                self._sever()  # injected drop_conn
            t_end = None
            shed = retry.ShedRetry(self._budget, self._op_timeout)
            while True:
                if self._sock is not None:
                    try:
                        status, payload = self._attempt(
                            op, name, a, b, batch=batch
                        )
                    except OSError as e:
                        if self._in_recovery or self._reconnect_deadline <= 0:
                            raise DSVCError(f"dsvc op {op} failed: {e!r}") from e
                        faults.log_event(
                            "conn_lost", role=self.role, op_code=op,
                            error=type(e).__name__,
                        )
                    else:
                        hint = wire.retry_after_ms(status)
                        if hint is None:
                            self._budget.on_success()
                            return status, payload
                        # One spelling of the shed-retry discipline
                        # (retry.ShedRetry): jittered off the server's
                        # hint, through the budget, deadline-bounded.
                        if not shed.backoff(hint):
                            raise DSVCDeadlineError(
                                f"data service at {self._host}:{self._port} "
                                f"kept shedding op {op} (RETRY_LATER) past "
                                "the op deadline / retry budget"
                            )
                        continue
                elif self._in_recovery or self._reconnect_deadline <= 0:
                    raise DSVCError(f"dsvc op {op} failed: not connected")
                if t_end is None:
                    t_end = time.monotonic() + self._reconnect_deadline
                # A transport replay spends the shared retry budget: a
                # storm of failing ops cannot replay unboundedly.
                if not self._budget.try_spend():
                    raise DSVCDeadlineError(
                        f"data service at {self._host}:{self._port} retry "
                        f"budget exhausted replaying op {op}"
                    )
                self._recover(t_end)

    # -- convenience ops -----------------------------------------------------

    @staticmethod
    def rejected_error(what: str, status: int) -> DSVCError:
        """The ONE typed-error path for server-side rejections (r17):
        every negative answer a caller cannot act on maps to
        :class:`DSVCRejectedError`, with the core's generic handler-
        failure band (``ERR``) named explicitly — the server logged the
        traceback; the client's job is only to say WHERE to look."""
        if status == ERR:
            return DSVCRejectedError(
                f"{what} failed server-side (ERR: handler error — see the "
                "data server's log)"
            )
        return DSVCRejectedError(f"{what} rejected: status {status}")

    def heartbeat(self) -> int:
        status, _ = self.call(DSVC_HEARTBEAT, a=self.worker_id)
        return status

    def stats(self) -> dict:
        status, raw = self.call(DSVC_STATS)
        if status != OK:
            raise self.rejected_error("stats", status)
        return json.loads(raw)

    def shutdown_server(self) -> None:
        self.call(DSVC_SHUTDOWN)


# ----------------------------------------------------------------------------
# RemoteDatasetSource — the dsvc:// branch of data/streams.py
# ----------------------------------------------------------------------------


class _BatchPrefetcher:
    """Double-buffered background prefetch (modeled on
    ``async_ps.ParamPrefetcher``): while the trainer consumes batch k, the
    fetch thread already pulls k+1 over the wire — transport latency hidden
    under compute.  Errors surface on the CONSUMING side, never corrupt it;
    a bounded queue (depth 2) caps both staleness and host RAM."""

    _DONE = object()

    def __init__(self, it: Iterator, *, depth: int = 2, stall_timeout_s: float = 300.0):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._stall = stall_timeout_s
        self._thread = threading.Thread(
            target=self._loop, args=(it,), daemon=True, name="dsvc-prefetch"
        )
        self._thread.start()

    def _put(self, item) -> bool:
        while True:
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                if self._stop.is_set():
                    return False

    def _loop(self, it) -> None:
        try:
            for item in it:
                if self._stop.is_set() or not self._put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised on the consumer
            self._put(e)
            return
        self._put(self._DONE)

    def __iter__(self):
        while True:
            try:
                item = self._q.get(timeout=self._stall)
            except queue.Empty:
                raise DSVCDeadlineError("data-service prefetch thread stalled")
            if item is self._DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def close(self) -> None:
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


class RemoteDatasetSource:
    """High-level consumer of one data server: owns a
    :class:`DataServiceClient`, runs the split protocol, and yields ready
    batches.  ``dsvc://host:port`` specs parse via :func:`parse_spec`.

    Reconnect healing: the source registers an ``on_reincarnation`` hook
    that RE-CLAIMS the unacknowledged in-flight split on the restarted
    server, then resumes at the same batch index — exact, because split
    batches are deterministic in ``(seed, split)``.  A split the restarted
    epoch already completed (or another worker claimed first) is dropped
    and the source moves on; duplicates are possible only across the
    failover (at-least-once), never in steady state.
    """

    def __init__(
        self, spec: str, *, worker_id: int = 0,
        op_timeout_s: float | None = 30.0, reconnect_deadline_s: float = 60.0,
        role: str | None = None, poll_s: float = 0.05,
        tenant: str = tenancy.DEFAULT_TENANT,
    ):
        host, port = parse_spec(spec)
        self.spec = spec
        self._wid = worker_id
        self._poll_s = poll_s
        self._client = DataServiceClient(
            host, port, worker_id=worker_id, op_timeout_s=op_timeout_s,
            reconnect_deadline_s=reconnect_deadline_s, role=role,
            tenant=tenant,
        )
        self._client.on_reincarnation(self._reclaim)
        self._epoch = int(self._client.server_info["epoch"])
        self._ack = -1
        self._cur: list | None = None  # [split, num_batches, next_index]

    @property
    def server_info(self) -> dict:
        return self._client.server_info

    @property
    def num_splits(self) -> int:
        return int(self._client.server_info["num_splits"])

    def stats(self) -> dict:
        return self._client.stats()

    def eval_chunk(self) -> dict[str, np.ndarray] | None:
        status, payload = self._client.call(DSVC_GET_EVAL, batch=True)
        if status == END_OF_SPLIT:
            return None
        if status != OK:
            raise DataServiceClient.rejected_error("get_eval", status)
        return payload

    def close(self) -> None:
        self._client.close()

    # -- reincarnation healing ----------------------------------------------

    def _reclaim(self, info: dict) -> None:
        """Runs inside the client's reconnect path: re-request the
        unacknowledged split from the restarted server (PR 1 semantics,
        extended to input), adopt its epoch, and forget an ack addressed to
        the dead incarnation only after handing it over."""
        self._epoch = int(info["epoch"])
        if self._cur is None:
            return
        split = self._cur[0]
        status, raw = self._client._attempt(DSVC_CLAIM_SPLIT, a=self._wid, b=split)
        if status == OK:
            faults.log_event(
                "dsvc_reclaimed", role=self._client.role, split=split,
                index=self._cur[2],
            )
            return  # keep streaming the same split at the same index
        # This split is no longer ours — drop it and move on.  The named
        # claim statuses make the log line actionable: CLAIM_DONE means an
        # ack raced ahead (the work already counted), CLAIM_TAKEN means a
        # peer claimed it across the failover (at-least-once duplicate).
        reason = (
            "completed" if status == CLAIM_DONE
            else "taken" if status == CLAIM_TAKEN
            else f"status_{status}"
        )
        faults.log_event(
            "dsvc_reclaim_lost", role=self._client.role, split=split,
            status=status, reason=reason,
        )
        self._cur = None

    # -- the split/batch loop ------------------------------------------------

    def _next_split(self, single_epoch: bool):
        while True:
            # The epoch always rides along: it tags the ack (so a stale ack
            # from before an epoch roll is ignored server-side, never
            # falsely completing the new epoch's copy) and, with ",strict",
            # constrains assignment to it (single-epoch iteration).
            sent_epoch = self._epoch
            name = f"epoch={sent_epoch}" + (",strict" if single_epoch else "")
            status, raw = self._client.call(
                DSVC_GET_SPLIT, name=name, a=self._wid, b=self._ack
            )
            self._ack = -1
            info = json.loads(raw) if raw else {}
            if status >= 0:
                self._epoch = int(info.get("epoch", self._epoch))
                return status, int(info["num_batches"])
            if status == WAIT:
                time.sleep(self._poll_s)
                continue
            if status == EPOCH_ROLLED:
                server_epoch = int(info.get("epoch", -1))
                if single_epoch and (
                    server_epoch < sent_epoch or self._epoch != sent_epoch
                ):
                    # Not a genuine roll: either the server RESTARTED into
                    # an earlier epoch (state lost), or a mid-call recovery
                    # already adopted the new incarnation's epoch while the
                    # REPLAYED request still carried the stale constraint
                    # (sent_epoch, not self._epoch, is what the server
                    # answered).  Either way the epoch this client is
                    # finishing IS the server's current one — adopt it and
                    # keep going.
                    self._epoch = server_epoch
                    continue
                return None, 0
            raise DataServiceClient.rejected_error("get_split", status)

    def _iter_batches(self, repeat: bool) -> Iterator[dict[str, np.ndarray]]:
        while True:
            split, nb = self._next_split(single_epoch=not repeat)
            if split is None:
                return
            self._cur = [split, nb, 0]
            while True:
                cur = self._cur
                if cur is None:
                    break  # lost to another worker across a failover
                if cur[2] >= cur[1]:
                    self._ack = cur[0]
                    self._cur = None
                    break
                status, payload = self._client.call(
                    DSVC_GET_BATCH, name=str(self._wid), a=cur[0], b=cur[2],
                    batch=True,
                )
                if status == END_OF_SPLIT:
                    self._ack = cur[0]
                    self._cur = None
                    break
                if status != OK or payload is None:
                    raise DataServiceClient.rejected_error(
                        f"get_batch({cur[0]},{cur[2]})", status
                    )
                if self._cur is cur:
                    cur[2] += 1
                yield payload

    def batches(
        self, *, repeat: bool = True, prefetch: bool = True,
    ) -> Iterator[dict[str, np.ndarray]]:
        """Ready batches from the server: this worker's FCFS share of each
        epoch's splits.  ``repeat=False`` stops when the epoch the source
        joined rolls over (every split acknowledged by someone).
        ``prefetch`` double-buffers the next pull under the consumer's
        compute."""
        it = self._iter_batches(repeat)
        if not prefetch:
            return it
        pf = _BatchPrefetcher(it)

        def stream():
            try:
                yield from pf
            finally:
                pf.close()

        return stream()


# ----------------------------------------------------------------------------
# Task-role hosting (the runner's `data_service` job)
# ----------------------------------------------------------------------------


def serve_from_dir(
    data_dir: str, *, batch_size: int, seed: int = 0, augment: bool = True,
    port: int = 0, loopback_only: bool = True, cache_splits: int = 4,
    tenant_quotas: dict | None = None,
) -> DataServiceServer:
    """A server over a ``shard-*.npz`` directory: last shard held out as the
    eval chunk (same convention as ``streams.resolve_image_source``), the
    rest served as training splits with the standard image decode/augment
    running server-side."""
    shards = filestream.list_shards(data_dir)
    if not shards:
        raise ValueError(f"no shard files under {data_dir!r} to serve")
    train = shards[:-1] if len(shards) > 1 else shards
    if len(shards) == 1:
        log.warning(
            "data service: single shard — eval REUSES the train shard "
            "(memorization!)"
        )
    return DataServiceServer(
        train,
        batch_size=batch_size,
        decode_fn=filestream.image_decode_fn(augment=augment, seed=seed),
        seed=seed,
        eval_chunk=filestream.load_chunk(shards[-1]),
        port=port,
        loopback_only=loopback_only,
        cache_splits=cache_splits,
        tenant_quotas=tenant_quotas,
        # Advertised so consumers can sanity-check their own seed/augment
        # request against what this pipeline actually runs (streams.py
        # warns on mismatch — the server's settings win).
        info_extra={"seed": seed, "augment": augment},
    )


def host_data_service_task(
    data_dir: str, port: int, *, batch_size: int, seed: int = 0,
    loopback_only: bool = True,
    ps_addrs: list[tuple[str, int]] | None = None,
    lease_poll_s: float = 2.0, ps_layout_version: int = 0,
    tenant_quotas: dict | None = None,
) -> int:
    """Dedicated data-service task body (``--job_name=data_service``): host
    the server until a client signals DSVC_SHUTDOWN (or the supervisor
    dies).  Arms ``die`` fault specs off the server's request counter —
    the deterministic "kill the data server at request N" fault the
    mid-epoch recovery tests inject; a supervisor restart plus the clients'
    re-claim path heals it.

    Elasticity (r14): with ``ps_addrs`` (the coordinator shard's replica
    list, from ``--ps_hosts``), the task WATCHES the membership lease
    registry — a worker whose lease expires or is released has its
    in-flight splits marked reassignable immediately, so the live
    rebalance follows the membership signal instead of waiting out the
    dispatcher's own liveness window."""
    server = serve_from_dir(
        data_dir, batch_size=batch_size, seed=seed, port=port,
        loopback_only=loopback_only, tenant_quotas=tenant_quotas,
    )
    faults.arm_process_faults(
        request_count_fn=server.request_count, leave_fn=server.stop,
    )
    watcher = None
    if ps_addrs:
        from ..parallel import membership

        def _member_left(m: dict) -> None:
            # Worker member ids carry their numeric wid as a trailing
            # index ("worker3"); members without one have no dispatcher
            # state to reassign.  The mark is scoped to the departed
            # member's tenant (r20): one tenant's lease expiry can never
            # reassign another tenant's splits.
            wid = membership.member_index(m["member"])
            if wid is not None:
                server.mark_worker_stale(wid, tenant=m.get("tenant"))

        try:
            # follow_epoch (r15): a live PS reshard moves the lease
            # registry to the new layout's coordinator; the watcher chases
            # the committed epoch so split reassignment keeps following
            # the membership signal across an N→M transition.
            watcher = membership.LeaseWatcher(
                list(ps_addrs), kind="worker", poll_s=lease_poll_s,
                on_leave=_member_left, follow_epoch=True,
                layout_version=ps_layout_version,
            )
        except (OSError, RuntimeError):
            log.warning(
                "data service: lease registry at %s unreachable; falling "
                "back to the liveness-window reassignment only", ps_addrs,
            )
    log.info(
        "data service task on port %d (%d splits%s; blocking until shutdown)",
        server.port, len(server._splits),
        ", watching worker leases" if watcher is not None else "",
    )
    supervised = os.environ.get("DTX_DSVC_SUPERVISED") == "1"
    ppid0 = os.getppid()
    try:
        while not server.shutdown_requested.wait(timeout=2.0):
            if supervised and os.getppid() != ppid0:
                log.warning("data service task: supervisor died; exiting")
                break
        bound = server.port
    finally:
        # Every exit — shutdown, supervisor death, or an exception out of
        # the wait loop — stops the watcher's poll thread and client: a
        # leaked watcher keeps dialing the PS forever (the r14 leaked-
        # heartbeat bug class; dtxlint's lifecycle pass pins this shape).
        if watcher is not None:
            watcher.close()
        server.stop()
    return bound
