"""Batching, per-host sharding, and device infeed.

Replaces the reference's input stack (SURVEY.md T7 ``tf.data`` +
D14 ``DistributedDataset``): each host materialises only its 1/num_hosts shard
of the stream (``Dataset.shard`` analog), batches are device_put as *global*
arrays sharded over the mesh's data axes, and a small background thread keeps
``prefetch`` batches in flight so the host->HBM copy overlaps the previous
step's compute (the ``Dataset.prefetch``/host-infeed analog).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..parallel.sharding import batch_sharding


class InMemoryPipeline:
    """Shuffled, sharded, infinitely-repeating batch stream over in-memory
    numpy arrays (every reference workload's dataset fits in host RAM).

    ``batch_size`` is the GLOBAL batch size; each host yields its local
    ``batch_size // num_processes`` rows, and ``as_global`` assembles them
    into one mesh-sharded ``jax.Array`` per field.
    """

    def __init__(
        self,
        arrays: dict[str, np.ndarray],
        *,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        process_index: int | None = None,
        process_count: int | None = None,
        drop_remainder: bool = True,
    ):
        lengths = {k: len(v) for k, v in arrays.items()}
        if len(set(lengths.values())) != 1:
            raise ValueError(f"mismatched field lengths {lengths}")
        self.fields = dict(arrays)
        self.n = next(iter(lengths.values()))
        self.global_batch = batch_size
        self.pidx = jax.process_index() if process_index is None else process_index
        self.pcount = jax.process_count() if process_count is None else process_count
        if batch_size % self.pcount:
            raise ValueError(
                f"global batch {batch_size} not divisible by {self.pcount} hosts"
            )
        self.local_batch = batch_size // self.pcount
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        """Yields local (per-host) batches forever; reshuffles each epoch with
        a deterministic per-epoch seed so every host agrees on the permutation
        (the determinism knob of SURVEY.md section 5.2)."""
        epoch = 0
        while True:
            if self.shuffle:
                order = np.random.default_rng((self.seed, epoch)).permutation(self.n)
            else:
                order = np.arange(self.n)
            # Host shard (Dataset.shard analog). Truncate to a multiple of the
            # host count first so every host's shard has the SAME length —
            # otherwise hosts would cross epoch boundaries at different steps
            # and global batches would silently mix epoch permutations.
            order = order[: self.n - (self.n % self.pcount)]
            local = order[self.pidx :: self.pcount]
            steps = len(local) // self.local_batch
            for s in range(steps):
                idx = local[s * self.local_batch : (s + 1) * self.local_batch]
                yield {k: v[idx] for k, v in self.fields.items()}
            epoch += 1


def as_global(
    batch: dict[str, np.ndarray],
    mesh: Mesh,
    *,
    spec: PartitionSpec | None = None,
) -> dict[str, jax.Array]:
    """Assemble per-host local batches into global mesh-sharded arrays.

    ``spec`` overrides the default leading-dim-over-data-axis layout (e.g.
    ``P(None, 'data')`` for [unroll, batch, ...] super-batches).
    """
    if spec is None:
        sharding = batch_sharding(mesh)
    else:
        sharding = NamedSharding(mesh, spec)
    out = {}
    for k, v in batch.items():
        out[k] = jax.make_array_from_process_local_data(sharding, np.asarray(v))
    return out


def prefetch_to_mesh(
    it: Iterable[dict[str, np.ndarray]],
    mesh: Mesh,
    *,
    depth: int = 2,
    spec: PartitionSpec | None = None,
    transform: Callable[[dict[str, np.ndarray]], Any] | None = None,
) -> Iterator[Any]:
    """Background-thread infeed: keeps ``depth`` global device batches queued
    ahead of the consumer, overlapping host->HBM DMA with step compute."""
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _SENTINEL = object()

    def _producer():
        try:
            for batch in it:
                if stop.is_set():
                    return
                if transform is not None:
                    batch = transform(batch)
                q.put(as_global(batch, mesh, spec=spec))
        except Exception as e:  # surface producer errors at the consumer
            q.put(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=_producer, daemon=True, name="infeed-prefetch")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        stop.set()
        # Drain so the producer's blocked put() can observe stop and exit.
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass


def stack_for_unroll(
    it: Iterator[dict[str, np.ndarray]], k: int
) -> Iterator[dict[str, np.ndarray]]:
    """Group k consecutive local batches into one [k, ...] super-batch for
    multi-step-unrolled train steps (amortises dispatch for tiny models —
    SURVEY.md section 7 'hard parts' #2)."""
    while True:
        group = [next(it) for _ in range(k)]
        yield {key: np.stack([g[key] for g in group]) for key in group[0]}
