"""Native (C++) out-of-core input pipeline: the tf.data C++ runtime slot.

The reference's input layer is tf.data, whose hot path (file IO, shuffling,
batch assembly) is C++ (SURVEY.md §2c T7: "5286 LoC, Py+C++").  The Python
``FileStreamPipeline`` (data/filestream.py) covers the streaming role with
threads; THIS module moves the hot path into ``native/dataloader.cc`` — a
worker-pool + bounded-ring loader behind a C ABI — so batch assembly costs
no GIL time at accelerator rates.

Shard format: ``DTXRAW1`` raw-record files (fixed-size records, header-
described fields) — written by :func:`write_raw_shards`, read by the C++
core.  Decode/augment beyond raw assembly stays in Python (compose with
``filestream.image_decode_fn`` downstream); normalization of u8 image bytes
to f32 happens in numpy on the assembled batch view.

Usage::

    write_raw_shards(dir, {"image": x_u8, "label": y_i32}, shard_records=4096)
    pipe = NativeFileStream(list_raw_shards(dir), batch_size=256, seed=0)
    for batch in pipe:   # {"image": [B,32,32,3] u8, "label": [B] i32}
        ...
"""

from __future__ import annotations

import ctypes
import glob
import os
from typing import Iterator

import numpy as np

_DTYPE_CODE = {np.dtype(np.uint8): 0, np.dtype(np.int32): 1, np.dtype(np.float32): 2}
_CODE_DTYPE = {"u8": np.uint8, "i32": np.int32, "f32": np.float32}
MAGIC = b"DTXRAW1\n"


def write_raw_shards(
    directory: str,
    arrays: dict[str, np.ndarray],
    *,
    shard_records: int = 4096,
    prefix: str = "shard",
) -> list[str]:
    """Split record-aligned arrays into DTXRAW1 shard files."""
    os.makedirs(directory, exist_ok=True)
    n = len(next(iter(arrays.values())))
    fields = []
    for name, a in arrays.items():
        if len(a) != n:
            raise ValueError(f"field {name!r} length {len(a)} != {n}")
        if a.dtype not in _DTYPE_CODE:
            raise ValueError(f"field {name!r}: unsupported dtype {a.dtype}")
        fields.append((name, np.ascontiguousarray(a)))

    def header() -> bytes:
        out = [MAGIC, np.uint32(len(fields)).tobytes()]
        for name, a in fields:
            nb = name.encode()
            out += [bytes([len(nb)]), nb, bytes([_DTYPE_CODE[a.dtype]])]
            dims = a.shape[1:]
            out += [bytes([len(dims)])] + [np.uint32(d).tobytes() for d in dims]
        return b"".join(out)

    paths = []
    for si, start in enumerate(range(0, n, shard_records)):
        stop = min(start + shard_records, n)
        path = os.path.join(directory, f"{prefix}-{si:05d}.dtxr")
        with open(path, "wb") as f:
            f.write(header())
            f.write(np.uint64(stop - start).tobytes())
            # Record-major interleave, matching the C++ reader.
            views = [a[start:stop].reshape(stop - start, -1) for _, a in fields]
            for r in range(stop - start):
                for v in views:
                    f.write(v[r].tobytes())
        paths.append(path)
    return paths


def list_raw_shards(directory: str, pattern: str = "shard-*.dtxr") -> list[str]:
    return sorted(glob.glob(os.path.join(directory, pattern)))


#: Caps on untrusted header values (mirrors native/dataloader.cc): this
#: Python parse is the user-facing validator — absurd claims must raise a
#: clear ValueError HERE, not surface as the C++ backstop's generic NULL.
MAX_RECORD_BYTES = 1 << 30
MAX_SHARD_BYTES = 1 << 40


def _read_header(f) -> tuple[list, int]:
    def take(n: int) -> bytes:
        b = f.read(n)
        if len(b) != n:
            raise ValueError(f"truncated DTXRAW1 header: {f.name}")
        return b

    if f.read(8) != MAGIC:
        raise ValueError(f"not a DTXRAW1 shard: {f.name}")
    n_fields = int(np.frombuffer(take(4), np.uint32)[0])
    fields = []
    record_bytes = 0
    for _ in range(n_fields):
        name_len = take(1)[0]
        name = take(name_len).decode()
        code = take(1)[0]
        if code > 2:
            raise ValueError(f"bad dtype code {code} in {f.name}")
        dtype = np.dtype([np.uint8, np.int32, np.float32][code])
        ndim = take(1)[0]
        shape = tuple(int(np.frombuffer(take(4), np.uint32)[0]) for _ in range(ndim))
        field_bytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape else dtype.itemsize
        if field_bytes > MAX_RECORD_BYTES:
            raise ValueError(
                f"absurd field {name!r} ({field_bytes} B/record) in {f.name}"
            )
        record_bytes += field_bytes
        fields.append((name, dtype, shape))
    n = int(np.frombuffer(take(8), np.uint64)[0])
    if record_bytes > MAX_RECORD_BYTES or n * max(record_bytes, 1) > MAX_SHARD_BYTES:
        raise ValueError(
            f"absurd shard claim in {f.name}: {n} records x {record_bytes} B"
        )
    # The claimed payload must actually exist in the file (a lying header
    # must not size any downstream buffer).
    data_offset = f.tell()
    f.seek(0, 2)
    avail = f.tell() - data_offset
    f.seek(data_offset)
    if n * record_bytes > avail:
        raise ValueError(
            f"shard {f.name} claims {n} x {record_bytes} B but only "
            f"{avail} B of payload exist"
        )
    return fields, n


def peek_shard(path: str) -> tuple[list, int]:
    """(fields, n_records) from a shard header — no data read."""
    with open(path, "rb") as f:
        return _read_header(f)


def read_raw_shard(path: str) -> dict[str, np.ndarray]:
    """Host-side (numpy) read of ONE shard — for eval splits; the training
    path goes through the C++ loader."""
    with open(path, "rb") as f:
        fields, n = _read_header(f)
        raw = f.read()
    rec_bytes = sum(
        int(np.prod(s, dtype=np.int64)) * d.itemsize for _, d, s in fields
    )
    recs = np.frombuffer(raw, np.uint8, count=n * rec_bytes).reshape(n, rec_bytes)
    out, off = {}, 0
    for name, dtype, shape in fields:
        nb = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        out[name] = (
            np.ascontiguousarray(recs[:, off : off + nb])
            .view(dtype)
            .reshape((n, *shape))
        )
        off += nb
    return out


_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ..native import _load as _load_native  # builds libdtx_native.so on demand

    lib = _load_native()
    lib.dtx_dl_new.restype = ctypes.c_void_p
    lib.dtx_dl_new.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
    ]
    lib.dtx_dl_schema.restype = ctypes.c_int
    lib.dtx_dl_schema.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.dtx_dl_batch_bytes.restype = ctypes.c_int64
    lib.dtx_dl_batch_bytes.argtypes = [ctypes.c_void_p]
    lib.dtx_dl_next.restype = ctypes.c_int
    lib.dtx_dl_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
    ]
    lib.dtx_dl_error.restype = ctypes.c_int
    lib.dtx_dl_error.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.dtx_dl_produced.restype = ctypes.c_int64
    lib.dtx_dl_produced.argtypes = [ctypes.c_void_p]
    lib.dtx_dl_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


class NativeFileStream:
    """Iterate DTXRAW1 shards through the C++ worker-pool loader.

    Yields ``{field: np.ndarray}`` batches.  ``repeat=True`` streams epochs
    forever (chunk order reshuffled per epoch, records shuffled per chunk —
    both seeded).  Remainder batches are dropped (fixed shapes keep XLA from
    recompiling).
    """

    def __init__(
        self,
        paths: list[str],
        *,
        batch_size: int,
        n_workers: int = 2,
        capacity: int = 8,
        seed: int = 0,
        repeat: bool = True,
        timeout_s: float = 120.0,
    ):
        if not paths:
            raise ValueError("no shard paths")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        # All user-facing construction validation happens HERE (one source of
        # truth; the C++ guards in dtx_dl_new are an internal backstop whose
        # NULL return then genuinely means "unreadable"): headers parse,
        # schemas agree, and at least one shard can emit a full batch.
        ref_fields, max_n = None, 0
        for p in paths:
            fields, n = peek_shard(p)  # ValueError on bad/truncated header
            if ref_fields is None:
                ref_fields = fields
            elif fields != ref_fields:
                raise ValueError(
                    f"shard schema mismatch: {paths[0]} has {ref_fields}, "
                    f"{p} has {fields}"
                )
            max_n = max(max_n, n)
        if batch_size > max_n:
            raise ValueError(
                f"batch_size {batch_size} > {max_n} records in the largest "
                "shard (drop_remainder): rewrite shards with more records "
                "or shrink the batch"
            )
        self._lib = _load()
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])
        self._h = self._lib.dtx_dl_new(
            arr, len(paths), batch_size, n_workers, capacity, seed,
            int(repeat), 1,
        )
        if not self._h:
            raise ValueError(f"cannot open DTXRAW1 shards: {paths[0]}")
        self.batch_size = batch_size
        self.timeout_s = timeout_s
        buf = ctypes.create_string_buffer(4096)
        if self._lib.dtx_dl_schema(self._h, buf, 4096) < 0:
            raise RuntimeError("schema too large")
        self.schema = []
        for part in buf.value.decode().split(";"):
            name, dt, dims = part.split(":")
            shape = () if dims == "-" else tuple(int(d) for d in dims.split("x"))
            self.schema.append((name, np.dtype(_CODE_DTYPE[dt]), shape))
        self._batch_bytes = self._lib.dtx_dl_batch_bytes(self._h)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        out = np.empty(self._batch_bytes, np.uint8)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        while True:
            n = self._lib.dtx_dl_next(self._h, ptr, int(self.timeout_s * 1000))
            if n == 0:
                return
            if n == -1:
                raise TimeoutError(
                    f"native loader: no batch within {self.timeout_s}s "
                    "(starved or shard files unreadable)"
                )
            if n == -2:
                err = ctypes.create_string_buffer(1024)
                self._lib.dtx_dl_error(self._h, err, 1024)
                raise RuntimeError(f"native loader: {err.value.decode()}")
            batch, off = {}, 0
            for name, dtype, shape in self.schema:
                nbytes = n * int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
                batch[name] = (
                    out[off : off + nbytes].view(dtype).reshape((n, *shape)).copy()
                )
                off += nbytes
            yield batch

    @property
    def batches_produced(self) -> int:
        return self._lib.dtx_dl_produced(self._h)

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dtx_dl_free(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:
            pass
