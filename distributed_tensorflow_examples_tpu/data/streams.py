"""Shared --data_dir resolution for the image CLIs (W2 cifar10, W3 resnet50).

One place implements the source selection every image example needs
(SURVEY.md T7), so the CLIs cannot drift:

- ``dsvc://host:port`` -> REMOTE disaggregated data service
                       (data/data_service.py): ready batches streamed from
                       dedicated input workers over the PS wire,
- ``shard-*.dtxr``  -> NATIVE C++ loader (native/dataloader.cc),
- ``shard-*.npz``   -> Python streaming pipeline (filestream),
- anything else     -> in-RAM dataset from ``fallback()`` (real file or
                       synthetic).

The LAST shard is held out as the eval split (one chunk in RAM) so test
accuracy measures the streamed distribution; a single-shard directory
reuses it for eval with an explicit memorization warning.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Iterator

import numpy as np

from . import datasets, filestream, native_loader
from .pipeline import InMemoryPipeline

log = logging.getLogger("dtx.data")


@dataclasses.dataclass(frozen=True)
class ImageSource:
    kind: str  # "dsvc" | "native" | "stream" | "memory"
    ds: datasets.ArrayDataset  # .test always populated; .train only for memory
    train_shards: list[str]
    remote_spec: str = ""  # "dsvc://host:port" for kind == "dsvc"


def resolve_image_source(
    data_dir: str | None,
    *,
    fallback: Callable[[], datasets.ArrayDataset],
    seed: int,
    num_classes: int,
    name: str = "dataset",
    tenant: str = "default",
) -> ImageSource:
    if data_dir and data_dir.startswith("dsvc://"):
        from . import data_service

        # Remote disaggregated input: the server owns shards, decode and
        # split assignment; the eval chunk is its held-out shard, served
        # raw and decoded here like the on-disk branches.  worker_id=-1:
        # a metadata-only probe must never count as a training worker in
        # the dispatcher's liveness tables.
        probe = data_service.RemoteDatasetSource(
            data_dir, worker_id=-1, tenant=tenant
        )
        try:
            raw_eval = probe.eval_chunk()
            if raw_eval is None:
                raise ValueError(f"data service {data_dir} serves no eval chunk")
            n_splits = probe.num_splits
        finally:
            probe.close()
        test = filestream.image_decode_fn(seed=seed)(raw_eval)
        log.info("%s source: %s (%d remote splits)", name, data_dir, n_splits)
        return ImageSource(
            "dsvc",
            datasets.ArrayDataset({}, test, data_dir, num_classes),
            [],
            remote_spec=data_dir,
        )
    raw = native_loader.list_raw_shards(data_dir) if data_dir else []
    if raw:
        test = filestream.image_decode_fn(seed=seed)(
            native_loader.read_raw_shard(raw[-1])
        )
        train, held = _holdout(raw)
        log.info(
            "%s source: native:%s (%d train shards, C++ loader, %s)",
            name, data_dir, len(train), held,
        )
        return ImageSource(
            "native",
            datasets.ArrayDataset({}, test, f"native:{data_dir}", num_classes),
            train,
        )
    npz = filestream.list_shards(data_dir) if data_dir else []
    if npz:
        test = filestream.image_decode_fn(seed=seed)(filestream.load_chunk(npz[-1]))
        train, held = _holdout(npz)
        log.info(
            "%s source: stream:%s (%d train shards, %s)",
            name, data_dir, len(train), held,
        )
        return ImageSource(
            "stream",
            datasets.ArrayDataset({}, test, f"stream:{data_dir}", num_classes),
            train,
        )
    ds = fallback()
    log.info("%s source: %s", name, ds.source)
    return ImageSource("memory", ds, [])


def _holdout(shards: list[str]) -> tuple[list[str], str]:
    if len(shards) > 1:
        return shards[:-1], "1 held-out eval shard"
    return shards, "eval REUSES the single train shard (memorization!)"


def train_iter(
    src: ImageSource,
    *,
    batch_size: int,
    seed: int,
    augment: bool = True,
    worker: int | None = None,
    n_workers: int = 1,
    tenant: str = "default",
) -> Iterator[dict[str, np.ndarray]]:
    """Training batches of ``batch_size`` from the resolved source.

    ``worker``/``n_workers``: the PS-emulation per-worker split — worker w
    streams a disjoint shard subset (native) / row stride (stream) / its own
    sample stream (memory), each with a worker-distinct seed.
    """
    w = 0 if worker is None else worker
    if src.kind == "dsvc":
        from . import data_service

        # Batches arrive READY (decoded/augmented on the data server);
        # double-buffered prefetch hides the wire under local compute.
        # The SERVER's pipeline settings win over this call's arguments —
        # every mismatch warns, none is silent.
        # r20: the claim stream runs under the caller's tenant — split
        # assignment, epoch position and liveness all live in THIS
        # tenant's dispatcher job on the shared server.
        remote = data_service.RemoteDatasetSource(
            src.remote_spec, worker_id=w, tenant=tenant
        )
        info = remote.server_info
        server_bs = int(info.get("batch_size", batch_size))
        if server_bs != batch_size:
            log.warning(
                "data service serves batch_size=%d (requested %d): the "
                "server's setting wins — relaunch it to change",
                server_bs, batch_size,
            )
        if "seed" in info and int(info["seed"]) != seed:
            log.warning(
                "data service pipeline runs seed=%s (requested %d): batches "
                "are NOT reproducible under the requested seed — relaunch "
                "the data service to change", info["seed"], seed,
            )
        if "augment" in info and bool(info["augment"]) != augment:
            log.warning(
                "data service pipeline runs augment=%s (requested %s): the "
                "server's decode_fn wins", info["augment"], augment,
            )

        def stream():
            try:
                yield from remote.batches(repeat=True)
            finally:
                remote.close()

        return stream()
    decode = filestream.image_decode_fn(augment=augment, seed=seed)
    if src.kind == "native":
        shards = src.train_shards[w::n_workers]
        if not shards:
            # Disjointness is the contract (the npz path row-strides, so any
            # n_workers works there); silently re-streaming ALL shards would
            # duplicate data across workers.
            raise ValueError(
                f"native loader: {len(src.train_shards)} train shard(s) "
                f"cannot give {n_workers} workers disjoint streams — write "
                f"more shards (shard_records smaller) or fewer workers"
            )
        return (
            decode(b)
            for b in native_loader.NativeFileStream(
                shards, batch_size=batch_size, seed=seed + w, repeat=True
            )
        )
    if src.kind == "stream":
        return iter(
            filestream.FileStreamPipeline(
                src.train_shards,
                batch_size=batch_size * n_workers,
                decode_fn=decode,
                seed=seed,
                process_index=w,
                process_count=n_workers,
            )
        )
    if worker is not None:
        return iter(
            InMemoryPipeline(
                src.ds.train, batch_size=batch_size, seed=seed + w,
                process_index=0, process_count=1,
            )
        )
    return iter(InMemoryPipeline(src.ds.train, batch_size=batch_size, seed=seed))
