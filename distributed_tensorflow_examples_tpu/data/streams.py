"""Shared --data_dir resolution for the image CLIs (W2 cifar10, W3 resnet50).

One place implements the three-way source selection every image example
needs (SURVEY.md T7), so the CLIs cannot drift:

- ``shard-*.dtxr``  -> NATIVE C++ loader (native/dataloader.cc),
- ``shard-*.npz``   -> Python streaming pipeline (filestream),
- anything else     -> in-RAM dataset from ``fallback()`` (real file or
                       synthetic).

The LAST shard is held out as the eval split (one chunk in RAM) so test
accuracy measures the streamed distribution; a single-shard directory
reuses it for eval with an explicit memorization warning.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Iterator

import numpy as np

from . import datasets, filestream, native_loader
from .pipeline import InMemoryPipeline

log = logging.getLogger("dtx.data")


@dataclasses.dataclass(frozen=True)
class ImageSource:
    kind: str  # "native" | "stream" | "memory"
    ds: datasets.ArrayDataset  # .test always populated; .train only for memory
    train_shards: list[str]


def resolve_image_source(
    data_dir: str | None,
    *,
    fallback: Callable[[], datasets.ArrayDataset],
    seed: int,
    num_classes: int,
    name: str = "dataset",
) -> ImageSource:
    raw = native_loader.list_raw_shards(data_dir) if data_dir else []
    if raw:
        test = filestream.image_decode_fn(seed=seed)(
            native_loader.read_raw_shard(raw[-1])
        )
        train, held = _holdout(raw)
        log.info(
            "%s source: native:%s (%d train shards, C++ loader, %s)",
            name, data_dir, len(train), held,
        )
        return ImageSource(
            "native",
            datasets.ArrayDataset({}, test, f"native:{data_dir}", num_classes),
            train,
        )
    npz = filestream.list_shards(data_dir) if data_dir else []
    if npz:
        test = filestream.image_decode_fn(seed=seed)(filestream.load_chunk(npz[-1]))
        train, held = _holdout(npz)
        log.info(
            "%s source: stream:%s (%d train shards, %s)",
            name, data_dir, len(train), held,
        )
        return ImageSource(
            "stream",
            datasets.ArrayDataset({}, test, f"stream:{data_dir}", num_classes),
            train,
        )
    ds = fallback()
    log.info("%s source: %s", name, ds.source)
    return ImageSource("memory", ds, [])


def _holdout(shards: list[str]) -> tuple[list[str], str]:
    if len(shards) > 1:
        return shards[:-1], "1 held-out eval shard"
    return shards, "eval REUSES the single train shard (memorization!)"


def train_iter(
    src: ImageSource,
    *,
    batch_size: int,
    seed: int,
    augment: bool = True,
    worker: int | None = None,
    n_workers: int = 1,
) -> Iterator[dict[str, np.ndarray]]:
    """Training batches of ``batch_size`` from the resolved source.

    ``worker``/``n_workers``: the PS-emulation per-worker split — worker w
    streams a disjoint shard subset (native) / row stride (stream) / its own
    sample stream (memory), each with a worker-distinct seed.
    """
    w = 0 if worker is None else worker
    decode = filestream.image_decode_fn(augment=augment, seed=seed)
    if src.kind == "native":
        shards = src.train_shards[w::n_workers]
        if not shards:
            # Disjointness is the contract (the npz path row-strides, so any
            # n_workers works there); silently re-streaming ALL shards would
            # duplicate data across workers.
            raise ValueError(
                f"native loader: {len(src.train_shards)} train shard(s) "
                f"cannot give {n_workers} workers disjoint streams — write "
                f"more shards (shard_records smaller) or fewer workers"
            )
        return (
            decode(b)
            for b in native_loader.NativeFileStream(
                shards, batch_size=batch_size, seed=seed + w, repeat=True
            )
        )
    if src.kind == "stream":
        return iter(
            filestream.FileStreamPipeline(
                src.train_shards,
                batch_size=batch_size * n_workers,
                decode_fn=decode,
                seed=seed,
                process_index=w,
                process_count=n_workers,
            )
        )
    if worker is not None:
        return iter(
            InMemoryPipeline(
                src.ds.train, batch_size=batch_size, seed=seed + w,
                process_index=0, process_count=1,
            )
        )
    return iter(InMemoryPipeline(src.ds.train, batch_size=batch_size, seed=seed))
