"""Flash attention as a Pallas TPU kernel (forward + FA2 backward).

The reference's "custom native op" slot is hand-written C++ compiled into
libtensorflow (SURVEY.md D11/D12); the TPU-native equivalent is a Pallas
kernel lowered through Mosaic.  This is the framework's flagship custom
kernel: O(block) VMEM attention — neither the [T, T] score matrix nor the
full k/v sequence is ever resident on-chip, so sequence length is bounded by
HBM, not VMEM (plain XLA attention materialises [T, T] scores and dies at
moderate T; a full-k/v-in-VMEM kernel dies at ~16k).

Design (per /opt/skills/guides/pallas_guide.md):
- 3D grid (batch*heads, q blocks, k blocks); the k dimension is innermost
  and "arbitrary" (sequential), so the online-softmax state for one q block
  lives in VMEM scratch across k steps and the output block is written on
  the last k step.
- Causal: blocks fully above the diagonal skip their compute via ``pl.when``
  (grid steps still occur, but no matmuls issue).
- Online softmax in f32; NEG_INF finite mask keeps partially-masked blocks
  NaN-free (same contract as ops.attention).
- Backward: two kernels with the same structure — dq (grid over q blocks,
  inner over k) and dk/dv (grid over k blocks, inner over q) — using the
  saved LSE and the FA2 recurrence: p = exp(s - lse); ds = p*(do.v^T - D);
  D = rowsum(do * o).
- ``interpret=True`` off-TPU so CPU tests run the same kernels.

Composes with ring attention (ops.attention): the ring rotates k/v shards
between chips; this kernel is the per-chip block compute.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: log2(e): the kernels run the online softmax in BASE 2 — ``exp2`` is the
#: hardware primitive (``exp`` lowers to exp2 plus a multiply per element,
#: and the [bq, bk] score tile is exactly where per-element VPU work
#: competes with the MXU at head_dim 64).  The 1/sqrt(d) scale is folded
#: into the same constant and applied ONCE to q (O(T*d)) instead of to
#: every score tile (O(T^2)).
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453

#: q/k.T with K-dim contraction (dim 1 of both operands).
_TRANS_B = (((1,), (1,)), ((), ()))
#: Contract dim 0 of both operands: a.T @ b without materialising a.T.
_TRANS_A = (((0,), (0,)), ((), ()))


def _dot_nt(a, b):
    """a @ b.T at the MXU's native input rate: operands keep their storage
    dtype (bf16 runs 8x the f32 rate on v5e) and accumulate in f32 via
    ``preferred_element_type`` — f32-casting the inputs first (the r1 kernel)
    silently ran every matmul at the f32 rate."""
    return jax.lax.dot_general(a, b, _TRANS_B, preferred_element_type=jnp.float32)


def _dot(a, b):
    """a @ b, f32 accumulation; ``a`` is cast to ``b``'s dtype first (the
    softmax weights are f32 — feed the MXU its native input width)."""
    return jax.lax.dot(a.astype(b.dtype), b, preferred_element_type=jnp.float32)


def _dot_tn(a, b):
    """a.T @ b via dot_general (no explicit transpose of the score tile)."""
    return jax.lax.dot_general(
        a.astype(b.dtype), b, _TRANS_A, preferred_element_type=jnp.float32
    )


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def compiler_params(semantics: tuple[str, ...]):
    """Version shim: pallas renamed TPUCompilerParams -> CompilerParams.
    Both vintages take the same dimension_semantics tuple, and the
    TPUCompilerParams-era interpret mode runs these kernels correctly
    (verified on jax 0.4.37), so resolve whichever this jax ships.  The
    ONE spelling every TPU kernel in the package uses."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(dimension_semantics=semantics)


def _params():
    return compiler_params(("parallel", "parallel", "arbitrary"))


def _mask(s, qi, kj, bq, bk):
    qpos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = kj * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(kpos > qpos, NEG_INF, s)


def _visible(qi, kj, bq, bk):
    """False iff the (qi, kj) block is entirely above the causal diagonal."""
    return kj * bk <= (qi + 1) * bq - 1


def _fully_visible(qi, kj, bq, bk):
    """True iff no element of the (qi, kj) block is masked (block entirely
    on/below the diagonal) — such blocks skip the iota/where mask and the
    masked-row guard entirely.  With bq == bk tiles only the diagonal
    blocks take the masked branch."""
    return kj * bk + bk - 1 <= qi * bq


def _causal_dispatch(step, causal, qi, kj, bq, bk):
    """Shared three-way block dispatch for every kernel: mask-free compute
    on fully-visible blocks, masked compute on diagonal-straddling blocks,
    nothing above the diagonal.  ``step(masked)`` returns the traced block
    body (the per-kernel compute closure)."""
    if causal:
        full = _fully_visible(qi, kj, bq, bk)
        pl.when(full)(step(masked=False))
        pl.when(
            jnp.logical_and(_visible(qi, kj, bq, bk), jnp.logical_not(full))
        )(step(masked=True))
    else:
        step(masked=False)()


# ----------------------------------------------------------------------------
# Forward
# ----------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *, causal, bq, bk):
    """q arrives PRE-SCALED by scale*log2(e); softmax state is base-2 (m/l
    in exp2 units), converted to the natural-log lse contract at the end."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    def _step(masked: bool):
        def _compute():
            q, k, v = q_ref[0], k_ref[0], v_ref[0]  # native dtype into the MXU
            s = _dot_nt(q, k)  # [bq, bk] f32, base-2 logits
            if masked:
                s = _mask(s, qi, kj, bq, bk)
            m_prev, l_prev = m_sc[:], l_sc[:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp2(s - m_new)
            if masked:
                p = p * (s > NEG_INF / 2)  # fully-masked rows contribute 0
            alpha = jnp.exp2(m_prev - m_new)
            acc_sc[:] = acc_sc[:] * alpha + _dot(p, v)
            l_sc[:] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
            m_sc[:] = m_new

        return _compute

    _causal_dispatch(_step, causal, qi, kj, bq, bk)

    @pl.when(kj == nk - 1)
    def _finish():
        l_safe = jnp.maximum(l_sc[:], 1e-30)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_sc[:] * LN2 + jnp.log(l_safe)


def _fwd(q, k, v, *, causal, block_q, block_k, out_dtype=None):
    bh, t, d = q.shape
    scale = 1.0 / math.sqrt(d)
    bq, bk = min(block_q, t), min(block_k, t)
    q = q * jnp.asarray(scale * LOG2E, q.dtype)  # fold scale+base-2 into q
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, bq=bq, bk=bk),
        grid=(bh, t // bq, t // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),  # running max
            pltpu.VMEM((bq, 1), jnp.float32),  # running sum
            pltpu.VMEM((bq, d), jnp.float32),  # output accumulator
        ],
        compiler_params=_params(),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ----------------------------------------------------------------------------
# Backward (FA2)
# ----------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc, *, scale, causal, bq, bk):
    """q arrives PRE-SCALED by scale*log2(e) (the forward's fold); the saved
    natural-log lse is converted to base 2 once per [bq, 1] block."""
    qi, kj = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    def _step(masked: bool):
        def _compute():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            lse2 = lse_ref[0] * LOG2E  # [bq, 1] natural -> base-2
            delta = delta_ref[0]
            s = _dot_nt(q, k)  # base-2 logits
            if masked:
                s = _mask(s, qi, kj, bq, bk)
            p = jnp.exp2(s - lse2)
            if masked:
                p = p * (s > NEG_INF / 2)
            ds = p * (_dot_nt(do, v) - delta)
            dq_sc[:] = dq_sc[:] + _dot(ds, k)

        return _compute

    _causal_dispatch(_step, causal, qi, kj, bq, bk)

    @pl.when(kj == nk - 1)
    def _finish():
        dq_ref[0] = (dq_sc[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal, bq, bk):
    """q PRE-SCALED as in _dq_kernel; dk's pending 1/sqrt(d)*base-2 factors
    are unwound once at the final write, not per block."""
    kj, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    def _step(masked: bool):
        def _compute():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            lse2 = lse_ref[0] * LOG2E
            delta = delta_ref[0]
            s = _dot_nt(q, k)
            if masked:
                s = _mask(s, qi, kj, bq, bk)
            p = jnp.exp2(s - lse2)
            if masked:
                p = p * (s > NEG_INF / 2)
            dv_sc[:] = dv_sc[:] + _dot_tn(p, do)
            ds = p * (_dot_nt(do, v) - delta)
            # ds.T @ q with q still carrying the scale*log2(e) fold: the
            # extra LOG2E is divided back out in _finish.
            dk_sc[:] = dk_sc[:] + _dot_tn(ds, q)

        return _compute

    _causal_dispatch(_step, causal, qi, kj, bq, bk)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = (dk_sc[:] * (1.0 / LOG2E)).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    delta = compute_delta(do, o)
    tq, d = q.shape[1], q.shape[2]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(k.shape[1], block_k)
    if _fused_bwd_policy(tq // bq, k.shape[1] // bk):
        if tq * d * 4 <= _FUSED_MAX_ACC_BYTES:
            return fused_bwd_call(
                q, k, v, do, lse, delta, causal=causal, block_q=bq, block_k=bk
            )
        seg = _fused_segment_rows(tq, d, bq, bk)
        if seg:
            return fused_bwd_segmented(
                q, k, v, do, lse, delta,
                causal=causal, block_q=bq, block_k=bk, seg=seg,
            )
    dq = dq_call(q, k, v, do, lse, delta, causal=causal, block_q=bq, block_k=bk)
    dk, dv = dkv_call(q, k, v, do, lse, delta, causal=causal, block_q=bq, block_k=bk)
    return dq, dk, dv


#: Fused-backward dispatch override: None = auto (the nq/nk >= 4 regime the
#: r3 expected-value analysis funds — BASELINE.md), True/False = force.
_FUSED_BWD_OVERRIDE: bool | None = None

#: Hardware-validation latch (ADVICE r4 medium): the fused kernel's
#: running-flush dq scheme depends on Mosaic writing the revisited dq output
#: window every grid step with last-write-wins ordering — semantics CPU
#: interpret mode cannot validate.  Until ``tools/flash_parity.py`` has
#: PASSED on a real chip, auto-dispatch stays on the split kernels; opt in
#: per-process with DTX_FUSED_BWD=1 (the measurement campaign does, after
#: running the parity gate first).  Flip to True once BASELINE.md records
#: the TPU parity + bitwise-determinism pass.
_FUSED_BWD_VALIDATED = False

#: Upper bound on the fused kernel's [tq, d] f32 dq accumulator (VMEM
#: scratch).  8 MB = T=16384 at head_dim 128 — beyond that the split
#: kernels take over (VMEM is ~tens of MB and the s/p tiles need most of
#: it).
_FUSED_MAX_ACC_BYTES = 8 * 1024 * 1024


def _use_fused_bwd(nq: int, nk: int, tq: int, d: int) -> bool:
    """The fused dq+dk+dv kernel removes the split kernels' s/p recompute
    (2 of 7 block matmuls, half the exp2) at the cost of a [tq, d] f32
    VMEM accumulator and nk running dq flushes; it starts paying at
    nq/nk >= 4 — exactly the long-context (T >= 4k per shard at 1024
    tiles) regime the r3 analysis funds.  The T=2048 flagship (nk=2)
    keeps the split kernels.

    DTX_FUSED_BWD=0 forces split, =1 opts into the auto regime without the
    ``_FUSED_BWD_VALIDATED`` latch (read at trace time, like the block-size
    env vars — one setting per process).

    This predicate answers "single fused call?"; beyond the VMEM cap the
    dispatcher (``_bwd``) may still serve the fused MECHANISM via the
    r5 segmented wrapper (``fused_bwd_segmented``)."""
    return _fused_bwd_policy(nq, nk) and tq * d * 4 <= _FUSED_MAX_ACC_BYTES


def _fused_bwd_policy(nq: int, nk: int) -> bool:
    """Override/env/latch + the nq/nk regime — everything about WANTING the
    fused mechanism; the VMEM-cap/segmentation split is the dispatcher's."""
    import os

    if _FUSED_BWD_OVERRIDE is not None:
        return _FUSED_BWD_OVERRIDE
    env = os.environ.get("DTX_FUSED_BWD", "")
    if env not in ("", "0", "1"):
        # Same contract as the DTX_FLASH_BQ/BK guard: an A/B typo
        # (=true, =yes) must not silently record a split-kernel run
        # under a fused label.
        raise ValueError(f"DTX_FUSED_BWD={env!r}: must be '0' or '1'")
    if env == "0":
        return False
    if env != "1" and not _FUSED_BWD_VALIDATED:
        return False
    return nq >= 4 and nk >= 4


def _fused_segment_rows(tq: int, d: int, bq: int, bk: int) -> int:
    """Largest q-segment length that (a) fits the [seg, d] f32 accumulator
    cap, (b) divides tq, (c) is a multiple of BOTH blocks (the diagonal and
    prefix calls tile k in bk-sized blocks over seg-multiples) — or 0 when
    no such segmentation exists (dispatcher falls back to the split
    kernels)."""
    cap_rows = _FUSED_MAX_ACC_BYTES // (d * 4)
    for m in range(2, tq // bq + 1):
        if tq % m:
            continue
        seg = tq // m
        if seg % bq or seg % bk:
            continue
        if seg <= cap_rows:
            return seg
    return 0


def fused_bwd_segmented(
    q, k, v, do, lse, delta, *, causal, block_q, block_k, seg,
):
    """r5: the fused backward past its VMEM cap — T splits into q segments
    whose [seg, d] dq accumulators fit, each running the SAME hardware-
    validated kernel against only the k/v it can see:

    - causal: segment s pairs one square DIAGONAL call (q_s x k_s, local
      causal == global causal because both carry the same offset) with one
      rectangular full-visibility PREFIX call (q_s x k[:s*seg],
      causal=False); k beyond the segment is fully masked and never runs.
    - non-causal: one rectangular call per segment (q_s x full k).

    dq is exact per segment (summed across its calls); dk/dv arrive as
    per-call partials accumulated in f32 outside the kernel.  Extra HBM
    traffic vs the in-cap path is the f32 dk/dv partial accumulation —
    O(nseg) passes over k-prefix-sized buffers — which the 7->5 matmul
    saving dominates at the T >= 32k shapes this serves (BASELINE.md r5).
    Parity: tests/test_flash_attention.py segmented sweep."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nseg = tq // seg
    f32 = jnp.float32
    dk_acc = jnp.zeros((bh, tk, d), f32)
    dv_acc = jnp.zeros((bh, tk, d), f32)
    dq_parts = []
    for s in range(nseg):
        rows = slice(s * seg, (s + 1) * seg)
        q_s, do_s = q[:, rows], do[:, rows]
        lse_s, delta_s = lse[:, rows], delta[:, rows]
        if not causal:
            dq_s, dk_p, dv_p = fused_bwd_call(
                q_s, k, v, do_s, lse_s, delta_s,
                causal=False, block_q=block_q, block_k=block_k, out_dtype=f32,
            )
            dk_acc = dk_acc + dk_p
            dv_acc = dv_acc + dv_p
        else:
            kcols = slice(s * seg, (s + 1) * seg)
            dq_s, dk_d, dv_d = fused_bwd_call(
                q_s, k[:, kcols], v[:, kcols], do_s, lse_s, delta_s,
                causal=True, block_q=block_q, block_k=block_k, out_dtype=f32,
            )
            dk_acc = dk_acc.at[:, kcols].add(dk_d)
            dv_acc = dv_acc.at[:, kcols].add(dv_d)
            if s > 0:
                pre = slice(0, s * seg)
                dq_p, dk_p, dv_p = fused_bwd_call(
                    q_s, k[:, pre], v[:, pre], do_s, lse_s, delta_s,
                    causal=False, block_q=block_q, block_k=block_k,
                    out_dtype=f32,
                )
                dq_s = dq_s + dq_p
                dk_acc = dk_acc.at[:, pre].add(dk_p)
                dv_acc = dv_acc.at[:, pre].add(dv_p)
        dq_parts.append(dq_s.astype(q.dtype))
    return (
        jnp.concatenate(dq_parts, axis=1),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
    )


def compute_delta(do, o):
    """FA2's D = rowsum(do * o), f32 — shared by the plain and ring paths."""
    return jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )


def dq_call(q, k, v, do, lse, delta, *, causal, block_q, block_k, out_dtype=None):
    """dq for one (q-block x k/v-block) pairing — exposed so ring attention
    can run the SAME Pallas backward per hop (q local, k/v visiting).
    q/do/lse/delta: [bh, tq, ...]; k/v: [bh, tk, d].  ``out_dtype``: f32 for
    ring partials (see fwd_call)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    bq, bk = min(block_q, tq), min(block_k, tk)
    q = q * jnp.asarray(scale * LOG2E, q.dtype)  # base-2 fold (see _fwd)

    return pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk),
        grid=(bh, tq // bq, tk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),  # do
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),  # delta
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)


def dkv_call(q, k, v, do, lse, delta, *, causal, block_q, block_k, out_dtype=None):
    """dk/dv for one (q-block x k/v-block) pairing (ring-reusable, see
    dq_call)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    bq, bk = min(block_q, tq), min(block_k, tk)
    q = q * jnp.asarray(scale * LOG2E, q.dtype)  # base-2 fold (see _fwd)

    return pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk),
        grid=(bh, tk // bk, tq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # do
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, out_dtype or k.dtype),
            jax.ShapeDtypeStruct(v.shape, out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)


def _fused_bwd_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dk_ref, dv_ref, dq_acc, dk_sc, dv_sc, *, scale, causal, bq, bk
):
    """dq+dk+dv from ONE s/p computation per (q, k) block pair (the split
    kernels compute s and do.v^T twice each — 7 block matmuls vs 5 here,
    and the exp2 softmax recompute twice vs once).

    Layout: grid (bh, k blocks, q blocks) with q innermost — dk/dv
    accumulate in [bk, d] VMEM scratch across the inner loop (written on
    its last step), while dq accumulates in a FULL-LENGTH [tq, d] f32
    scratch that persists across the whole grid.  Every step stores the
    RUNNING dq value of its q block to the output window: Pallas flushes
    the window once per step, earlier (incomplete) flushes are overwritten
    sequentially, and the LAST flush of each window — at the final k
    iteration — carries the completed sum.  No aliasing, no cross-step
    output reads: only documented Pallas semantics, so interpret mode and
    Mosaic agree (the r3-parked alias design did not — interpret re-reads
    pristine input on every visit).  Net HBM traffic is BELOW the split
    kernels' (nk bf16 dq flushes replace a full second operand pass), so
    the 7->5 matmul saving is pure win; the full-length accumulator is
    what gates dispatch via _FUSED_MAX_ACC_BYTES (VMEM)."""
    kj, qi = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)
    rows = pl.ds(qi * bq, bq)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    def _step(masked: bool):
        def _compute():
            q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
            lse2 = lse_ref[0] * LOG2E
            delta = delta_ref[0]
            s = _dot_nt(q, k)  # base-2 logits (q pre-scaled)
            if masked:
                s = _mask(s, qi, kj, bq, bk)
            p = jnp.exp2(s - lse2)
            if masked:
                p = p * (s > NEG_INF / 2)
            dv_sc[:] = dv_sc[:] + _dot_tn(p, do)
            ds = p * (_dot_nt(do, v) - delta)
            dk_sc[:] = dk_sc[:] + _dot_tn(ds, q)
            contrib = _dot(ds, k) * scale
            # kj == 0 is visible from every q block (causal or not), so
            # the first visit (re)initialises this b's accumulator slice
            # (stale values from the previous b never leak).
            dq_acc[rows, :] = jnp.where(
                kj == 0, contrib, dq_acc[rows, :] + contrib
            )

        return _compute

    _causal_dispatch(_step, causal, qi, kj, bq, bk)

    # Store the RUNNING value every step (the window flushes regardless;
    # an unwritten buffer would flush garbage).  The last flush per q
    # block — at kj = nk-1 — is the complete sum.
    dq_ref[0] = dq_acc[rows, :].astype(dq_ref.dtype)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = (dk_sc[:] * (1.0 / LOG2E)).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def fused_bwd_call(q, k, v, do, lse, delta, *, causal, block_q, block_k, out_dtype=None):
    """(dq, dk, dv) for one (q x k/v) pairing via the fused kernel (same
    contract as dq_call + dkv_call; ``out_dtype`` = f32 for ring
    partials).  Dispatch via ``_use_fused_bwd`` — the [tq, d] f32 dq
    accumulator lives in VMEM."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(d)
    bq, bk = min(block_q, tq), min(block_k, tk)
    qs = q * jnp.asarray(scale * LOG2E, q.dtype)  # base-2 fold (see _fwd)

    return pl.pallas_call(
        functools.partial(
            _fused_bwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk
        ),
        grid=(bh, tk // bk, tq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # q
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # k
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # v
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # do
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),  # dq
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # dk
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),  # dv
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
            jax.ShapeDtypeStruct(k.shape, out_dtype or k.dtype),
            jax.ShapeDtypeStruct(v.shape, out_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tq, d), jnp.float32),  # full-length dq accumulator
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        # Unlike the split kernels, BOTH k and q grid dims carry loop state
        # (dq_acc accumulates across kj with kj==0 as its reinit; dk/dv
        # scratch across qi) — only the batch*heads dim may be partitioned.
        compiler_params=compiler_params(
            ("parallel", "arbitrary", "arbitrary")
        ),
        interpret=_interpret(),
    )(qs, k, v, do, lse, delta)


def fwd_call(q, k, v, *, causal, block_q, block_k, out_dtype=None):
    """(o, lse) forward for one block pairing — ring attention's per-hop
    compute (lse enables exact cross-hop online-softmax merging).

    ``out_dtype``: set f32 when the result is a PARTIAL to be merged — the
    kernel's accumulator is f32 already, and rounding each hop's partial to
    bf16 before merging accumulates O(n_hops) quantization error."""
    return _fwd(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        out_dtype=out_dtype,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhd(q, k, v, causal, block_q, block_k):
    o, _ = _fwd(q, k, v, causal=causal, block_q=block_q, block_k=block_k)
    return o


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    o, lse = _fwd(q, k, v, causal=causal, block_q=block_q, block_k=block_k)
    return o, (q, k, v, o, lse)


_flash_bhd.defvjp(_flash_fwd_rule, _bwd)


def _pick_block(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is <= ``want``: any T works (e.g. 640 ->
    128 with the default 512), degrading to smaller tiles rather than raising
    at trace time.  Degenerate divisors (prime-ish T -> tiny tiles) get a
    warning: pad T to a multiple of 128 for MXU-shaped blocks."""
    from .common import largest_divisor

    b = largest_divisor(t, want)
    if b < 128 <= t:
        import warnings

        warnings.warn(
            f"flash_attention: seq len {t} has no block-sized divisor <= "
            f"{want}; using {b}-row tiles (slow on TPU). Pad T to a multiple "
            "of 128 for MXU-shaped blocks."
        )
    return b


def flash_viable(t: int) -> bool:
    """Shared auto-dispatch gate: flash pays off on TPU when the (per-shard)
    sequence tiles cleanly; awkward lengths degrade to tiny Pallas blocks,
    slower than XLA attention.  Used by both the non-ring auto path
    (models/transformer._use_flash) and the ring auto path
    (ops/attention.sequence_parallel_attention) so the two policies cannot
    drift."""
    import jax as _jax

    return _jax.default_backend() == "tpu" and t % 512 == 0


def flash_attention(
    q, k, v, *, causal: bool = False,
    block_q: int | None = None, block_k: int | None = None,
):
    """Drop-in for ``ops.attention.mha``: q/k/v [B, H, T, D] -> [B, H, T, D].

    Block sizes auto-shrink to the largest divisor of T (so any T traces);
    differentiable (custom FA2 VJP); runs interpreted off-TPU.  Default
    1024x1024 tiles: the measured optimum of the v5e sweep (BASELINE.md;
    ~18% faster than 512x512, and 2048 tiles blow VMEM at D=64).  The
    DTX_FLASH_BQ / DTX_FLASH_BK env vars override the defaults — the
    in-step block-sweep knob (bench.py re-runs per setting), read at
    trace time.
    """
    import os

    def _env_block(name: str) -> int:
        raw = os.environ.get(name, "1024")
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"{name}={raw!r}: flash block overrides must be integers"
            ) from None
        if val < 128:
            # A sweep typo (0, '2k', 16) must not silently record a
            # pathological 1-row-tile run as a data point.
            raise ValueError(f"{name}={val}: flash blocks must be >= 128")
        return val

    if block_q is None:
        block_q = _env_block("DTX_FLASH_BQ")
    if block_k is None:
        block_k = _env_block("DTX_FLASH_BK")
    B, H, T, D = q.shape
    bq = _pick_block(T, block_q)
    bk = _pick_block(T, block_k)
    if "DTX_FLASH_BQ" in os.environ or "DTX_FLASH_BK" in os.environ:
        # Env overrides are read at TRACE time and do not key the jit cache:
        # an in-process sweep that re-sets them silently reuses the first
        # trace (ADVICE r4).  Each sweep point must be a fresh process
        # (bench.py is); this line only prints when a trace actually
        # happens, so a sweep log with a missing line is a stale-cache run.
        import sys

        print(
            f"flash_attention: traced with blocks bq={bq} bk={bk} (T={T})",
            file=sys.stderr,
        )
    fold = lambda x: x.reshape(B * H, T, D)
    o = _flash_bhd(fold(q), fold(k), fold(v), causal, bq, bk)
    return o.reshape(B, H, T, D)
