"""Small shared helpers for the custom-op modules."""

from __future__ import annotations


def largest_divisor(n: int, want: int) -> int:
    """Largest divisor of ``n`` that is <= ``want`` (>= 1).  The common core
    of every block/tile/group-size pick in ops/ — kernels layer their own
    policy (MXU-alignment warnings, shard-multiple constraints) on top."""
    b = max(1, min(n, want))
    while n % b:
        b -= 1
    return b
