"""Fused BatchNorm(+ReLU) statistics kernels (Pallas TPU) + custom-VJP path.

Why this exists (r3 profile, BASELINE.md): at batch 256 the ResNet-50 step
spent 42% of its device time in BN-adjacent reductions — the forward
E[x]/E[x^2] passes (``convert_reduce`` fusions, 17.8 ms) and the backward
sum(dy)/sum(dy*xhat) passes (``multiply_reduce`` fusions, 23.7 ms) — running
at ~260-440 GB/s against an ~820 GB/s HBM roofline, while the convs
themselves already ran near the MXU roofline.  These kernels do each
direction's statistics in ONE near-bandwidth pass; all elementwise work
(normalise, scale, dx) stays in XLA so it keeps fusing into the adjacent
convolutions exactly as before.

Two design points learned the hard way (first cut was 1.7x SLOWER than the
XLA path it replaced):
- Blocks are 4-D [bn, H, W, C] views of the activation, NOT a reshape to
  [M, C]: the host-level reshape materialised layout copies (+58 ms/step).
- The backward kernel takes the RAW upstream cotangent and recomputes the
  ReLU mask from xhat (mask = xhat*(inv*scale)+bias > 0), so the masked
  gradient dy = do * mask never materialises in HBM — in the XLA path that
  mask application fused into the reduction; a Pallas operand would have
  forced it into its own full-size pass (+29 ms/step).

SyncBN contract (layers.batchnorm): statistics are over the GLOBAL batch —
per-shard partial sums inside ``shard_map``, ``psum`` over the ``data``
axis (the explicit form of the reduction GSPMD inserts for the XLA path;
reference role: MirroredStrategy's synchronized BN, SURVEY.md W3).

Backward math (standard BN, biased variance, matching the E[x^2]-E[x]^2
forward):  xhat = (x - mean) * inv;  dy = do * relu_mask;  s1 = sum(dy);
s2 = sum(dy * xhat);  dbeta = s1;  dgamma = s2;
dx = gamma * inv * (dy - s1/n - xhat * s2/n).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel import collectives
from . import flash_attention
from .common import largest_divisor as _largest_divisor

#: Test hook: force the fused path off-TPU so CPU parity tests exercise the
#: same code (Pallas kernels run interpreted).
FORCE_PALLAS = False

#: Statistics implementation: "pallas" (hand-written reduction kernels) or
#: "matmul" (MXU 1^T.x / block-diag Gram contractions).  STATUS (BASELINE.md
#: r3 measured table): on the current XLA/axon stack BOTH lose to the plain
#: XLA path end-to-end on ResNet-50 — Pallas operands force layout-
#: conversion copies and break conv fusion chains; the matmul forms get
#: algebraically simplified back into the same slow reduces.  The module is
#: retained as the measured evidence for that ceiling and for stacks where
#: Pallas operands stop forcing layout copies; nothing in the shipped
#: models threads a mesh into batchnorm by default.
IMPL = "pallas"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _use_pallas() -> bool:
    """Gate for the fused BN path as a whole (name kept for callers)."""
    return FORCE_PALLAS or not _interpret()


def _gram_diag(a2d, b2d, blk: int = 128):
    """sum_m a[m,c]*b[m,c] per channel via BLOCK-DIAGONAL MXU contractions:
    channels split into ``blk``-wide groups, one batched [blk, blk] Gram per
    group, diagonal extracted.  2*M*C*blk FLOPs — the full [C, C] Gram
    (first cut) cost 2*M*C^2, which at C=1024/2048 added ~4.8 TF/step to
    the ResNet bench, ~24 ms of pure waste.  The contraction streams both
    operands once at near-HBM-bandwidth where XLA's reduce emitter measured
    260-440 GB/s."""
    m, c = a2d.shape
    if c <= blk:
        g = jax.lax.dot_general(
            a2d, b2d, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return jnp.diagonal(g)
    # One [blk, blk] Gram per channel-block, via column SLICES: in the
    # tiled C-minor layout each 128-wide channel slice is layout-native,
    # where a batched dot_general with the batch dim in the middle made XLA
    # transpose-copy the whole operand first (measured slower than the full
    # Gram it was meant to fix).
    diags = []
    for i in range(0, c, blk):
        ga = jax.lax.dot_general(
            a2d[:, i : i + blk],
            b2d[:, i : i + blk],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        diags.append(jnp.diagonal(ga))
    return jnp.concatenate(diags)


def _mm_sums(x2d):
    ones = jnp.ones((1, x2d.shape[0]), x2d.dtype)
    s = jax.lax.dot(ones, x2d, preferred_element_type=jnp.float32)[0]
    return s


def mm_stats(x):
    """Matmul-form statistics: (sum [C], sumsq [C]) f32."""
    c = x.shape[-1]
    x2d = x.reshape(-1, c)
    return _mm_sums(x2d), _gram_diag(x2d, x2d)


def mm_bwd_stats(do, x, mean, inv, scale, bias, *, relu: bool):
    """Matmul-form backward sums: s1 = sum(dy), s2 = sum(dy * xhat), with
    dy = do * relu_mask and s2 folded onto RAW operands:
    s2 = inv * (diag(dy^T x) - mean * s1) — no xhat tensor materialises."""
    c = x.shape[-1]
    do2, x2 = do.reshape(-1, c), x.reshape(-1, c)
    if relu:
        ivs = (inv * scale).astype(x.dtype)
        pre = (x2 - mean.astype(x.dtype)) * ivs + bias.astype(x.dtype)
        do2 = do2 * (pre > 0).astype(do.dtype)
    s1 = _mm_sums(do2)
    s2 = inv * (_gram_diag(do2, x2) - mean * s1)
    return s1, s2


_BLOCK_BYTES = 1 << 20


def _pick_blocks(n: int, h: int, w: int, c: int, itemsize: int):
    """(bn, bh): block [bn, bh, W, C] stays ~<=1 MB — two double-buffered
    bf16 input streams PLUS the kernel's f32 temporaries (xf, xhat,
    products: ~5 block-sized f32 arrays in the backward) must fit the
    16 MB scoped-VMEM budget.  Large images (112^2 x 64 = 1.6 MB each)
    additionally block over H; small ones batch several images per step."""
    per_image = h * w * c * itemsize
    if per_image <= _BLOCK_BYTES:
        return _largest_divisor(n, _BLOCK_BYTES // per_image), h
    return 1, _largest_divisor(h, _BLOCK_BYTES // (w * c * itemsize))


def _row_specs(bn, bh, w, c):
    return pl.BlockSpec((bn, bh, w, c), lambda i, j: (i, j, 0, 0))


def _vec_spec(c):
    return pl.BlockSpec((1, c), lambda i, j: (0, 0))


def _is_first():
    return jnp.logical_and(pl.program_id(0) == 0, pl.program_id(1) == 0)


def _is_last():
    return jnp.logical_and(
        pl.program_id(0) == pl.num_programs(0) - 1,
        pl.program_id(1) == pl.num_programs(1) - 1,
    )


def _stats_kernel(x_ref, s_ref, ss_ref, acc_s, acc_ss):
    @pl.when(_is_first())
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)
        acc_ss[:] = jnp.zeros_like(acc_ss)

    c = x_ref.shape[-1]
    xf = x_ref[...].astype(jnp.float32).reshape(-1, c)
    acc_s[:] += jnp.sum(xf, axis=0, keepdims=True)
    acc_ss[:] += jnp.sum(xf * xf, axis=0, keepdims=True)

    @pl.when(_is_last())
    def _done():
        s_ref[...] = acc_s[:]
        ss_ref[...] = acc_ss[:]


def bn_stats(x):
    """x [N, H, W, C] -> (sum [1, C] f32, sumsq [1, C] f32), one pass."""
    n, h, w, c = x.shape
    bn, bh = _pick_blocks(n, h, w, c, x.dtype.itemsize)
    return pl.pallas_call(
        _stats_kernel,
        grid=(n // bn, h // bh),
        in_specs=[_row_specs(bn, bh, w, c)],
        out_specs=[_vec_spec(c), _vec_spec(c)],
        out_shape=[
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),
            pltpu.VMEM((1, c), jnp.float32),
        ],
        compiler_params=flash_attention.compiler_params(
            ("arbitrary", "arbitrary")
        ),
        interpret=_interpret(),
    )(x)


def bn_bwd_stats(do, x, mean, inv, scale, bias, *, relu: bool):
    """(s1, s2) = (sum(dy), sum(dy*xhat)) with dy = do * relu_mask computed
    in-kernel (relu=True) or dy = do (relu=False); one two-stream pass."""
    n, h, w, c = x.shape
    bn, bh = _pick_blocks(n, h, w, c, x.dtype.itemsize)
    return pl.pallas_call(
        functools.partial(_bwd_stats_kernel, relu=relu),
        grid=(n // bn, h // bh),
        in_specs=[
            _row_specs(bn, bh, w, c),
            _row_specs(bn, bh, w, c),
            _vec_spec(c),
            _vec_spec(c),
            _vec_spec(c),
            _vec_spec(c),
        ],
        out_specs=[_vec_spec(c), _vec_spec(c)],
        out_shape=[
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, c), jnp.float32),
            pltpu.VMEM((1, c), jnp.float32),
        ],
        compiler_params=flash_attention.compiler_params(
            ("arbitrary", "arbitrary")
        ),
        interpret=_interpret(),
    )(do, x, mean, inv, scale, bias)


def _bwd_stats_kernel(
    do_ref, x_ref, mean_ref, inv_ref, scale_ref, bias_ref, s1_ref, s2_ref,
    a1, a2, *, relu,
):
    @pl.when(_is_first())
    def _init():
        a1[:] = jnp.zeros_like(a1)
        a2[:] = jnp.zeros_like(a2)

    c = x_ref.shape[-1]
    dof = do_ref[...].astype(jnp.float32).reshape(-1, c)
    xf = x_ref[...].astype(jnp.float32).reshape(-1, c)
    xhat = (xf - mean_ref[...]) * inv_ref[...]
    if relu:
        pre = xhat * scale_ref[...] + bias_ref[...]
        dof = dof * (pre > 0)
    a1[:] += jnp.sum(dof, axis=0, keepdims=True)
    a2[:] += jnp.sum(dof * xhat, axis=0, keepdims=True)

    @pl.when(_is_last())
    def _done():
        s1_ref[...] = a1[:]
        s2_ref[...] = a2[:]


def _shard_stats(fn, mesh, n_sharded, n_rep, **kw):
    """Run a local-partial-sums kernel under shard_map with a psum over the
    'data' axis (SyncBN's cross-replica reduction, made explicit)."""
    spec_x = jax.sharding.PartitionSpec("data")
    spec_r = jax.sharding.PartitionSpec()
    in_specs = (spec_x,) * n_sharded + (spec_r,) * n_rep

    def local(*args):
        outs = fn(*args, **kw)
        return tuple(jax.lax.psum(o, "data") for o in outs)

    return collectives.shard_map(
        local, mesh, in_specs=in_specs, out_specs=(spec_r, spec_r)
    )


def _count(x):
    # ``x`` is the jit-level GLOBAL array (shard_map only sees shards of
    # it), so its row count already IS the SyncBN global count.
    return x.size // x.shape[-1]


def _stats_of(x, mesh):
    if IMPL == "matmul":
        # Native XLA contractions: GSPMD partial-sums + all-reduces them
        # over the sharded row dim itself — no shard_map needed for SyncBN.
        s, ss = mm_stats(x)
    elif mesh is not None and mesh.shape.get("data", 1) > 1:
        s, ss = _shard_stats(bn_stats, mesh, 1, 0)(x)
        s, ss = s[0], ss[0]
    else:
        s, ss = bn_stats(x)
        s, ss = s[0], ss[0]
    n = _count(x)
    mean = s / n
    var = jnp.maximum(ss / n - jnp.square(mean), 0.0)  # one-pass, clamped
    return mean, var


def _bn_fwd_impl(scale, bias, x, eps, mesh, relu):
    mean, var = _stats_of(x, mesh)
    inv = jax.lax.rsqrt(var + eps)
    # Same elementwise formula (and compute dtype) as layers.batchnorm's
    # XLA path; stays in XLA so it fuses into the consuming conv.
    y = (x - mean.astype(x.dtype)) * (inv * scale).astype(x.dtype) + bias.astype(
        x.dtype
    )
    if relu:
        y = jax.nn.relu(y)
    return y, mean, var


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def batchnorm_train(scale, bias, x, eps, mesh, relu=False):
    """(y, mean, var); y is post-ReLU when ``relu``.  mean/var feed the
    caller's running-stats update (stop-gradded there — their cotangents
    are zero and the backward ignores them)."""
    return _bn_fwd_impl(scale, bias, x, eps, mesh, relu)


def _bn_train_fwd(scale, bias, x, eps, mesh, relu):
    y, mean, var = _bn_fwd_impl(scale, bias, x, eps, mesh, relu)
    inv = jax.lax.rsqrt(var + eps)
    return (y, mean, var), (scale, bias, x, mean, inv)


def _bn_train_bwd(eps, mesh, relu, res, cts):
    do, _, _ = cts  # mean/var cotangents are zero (running stats stop-grad)
    scale, bias, x, mean, inv = res
    if IMPL == "matmul":
        s1, s2 = mm_bwd_stats(do, x, mean, inv, scale, bias, relu=relu)
    else:
        mean2d, inv2d = mean[None], inv[None]
        s2d = scale[None].astype(jnp.float32)
        b2d = bias[None].astype(jnp.float32)
        if mesh is not None and mesh.shape.get("data", 1) > 1:
            s1, s2 = _shard_stats(bn_bwd_stats, mesh, 2, 4, relu=relu)(
                do, x, mean2d, inv2d, s2d, b2d
            )
        else:
            s1, s2 = bn_bwd_stats(do, x, mean2d, inv2d, s2d, b2d, relu=relu)
        s1, s2 = s1[0], s2[0]
    n = _count(x)
    # Elementwise dx stays in XLA: the ReLU mask recompute and the rank-1
    # broadcasts fuse into the consuming conv-backward ops, as they did on
    # the all-XLA path.
    xhat = (x - mean.astype(x.dtype)) * inv.astype(x.dtype)
    dy = do
    if relu:
        pre = xhat * scale.astype(x.dtype) + bias.astype(x.dtype)
        dy = do * (pre > 0).astype(x.dtype)
    g = (scale * inv).astype(x.dtype)
    dx = g * (dy - (s1 / n).astype(x.dtype) - xhat * (s2 / n).astype(x.dtype))
    return s2, s1, dx  # dgamma, dbeta, dx


batchnorm_train.defvjp(_bn_train_fwd, _bn_train_bwd)
