"""Attention ops: reference MHA + BOTH canonical sequence/context-parallel
layouts — the ring and (r4) Ulysses all-to-all.

No reference analog (SURVEY.md section 5.7: the reference has no attention
model; its longest-sequence workload scales only by TBPTT unroll).  This is
the framework's long-context growth path, first-class per the blueprint:
sequences shard over the mesh ``seq`` axis, and attention runs either

- as a RING — queries stay local while key/value blocks rotate around the
  axis via ``ppermute`` (one hop per step, riding ICI neighbor links), with
  the online-softmax accumulation of flash attention so no shard ever
  materialises the full [T, T] score matrix; works for any head count — or
- as ULYSSES all-to-all CP — one ``all_to_all`` per tensor trades the
  sequence sharding for head sharding, attention runs locally over the
  full sequence (no cross-hop softmax bookkeeping; the fused flash
  backward's regime), one ``all_to_all`` back; needs local heads
  divisible by the seq shards (:func:`ulysses_attention`).

Numerical contract (tested): either layout over a seq-sharded mesh ==
full-sequence attention on one device, for both causal and full attention,
values and gradients.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import collectives

#: Finite "minus infinity" for masked logits: keeps the online-softmax
#: recurrence NaN-free when a block is fully masked (exp(-1e30 - m) == 0 for
#: any finite m), where a true -inf would produce inf-inf = NaN.
NEG_INF = -1e30


def mha(q, k, v, *, causal: bool = False, q_offset: int = 0, k_offset: int = 0):
    """Reference multi-head attention.  q: [B, H, Tq, D], k/v: [B, H, Tk, D].

    ``q_offset``/``k_offset`` are the global positions of the first row of
    q/k — the pieces ring attention needs for causal masking across shards.
    """
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(kpos > qpos, NEG_INF, s)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _block(q, k, v, carry, *, scale, causal, q_offset, k_offset):
    """One online-softmax accumulation step (the flash-attention recurrence)
    against a single k/v block.  carry = (o, m, l):
    o [B,H,Tq,D] unnormalised output, m [B,H,Tq,1] running max,
    l [B,H,Tq,1] running sum of exp."""
    o, m, l = carry
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(kpos > qpos, NEG_INF, s)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # Valid (unmasked) entries only: a fully-masked block contributes 0.
    p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
    alpha = jnp.exp(m - m_new)
    o = o * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    return o, m_new, l


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False):
    """Sequence-parallel attention inside ``shard_map``: queries stay local,
    k/v blocks rotate ``axis_size`` hops around the ring (permuter.h role —
    SURVEY.md D11 — but emitted as XLA ``ppermute`` on ICI).

    Shapes per shard: q/k/v [B, H, T_local, D]; the global sequence is the
    concatenation over the axis in index order.
    """
    n = collectives.axis_size(axis_name)
    my = collectives.axis_index(axis_name)
    t_local = q.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    q32, dtype = q.astype(jnp.float32), q.dtype
    o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)

    def body(carry, i):
        o, m, l, k, v = carry
        src = (my + i) % n
        o, m, l = _block(
            q32,
            k.astype(jnp.float32),
            v.astype(jnp.float32),
            (o, m, l),
            scale=scale,
            causal=causal,
            q_offset=my * t_local,
            k_offset=src * t_local,
        )
        # Receive-from-next rotation (shift=-1): after i hops we hold shard
        # (my + i) % n's k/v; every shard does n identical hops => a clean
        # ICI ring schedule.  The nth hop returns k/v to their owners; XLA
        # drops it as dead code since the outputs are unused.
        k, v = jax.tree.map(
            lambda x: collectives.ring_permute(x, axis_name, shift=-1), (k, v)
        )
        return (o, m, l, k, v), None

    (o, m, l, k, v), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)).astype(dtype)


# ----------------------------------------------------------------------------
# Ring attention with Pallas flash block compute (fwd + bwd)
# ----------------------------------------------------------------------------
#
# The plain ring above computes each hop's block attention in XLA f32 ops —
# correct, but the per-hop [Tq, Tk] scores run at the f32 MXU rate and live
# in HBM.  This variant runs the SAME ring schedule with the Pallas flash
# kernel as the per-hop compute (bf16 MXU rate, O(block) VMEM), merging hops
# by their log-sum-exp.  Causal structure exploited statically: hop 0 is
# ALWAYS the diagonal shard (kernel compiled causal), later hops are never
# diagonal (kernel compiled non-causal; whole-block visibility is a traced
# where-mask, since under causal masking a later shard's k/v block is either
# fully visible or fully masked).  The backward runs the flash dq/dkv
# kernels per hop, with dk/dv accumulators rotating in lockstep with their
# k/v blocks so every gradient arrives home after the full circle.


def _merge(o1, lse1, o2, lse2):
    """Merge two normalised attention partials by their lse (f32)."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)
    w2 = jnp.exp(lse2 - lse)
    return o1 * w1 + o2 * w2, lse


def _fold_heads(x):
    B, H, T, D = x.shape
    return x.reshape(B * H, T, D)


def ring_flash_attention(
    q, k, v, *, axis_name: str, causal: bool = False, block_q: int = 1024,
    block_k: int = 1024,
):
    """Ring attention whose per-hop block compute is the Pallas flash kernel
    (inside ``shard_map``; shapes per shard [B, H, T_local, D]).

    Differentiable via a hand-written ring backward (flash dq/dkv kernels
    per hop).  Exact-parity contract with :func:`ring_attention` (tested).
    """
    return _ring_flash(q, k, v, axis_name, causal, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, causal, block_q, block_k):
    o, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k)
    return o


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k):
    from . import flash_attention as fa

    n = collectives.axis_size(axis_name)
    # Shard identity is only consumed by the causal visibility test; tracing
    # it unconditionally leaves a DEAD axis_index in the jaxpr (the
    # custom_vjp boundary blocks DCE), which lowers to an unannotated
    # partition-id the CPU SPMD partitioner rejects outright.
    my = collectives.axis_index(axis_name) if causal else None
    B, H, T, D = q.shape
    dtype = q.dtype
    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    bq = fa._pick_block(T, block_q)
    bk = fa._pick_block(T, block_k)

    # Hop 0: the diagonal shard — statically causal.  All partials emit f32
    # straight from the kernel's accumulator: rounding each hop to bf16
    # before merging would accumulate O(n_hops) quantization error.
    o, lse = fa.fwd_call(
        qf, kf, vf, causal=causal, block_q=bq, block_k=bk, out_dtype=jnp.float32
    )

    def body(carry, i):
        o, lse, kr, vr = carry
        kr, vr = jax.tree.map(
            lambda x: collectives.ring_permute(x, axis_name, shift=-1), (kr, vr)
        )
        src = (my + i) % n if causal else None

        # Never the diagonal for i in 1..n-1 — statically non-causal kernel;
        # under causal masking the whole block is visible iff src < my.
        # lax.cond skips the kernel entirely on masked hops (no wasted
        # compute, and nothing numerically suspect ever materialises).
        def visit(o, lse):
            o_h, lse_h = fa.fwd_call(
                qf, kr, vr, causal=False, block_q=bq, block_k=bk,
                out_dtype=jnp.float32,
            )
            return _merge(o, lse, o_h, lse_h)

        if causal:
            o, lse = lax.cond(src < my, visit, lambda o, lse: (o, lse), o, lse)
        else:
            o, lse = visit(o, lse)
        return (o, lse, kr, vr), None

    if n > 1:
        (o, lse, _, _), _ = lax.scan(body, (o, lse, kf, vf), jnp.arange(1, n))
    return o.astype(dtype).reshape(B, H, T, D), lse


def _ring_flash_fwd_rule(q, k, v, axis_name, causal, block_q, block_k):
    o, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd_rule(axis_name, causal, block_q, block_k, res, do):
    from . import flash_attention as fa

    q, k, v, o, lse = res
    n = collectives.axis_size(axis_name)
    my = collectives.axis_index(axis_name)
    B, H, T, D = q.shape
    qf, kf, vf = _fold_heads(q), _fold_heads(k), _fold_heads(v)
    dof = _fold_heads(do)
    delta = fa.compute_delta(dof, _fold_heads(o))
    bq = fa._pick_block(T, block_q)
    bk = fa._pick_block(T, block_k)

    # Hop 0 (diagonal, statically causal); all partials f32 (see fwd).
    f32 = jnp.float32

    def hop_bwd(kh, vh, *, hop_causal):
        """Per-hop (dq, dk, dv) partials: the fused single-pass kernel when
        the per-shard block counts reach its dispatch regime (long-context
        shards), the split kernels otherwise — same contract either way."""
        if fa._use_fused_bwd(T // bq, kh.shape[1] // bk, T, D):
            return fa.fused_bwd_call(
                qf, kh, vh, dof, lse, delta, causal=hop_causal,
                block_q=bq, block_k=bk, out_dtype=f32,
            )
        dq_h = fa.dq_call(
            qf, kh, vh, dof, lse, delta, causal=hop_causal, block_q=bq,
            block_k=bk, out_dtype=f32,
        )
        dk_h, dv_h = fa.dkv_call(
            qf, kh, vh, dof, lse, delta, causal=hop_causal, block_q=bq,
            block_k=bk, out_dtype=f32,
        )
        return dq_h, dk_h, dv_h

    dq, dk0, dv0 = hop_bwd(kf, vf, hop_causal=causal)

    def body(carry, i):
        dq, kr, vr, dk, dv = carry
        # dk/dv accumulators rotate in LOCKSTEP with their k/v blocks, so
        # after the full circle every block's gradient is back home.
        kr, vr, dk, dv = jax.tree.map(
            lambda x: collectives.ring_permute(x, axis_name, shift=-1),
            (kr, vr, dk, dv),
        )
        src = (my + i) % n

        # lax.cond, NOT a multiply-by-zero mask: on a fully-masked hop the
        # non-causal kernel computes exp(s - lse) where lse covers only
        # VISIBLE keys — a masked score exceeding lse by ~88 overflows f32
        # exp, and 0 * inf would poison the gradients with NaN.  The cond
        # never runs the kernel there (and skips ~half the off-diagonal
        # backward FLOPs under causal masking).
        def visit(dq, dk, dv):
            dq_h, dk_h, dv_h = hop_bwd(kr, vr, hop_causal=False)
            return dq + dq_h, dk + dk_h, dv + dv_h

        if causal:
            dq, dk, dv = lax.cond(
                src < my, visit, lambda dq, dk, dv: (dq, dk, dv), dq, dk, dv
            )
        else:
            dq, dk, dv = visit(dq, dk, dv)
        return (dq, kr, vr, dk, dv), None

    if n > 1:
        (dq, _, _, dk, dv), _ = lax.scan(
            body, (dq, kf, vf, dk0, dv0), jnp.arange(1, n)
        )
        # One final rotation brings the accumulators home (they have moved
        # n-1 hops with their blocks).
        dk, dv = jax.tree.map(
            lambda x: collectives.ring_permute(x, axis_name, shift=-1), (dk, dv)
        )
    else:
        dk, dv = dk0, dv0

    unfold = lambda x, ref: x.astype(ref.dtype).reshape(ref.shape)
    return unfold(dq, q), unfold(dk, k), unfold(dv, v)


_ring_flash.defvjp(_ring_flash_fwd_rule, _ring_flash_bwd_rule)


def sequence_parallel_attention(
    mesh: Mesh,
    q,
    k,
    v,
    *,
    causal: bool = False,
    seq_axis: str = "seq",
    batch_axis="data",
    head_axis: str = "model",
    impl: str = "auto",
):
    """Global-array entry point: [B, H, T, D] inputs with T sharded over
    ``seq_axis`` (and heads over ``head_axis`` when present — ring SP and
    Megatron TP compose).  Internally a ``shard_map`` running the ring.
    Falls back to plain (XLA-partitioned) attention when the mesh has no seq
    axis.

    ``impl``: per-hop block compute — "xla" (the reference ring), "flash"
    (Pallas kernels fwd+bwd), "ulysses" (all-to-all head-resharding CP —
    see :func:`ulysses_attention`), or "auto" (flash ring on TPU, xla
    elsewhere — interpret-mode Pallas inside a scan is prohibitively slow
    on CPU).

    ``batch_axis`` may be a tuple of axes (('data','expert') for MoE
    models whose batches shard over both — models/transformer.data_axes).
    """
    if impl not in ("auto", "xla", "flash", "ulysses"):
        raise ValueError(f"impl must be auto|xla|flash|ulysses, got {impl!r}")
    if mesh.shape.get(seq_axis, 1) == 1:
        return mha(q, k, v, causal=causal)
    if impl == "ulysses":
        return ulysses_attention(
            mesh, q, k, v, causal=causal, seq_axis=seq_axis,
            batch_axis=batch_axis, head_axis=head_axis,
        )
    h_entry = head_axis if mesh.shape.get(head_axis, 1) > 1 else None
    spec = P(batch_axis, h_entry, seq_axis, None)

    if impl == "auto":
        from .flash_attention import flash_viable

        impl = "flash" if flash_viable(q.shape[2] // mesh.shape[seq_axis]) else "xla"
    if impl == "flash":
        fn = functools.partial(
            ring_flash_attention, axis_name=seq_axis, causal=causal
        )
    else:
        fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    mapped = collectives.shard_map(
        fn, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return mapped(q, k, v)


def ulysses_attention(
    mesh: Mesh,
    q,
    k,
    v,
    *,
    causal: bool = False,
    seq_axis: str = "seq",
    batch_axis="data",
    head_axis: str = "model",
):
    """All-to-all sequence/context parallelism (the DeepSpeed-Ulysses
    layout; SURVEY.md section 7 growth path #7 names it next to the ring):
    instead of rotating k/v shards around a ring, ONE ``all_to_all`` per
    tensor re-shards [B, H_loc, T/s, D] -> [B, H_loc/s, T, D] — sequence
    gathered, heads scattered — then attention runs LOCALLY over the full
    sequence (plain causal flag, no cross-hop online-softmax bookkeeping),
    and one ``all_to_all`` brings the output back to the sequence layout.

    Trade vs the ring: 4 all_to_alls moving activation-sized payloads per
    layer and full-T local compute (which puts the per-shard shape squarely
    in the fused flash backward's regime), against the ring's n-1
    latency-chained permutes of k/v; Ulysses needs heads divisible by the
    seq shards, the ring does not.  Same entry contract as
    :func:`sequence_parallel_attention` (composes with Megatron head
    sharding over ``head_axis``).
    """
    s = mesh.shape.get(seq_axis, 1)
    if s == 1:
        return mha(q, k, v, causal=causal)
    H = q.shape[1]
    h_shards = mesh.shape.get(head_axis, 1)
    h_entry = head_axis if h_shards > 1 else None
    if (H // h_shards) % s:
        raise ValueError(
            f"ulysses: {H} heads / {h_shards} '{head_axis}' shards leaves "
            f"{H // h_shards} local heads, not divisible by {seq_axis}={s}; "
            "use the ring (impl='flash'/'xla') for this shape"
        )
    spec = P(batch_axis, h_entry, seq_axis, None)

    from .flash_attention import flash_attention, flash_viable

    T = q.shape[2]
    use_flash = flash_viable(T)  # full T is local after the reshard

    def local(q, k, v):
        # [b, h_loc, T/s, D] -> heads scattered, sequence gathered.
        a2a = functools.partial(
            lax.all_to_all, axis_name=seq_axis, tiled=True
        )
        q, k, v = (a2a(t, split_axis=1, concat_axis=2) for t in (q, k, v))
        if use_flash:
            o = flash_attention(q, k, v, causal=causal)
        else:
            o = mha(q, k, v, causal=causal)
        # Back to the sequence-sharded layout for the rest of the layer.
        return a2a(o, split_axis=2, concat_axis=1)

    return collectives.shard_map(
        local, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )(q, k, v)
