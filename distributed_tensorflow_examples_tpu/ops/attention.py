"""Attention ops: reference MHA + ring attention for sequence/context
parallelism.

No reference analog (SURVEY.md section 5.7: the reference has no attention
model; its longest-sequence workload scales only by TBPTT unroll).  This is
the framework's long-context growth path, first-class per the blueprint:
sequences shard over the mesh ``seq`` axis, and attention runs as a ring —
each shard keeps its queries local while key/value blocks rotate around the
axis via ``ppermute`` (one hop per step, riding ICI neighbor links), with the
online-softmax accumulation of flash attention so no shard ever materialises
the full [T, T] score matrix.

Numerical contract (tested): ring attention over a seq-sharded mesh ==
full-sequence attention on one device, for both causal and full attention.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel import collectives

#: Finite "minus infinity" for masked logits: keeps the online-softmax
#: recurrence NaN-free when a block is fully masked (exp(-1e30 - m) == 0 for
#: any finite m), where a true -inf would produce inf-inf = NaN.
NEG_INF = -1e30


def mha(q, k, v, *, causal: bool = False, q_offset: int = 0, k_offset: int = 0):
    """Reference multi-head attention.  q: [B, H, Tq, D], k/v: [B, H, Tk, D].

    ``q_offset``/``k_offset`` are the global positions of the first row of
    q/k — the pieces ring attention needs for causal masking across shards.
    """
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(kpos > qpos, NEG_INF, s)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def _block(q, k, v, carry, *, scale, causal, q_offset, k_offset):
    """One online-softmax accumulation step (the flash-attention recurrence)
    against a single k/v block.  carry = (o, m, l):
    o [B,H,Tq,D] unnormalised output, m [B,H,Tq,1] running max,
    l [B,H,Tq,1] running sum of exp."""
    o, m, l = carry
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale  # [B,H,Tq,Tk]
    if causal:
        qpos = q_offset + jnp.arange(q.shape[2])[:, None]
        kpos = k_offset + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(kpos > qpos, NEG_INF, s)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    # Valid (unmasked) entries only: a fully-masked block contributes 0.
    p = jnp.exp(s - m_new) * (s > NEG_INF / 2)
    alpha = jnp.exp(m - m_new)
    o = o * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    return o, m_new, l


def ring_attention(q, k, v, *, axis_name: str, causal: bool = False):
    """Sequence-parallel attention inside ``shard_map``: queries stay local,
    k/v blocks rotate ``axis_size`` hops around the ring (permuter.h role —
    SURVEY.md D11 — but emitted as XLA ``ppermute`` on ICI).

    Shapes per shard: q/k/v [B, H, T_local, D]; the global sequence is the
    concatenation over the axis in index order.
    """
    n = collectives.axis_size(axis_name)
    my = collectives.axis_index(axis_name)
    t_local = q.shape[2]
    scale = 1.0 / math.sqrt(q.shape[-1])
    q32, dtype = q.astype(jnp.float32), q.dtype
    o = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)

    def body(carry, i):
        o, m, l, k, v = carry
        src = (my + i) % n
        o, m, l = _block(
            q32,
            k.astype(jnp.float32),
            v.astype(jnp.float32),
            (o, m, l),
            scale=scale,
            causal=causal,
            q_offset=my * t_local,
            k_offset=src * t_local,
        )
        # Receive-from-next rotation (shift=-1): after i hops we hold shard
        # (my + i) % n's k/v; every shard does n identical hops => a clean
        # ICI ring schedule.  The nth hop returns k/v to their owners; XLA
        # drops it as dead code since the outputs are unused.
        k, v = jax.tree.map(
            lambda x: collectives.ring_permute(x, axis_name, shift=-1), (k, v)
        )
        return (o, m, l, k, v), None

    (o, m, l, k, v), _ = lax.scan(body, (o, m, l, k, v), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)).astype(dtype)


def sequence_parallel_attention(
    mesh: Mesh,
    q,
    k,
    v,
    *,
    causal: bool = False,
    seq_axis: str = "seq",
    batch_axis: str = "data",
    head_axis: str = "model",
):
    """Global-array entry point: [B, H, T, D] inputs with T sharded over
    ``seq_axis`` (and heads over ``head_axis`` when present — ring SP and
    Megatron TP compose).  Internally a ``shard_map`` running the ring.
    Falls back to plain (XLA-partitioned) attention when the mesh has no seq
    axis."""
    if mesh.shape.get(seq_axis, 1) == 1:
        return mha(q, k, v, causal=causal)
    h_entry = head_axis if mesh.shape.get(head_axis, 1) > 1 else None
    spec = P(batch_axis, h_entry, seq_axis, None)

    fn = functools.partial(ring_attention, axis_name=seq_axis, causal=causal)
    mapped = collectives.shard_map(
        fn, mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return mapped(q, k, v)
