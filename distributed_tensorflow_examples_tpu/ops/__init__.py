"""Custom ops: Pallas TPU kernels with XLA reference fallbacks.

The reference's "custom native op" path is hand-written C++ kernels compiled
into libtensorflow (SURVEY.md D11/D12).  The TPU-native equivalent is Pallas:
kernels lower through Mosaic to real TPU code, while a pure-XLA reference
implementation of each op serves CPU tests and autodiff checks.
"""

from . import attention  # noqa: F401
from . import flash_attention  # noqa: F401
