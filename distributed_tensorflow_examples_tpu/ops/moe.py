"""Mixture-of-Experts FFN with expert parallelism over the ``expert`` axis.

No reference analog (SURVEY.md §2b strategy table: EP "not needed" for
parity) — provided because a complete TPU framework serves the axis, and
because MoE is where the ``expert`` mesh axis and ``all_to_all`` earn their
keep (the same role D11's ``collective_nccl_all_to_all.h`` plays in the
reference's native layer).

TPU-first formulation — the GShard/Mesh-TF einsum dispatch, not a gather
loop: token->expert routing materialises as STATIC-shaped one-hot dispatch/
combine tensors and three einsums, so XLA sees dense MXU work plus a
layout change it lowers to ``all_to_all`` over the expert axis when the
expert dim is sharded (dynamic shapes would fall off the MXU entirely).
Capacity-bounded: each expert processes at most C tokens per step;
overflow tokens are dropped (contribute zero) exactly as in Switch/GShard.

Components:
- top-k router (k=2 default) with renormalised gates,
- capacity C = ceil(k*N/E * capacity_factor),
- load-balance auxiliary loss (Switch eq. 4): E * sum_e f_e * p_e,
- expert FFN: per-expert GELU MLP, weights stacked [E, ...] and sharded
  ``P('expert', ...)`` so each rank holds only its experts (rules below).
"""

from __future__ import annotations

import dataclasses
import math
import warnings

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models import layers


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    #: Routing-group size (GShard's G): tokens route within fixed-size
    #: groups so the dispatch tensor is [G, g, E, C_g] with C_g ~ k*g/E —
    #: total memory O(N*g*k), NOT the O(N^2*k) of ungrouped [N, E, C]
    #: dispatch (which OOMs at real sequence lengths).
    group_size: int = 1024


def init(rng, dim: int, hidden: int, moe: MoEConfig):
    ks = jax.random.split(rng, 3)
    E = moe.n_experts
    # Per-expert glorot: fan_in/out of ONE expert's matrices.
    w1 = jax.vmap(lambda k: layers.glorot_uniform(k, (dim, hidden)))(
        jax.random.split(ks[0], E)
    )
    w2 = jax.vmap(lambda k: layers.glorot_uniform(k, (hidden, dim)))(
        jax.random.split(ks[1], E)
    )
    return {
        "router": {"kernel": layers.glorot_uniform(ks[2], (dim, E))},
        "w1": w1,
        "b1": jnp.zeros((E, hidden), jnp.float32),
        "w2": w2,
        "b2": jnp.zeros((E, dim), jnp.float32),
    }


def capacity(group_tokens: int, moe: MoEConfig) -> int:
    c = math.ceil(moe.top_k * group_tokens / moe.n_experts * moe.capacity_factor)
    return max(4, c)


def _group(n: int, want: int, shards: int = 1) -> int:
    """Largest divisor of ``n`` that is <= ``want`` (the routing-group size).

    ``shards``: number of mesh shards the flattened token dim arrives
    distributed over (data x expert).  The group count N/g must be a
    multiple of it, so groups never straddle a shard boundary — routing
    then stays shard-local and only the dispatched [E, G, C, D] buffers
    cross the mesh (as all_to_all).  Falls back to plain divisor-of-N when
    no such g exists (e.g. tiny unit-test shapes)."""
    from .common import largest_divisor

    g = min(want, n)
    while g > 1 and not (n % g == 0 and (n // g) % shards == 0):
        g -= 1
    if g > 1 or n % shards == 0:
        return g
    warnings.warn(
        f"moe: no routing-group size <= {want} splits {n} tokens into a "
        f"multiple of {shards} shards; groups will straddle shard "
        "boundaries and the dispatch may lower to all-gather instead of "
        "all_to_all (pad batch*seq to a multiple of data*expert shards)."
    )
    return largest_divisor(n, want)


def apply(p, x, moe: MoEConfig, *, dtype=None, mesh=None):
    """x: [B, T, D] -> (y [B, T, D], aux_loss scalar f32).

    Routing runs in f32 (softmax/top-k numerics); expert matmuls in
    ``dtype`` (bf16 on TPU) like every other dense layer.  Tokens route
    within groups of ``moe.group_size`` (capacity is per group), the GShard
    construction that keeps the dispatch tensors linear in total tokens.

    With ``mesh`` (carrying an ``expert`` axis): tokens arrive sharded over
    ``('data','expert')`` (the caller shards its batch over BOTH axes —
    models/transformer.py ``data_axes``), expert_in/out are pinned to
    ``P('expert','data',...)``, and the group->expert redistribution on each
    side of the expert FFN lowers to a genuine ``all_to_all`` over the
    expert axis (asserted at the HLO level by tests/test_hlo_sharding.py).
    Without a mesh the einsums run locally (unit tests, single chip).
    """
    B, T, D = x.shape
    E, k = moe.n_experts, moe.top_k
    N = B * T
    shards = 1
    if mesh is not None:
        shards = mesh.shape.get("data", 1) * mesh.shape.get("expert", 1)
    g = _group(N, moe.group_size, shards)
    G = N // g
    C = capacity(g, moe)
    tok = x.reshape(G, g, D)
    if mesh is not None and G % shards == 0:
        # Keep the group dim on the token shards across the reshape: groups
        # are whole-shard slices (see _group), so this is a no-move pin.
        tok = jax.lax.with_sharding_constraint(
            tok,
            jax.sharding.NamedSharding(mesh, P(("data", "expert"), None, None)),
        )
    elif mesh is not None and shards > 1:
        warnings.warn(
            f"moe: group count {G} is not a multiple of the {shards} token "
            "shards; skipping the ('data','expert') token pin — the "
            "dispatch may not lower to all_to_all at this shape."
        )

    logits = jnp.einsum("gnd,de->gne", tok.astype(jnp.float32), p["router"]["kernel"])
    probs = jax.nn.softmax(logits, axis=-1)  # [G, g, E]

    # Top-k expert choice per token; gates renormalised over the chosen k.
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # [G, g, k]
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9
    )

    # Position of each (token, choice) within its expert's per-group
    # capacity buffer: rank by arrival order (cumsum over the one-hot),
    # GShard's position-in-group; positions >= C are dropped.
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [G, g, k, E]
    # Priority: every token's FIRST choice ranks before any second choice
    # (GShard's ordering) — lay choices out [k, g] inside each group.
    flat = onehot.transpose(0, 2, 1, 3).reshape(G, k * g, E)
    pos_flat = jnp.cumsum(flat, axis=1) - flat
    pos_in_expert = pos_flat.reshape(G, k, g, E).transpose(0, 2, 1, 3)
    pos = jnp.sum(pos_in_expert * onehot, axis=-1)  # [G, g, k]
    keep = pos < C
    gate_vals = gate_vals * keep

    # combine[g, n, e, c]: gate weight of token n at slot c of expert e.
    slot = jax.nn.one_hot(
        jnp.where(keep, pos, C).astype(jnp.int32), C, dtype=jnp.float32
    )  # [G, g, k, C]
    combine = jnp.einsum("gnke,gnkc->gnec", onehot, slot * gate_vals[..., None])
    dispatch = jnp.einsum("gnke,gnkc->gnec", onehot, slot * keep[..., None])

    cd = jnp.float32 if dtype is None else dtype
    expert_in = jnp.einsum(
        "gnec,gnd->egcd", dispatch.astype(cd), tok.astype(cd)
    )  # [E, G, C, D] — expert x group: the all_to_all boundary (tokens
    # leave their home ('data','expert') shard for their expert's rank)
    expert_in = _constrain_expert(expert_in, mesh)
    h = jnp.einsum("egcd,edh->egch", expert_in, p["w1"].astype(cd))
    h = jax.nn.gelu(h + p["b1"].astype(cd)[:, None, None, :])
    out = jnp.einsum("egch,ehd->egcd", h, p["w2"].astype(cd))
    out = out + p["b2"].astype(cd)[:, None, None, :]
    out = _constrain_expert(out, mesh)
    y = jnp.einsum("gnec,egcd->gnd", combine.astype(cd), out)

    # Switch load-balance loss: E * sum_e (tokens routed to e / N) * mean_e
    # router prob.  Uses the FIRST choice's routing fraction (Switch eq. 4),
    # computed over ALL tokens (groups together).
    frac = jnp.mean(onehot[:, :, 0, :], axis=(0, 1))  # [E]
    mean_prob = jnp.mean(probs, axis=(0, 1))  # [E]
    aux = E * jnp.sum(frac * mean_prob)

    return y.reshape(B, T, D).astype(x.dtype), aux


def _constrain_expert(t, mesh):
    """Pin [E, G, C, D] to ``P('expert','data',...)`` between the dispatch/
    combine einsums and the expert FFN: E on the expert ranks (each holds its
    experts' capacity buffers), G back on the data axis.  Because the input
    tokens are sharded over ``('data','expert')`` on G's flattened source,
    this reshard is exactly the GShard all_to_all.

    Explicit-mesh (round-3 fix): the previous bare-``PartitionSpec`` +
    ``except Exception`` form silently no-op'd under the jitted train step
    (which establishes no global mesh context) — per ADVICE.md, failures
    must propagate.  Skips only the two legitimate cases: no mesh given
    (unit tests / single chip) or a mesh without an ``expert`` axis; G is
    left unconstrained when it doesn't divide the data axis (a 1-group
    input must not be forced onto 'data')."""
    if mesh is None or mesh.shape.get("expert", 1) <= 1:
        return t
    g_entry = "data" if t.shape[1] % mesh.shape.get("data", 1) == 0 else None
    if g_entry is None and mesh.shape.get("data", 1) > 1:
        warnings.warn(
            f"moe: group dim {t.shape[1]} does not divide the data axis "
            f"({mesh.shape.get('data', 1)}); dropping the group entry from "
            "the expert buffers' sharding — capacity buffers replicate over "
            "'data' at this shape."
        )
    return jax.lax.with_sharding_constraint(
        t, jax.sharding.NamedSharding(mesh, P("expert", g_entry, None, None))
    )


#: Rule fragment for a block containing one MoE layer under prefix `moe/`.
SHARDING_RULES: tuple = (
    (r".*moe/router/kernel", P(None, None)),
    (r".*moe/w1", P("expert", None, "model")),
    (r".*moe/b1", P("expert", "model")),
    (r".*moe/w2", P("expert", "model", None)),
    (r".*moe/b2", P("expert", None)),
)
