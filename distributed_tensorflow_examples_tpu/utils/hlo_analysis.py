"""Compiled-HLO collective analysis (SURVEY.md section 6 scaling evidence).

The reference's communication layer is observable: you can read
``ring_reducer.h`` and count NCCL calls.  The TPU-native equivalent is
XLA-emitted, so the observable artifact is the compiled HLO: this module
parses ``compiled.as_text()`` and reports every cross-device collective
(kind, result shape, bytes) so tests can assert sharding properties ("no
full-table all-gather in the word2vec step") and the scaling analysis can
model per-step communication volume vs device count.
"""

from __future__ import annotations

import dataclasses
import re
from collections import defaultdict

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "pred": 1,
}

#: Cross-device collectives XLA emits for SPMD programs.
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "collective-broadcast",
)

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


@dataclasses.dataclass
class Collective:
    kind: str
    shapes: list[str]  # e.g. ["f32[1024,128]"]
    bytes: int  # total result payload
    line: str  # the HLO line (trimmed), for debugging/asserts
    groups_attr: str = ""  # replica_groups/source_target_pairs attr (FULL,
    # extracted before the line is trimmed; "" = attr absent, which for
    # SPMD collectives means ONE global group)

    @property
    def groups(self) -> list[list[int]] | None:
        """Replica groups, parsed from the line: explicit ``{{0,1},{2,3}}``
        form or the iota form ``[g,k]<=[N]`` / ``[g,k]<=[a,b]T(1,0)``.
        None when absent or unparseable (callers must treat None as
        'unknown', not 'global').  collective-permute carries
        ``source_target_pairs`` instead; each pair is returned as a
        2-element group."""
        src = self.groups_attr or self.line
        m = re.search(r"source_target_pairs=\{(\{[\d, ]*\}(?:\s*,\s*\{[\d, ]*\})*)\}", src)
        if m:
            return [
                [int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([\d, ]*)\}", m.group(1))
            ]
        m = re.search(r"replica_groups=\{(\{[\d, ]*\}(?:\s*,\s*\{[\d, ]*\})*)\}", src)
        if m:
            return [
                [int(x) for x in g.split(",") if x.strip()]
                for g in re.findall(r"\{([\d, ]*)\}", m.group(1))
            ]
        m = re.search(
            r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?",
            src,
        )
        if m:
            g, k = int(m.group(1)), int(m.group(2))
            dims = [int(x) for x in m.group(3).split(",")]
            import numpy as _np

            arr = _np.arange(_np.prod(dims)).reshape(dims)
            if m.group(4):
                arr = arr.transpose([int(x) for x in m.group(4).split(",")])
            flat = arr.reshape(-1)
            if flat.size != g * k:
                return None
            return [flat[i * k : (i + 1) * k].tolist() for i in range(g)]
        return None


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def parse_collectives(hlo_text: str) -> list[Collective]:
    """Every collective instruction in an HLO module dump, with result bytes.

    Handles variadic results (``(f32[..], f32[..]) all-reduce(...)``) and
    ``X-start``/``X-done`` async pairs (the ``-start`` carries the shape;
    ``-done`` lines are skipped to avoid double counting).
    """
    op_re = re.compile(
        r"=\s*(.+?)\s+(" + "|".join(COLLECTIVE_OPS) + r")(-start|-done)?\("
    )
    out = []
    for raw in hlo_text.splitlines():
        line = raw.strip()
        m = op_re.search(line)
        if not m:
            continue
        result, op, suffix = m.groups()
        if suffix == "-done":
            continue  # async pair: the -start line carries the payload shape
        shapes = _SHAPE_RE.findall(result)
        if not shapes:
            continue
        sizes = [_shape_bytes(dt, dims) for dt, dims in shapes]
        if suffix == "-start":
            # Async form returns (operands..., results..., context...).  For
            # all-reduce/collective-permute the operand and result halves
            # mirror each other, so half the tuple total is the payload
            # (context scalars are ~0 bytes); max() would count only the
            # largest tensor of a variadic fused collective and undercount
            # multi-tensor all-reduces badly.  For all-gather/reduce-scatter
            # the result is N x (or 1/N of) the operand, so the halves do
            # NOT mirror: the transfer-relevant payload is the LARGER side
            # (gathered result / pre-scatter operand), whose ring transfer
            # moves (N-1)/N of those bytes.
            if op in ("all-gather", "reduce-scatter") and len(sizes) > 1:
                # (operands..., results...): each result pairs with one
                # operand and the larger of each pair is transfer-relevant;
                # with k pairs that is exactly the k largest tuple entries.
                k = max(1, len(sizes) // 2)
                total = sum(sorted(sizes, reverse=True)[:k])
            else:
                total = sum(sizes) // 2 if len(sizes) > 1 else sizes[0]
        else:
            total = sum(sizes)  # sync variadic tuple = genuinely N payloads
        ga = re.search(
            r"(?:replica_groups|source_target_pairs)=(?:\{[^=]*?\}\}|\{\}|"
            r"\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)",
            line,
        )
        out.append(
            Collective(
                kind=op,
                shapes=[f"{dt}[{dims}]" for dt, dims in shapes],
                bytes=total,
                line=line[:240],
                groups_attr=ga.group(0) if ga else "",
            )
        )
    return out


def summarize(collectives: list[Collective]) -> dict:
    """{kind: {"count": n, "bytes": total}} + grand totals."""
    agg: dict = defaultdict(lambda: {"count": 0, "bytes": 0})
    for c in collectives:
        agg[c.kind]["count"] += 1
        agg[c.kind]["bytes"] += c.bytes
    agg = dict(agg)
    agg["total"] = {
        "count": sum(v["count"] for v in agg.values()),
        "bytes": sum(v["bytes"] for v in agg.values()),
    }
    return agg


def max_collective_bytes(hlo_text: str, kind: str | None = None) -> int:
    """Largest single collective payload (optionally of one kind)."""
    cs = parse_collectives(hlo_text)
    if kind is not None:
        cs = [c for c in cs if c.kind == kind]
    return max((c.bytes for c in cs), default=0)


def max_tensor_bytes(hlo_text: str, kind: str | None = None) -> int:
    """Largest single TENSOR moved by any collective (XLA fuses many grads
    into one variadic all-reduce, so per-op bytes overstate the largest
    logical payload; per-tensor is the right unit for 'did a whole table
    cross the mesh' assertions)."""
    best = 0
    for c in parse_collectives(hlo_text):
        if kind is not None and c.kind != kind:
            continue
        for s in c.shapes:
            m = _SHAPE_RE.match(s)
            if m:
                best = max(best, _shape_bytes(m.group(1), m.group(2)))
    return best


def compiled_step_hlo(step_fn, *example_args) -> str:
    """Lower+compile a jitted step and return its optimized HLO text."""
    return step_fn.lower(*example_args).compile().as_text()
