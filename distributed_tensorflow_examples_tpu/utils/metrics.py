"""Metrics writer: the tf.summary event-file role (SURVEY.md T4, section 5.5).

Primary sink is JSONL (``<log_dir>/metrics.jsonl``) — trivially parseable by
the bench harness and tests.  If TensorBoard's pure-python writer is importable
(it ships with the baked TF install), scalars are mirrored into real event
files so standard tooling works; its absence degrades silently.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np


def shard_scalars(kind: str, ms_per_shard) -> dict[str, float]:
    """Per-shard PS transport wall times as TensorBoard scalar tags —
    ``ps/<kind>_ms_shard<i>`` (r9 satellite).  One naming convention for
    every emitter, so dashboards can glob ``ps/pull_ms_shard*`` and a hot
    or slow shard server shows up as one series running away from its
    siblings."""
    return {
        f"ps/{kind}_ms_shard{i}": float(ms)
        for i, ms in enumerate(ms_per_shard)
    }


class LatencyRecorder:
    """Ring buffer of recent op wall times -> latency/throughput scalars
    (r10 satellite, the serving plane's ``serve/latency_*`` family).

    ``record(seconds)`` is O(1) and thread-safe (many connection handlers
    record concurrently); :meth:`percentile_scalars` reduces the retained
    window into ``<prefix>/latency_p50_ms`` / ``p90`` / ``p99`` plus
    ``<prefix>/qps`` (events per second across the window's wall-time
    span).  Same naming convention as :func:`shard_scalars` — one emitter,
    one tag family, so dashboards glob ``serve/latency_*`` the way they
    glob ``ps/pull_ms_shard*``."""

    def __init__(self, capacity: int = 2048):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self._cap = int(capacity)
        self._dur = np.zeros(self._cap, np.float64)
        self._at = np.zeros(self._cap, np.float64)
        self._n = 0  # total ever recorded; ring index is _n % _cap
        self._lock = threading.Lock()

    def record(self, seconds: float, *, at: float | None = None) -> None:
        """Record one op's wall time.  ``at`` (monotonic seconds) defaults
        to now — tests pass explicit stamps for deterministic qps."""
        with self._lock:
            i = self._n % self._cap
            self._dur[i] = seconds
            self._at[i] = time.monotonic() if at is None else at
            self._n += 1

    def __len__(self) -> int:
        return min(self._n, self._cap)

    @property
    def total(self) -> int:
        """Ops ever recorded (the ring only bounds the percentile window)."""
        return self._n

    def percentile_scalars(self, prefix: str) -> dict[str, float]:
        """The retained window as scalar tags; empty dict when nothing has
        been recorded yet (emitters skip the write instead of publishing
        zeros that read as impossibly fast ops)."""
        with self._lock:
            m = min(self._n, self._cap)
            if m == 0:
                return {}
            dur = self._dur[:m].copy()
            at = self._at[:m].copy()
        out = {
            f"{prefix}/latency_p{p}_ms": float(np.percentile(dur, p) * 1e3)
            for p in (50, 90, 99)
        }
        span = float(at.max() - at.min())
        out[f"{prefix}/qps"] = (m - 1) / span if m >= 2 and span > 0 else 0.0
        return out


class MetricsWriter:
    def __init__(self, log_dir: str | None, *, tensorboard: bool = True):
        self.log_dir = log_dir
        self._f = None
        self._tb = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._f = open(os.path.join(log_dir, "metrics.jsonl"), "a", buffering=1)
            if tensorboard:
                try:  # optional dependency — degrade to JSONL-only
                    from tensorboard.summary.writer.event_file_writer import (
                        EventFileWriter,
                    )
                    from tensorboard.compat.proto.summary_pb2 import Summary
                    from tensorboard.compat.proto.event_pb2 import Event

                    self._tb = EventFileWriter(log_dir)
                    self._Summary, self._Event = Summary, Event
                except Exception:
                    self._tb = None

    def scalars(self, step: int, values: dict[str, float]) -> None:
        if self._f is not None:
            self._f.write(
                json.dumps({"step": step, "time": time.time(), **values}) + "\n"
            )
        if self._tb is not None:
            summ = self._Summary(
                value=[
                    self._Summary.Value(tag=k, simple_value=float(v))
                    for k, v in values.items()
                ]
            )
            self._tb.add_event(
                self._Event(step=step, wall_time=time.time(), summary=summ)
            )

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        """Flush + close both sinks.  Idempotent: teardown paths (context
        exit, ``Experiment.finish``, test fixtures) may all call it."""
        f, self._f = self._f, None
        tb, self._tb = self._tb, None
        if f is not None:
            f.flush()
            f.close()
        if tb is not None:
            tb.flush()
            tb.close()

    # Context manager: ``with MetricsWriter(d) as w: ...`` guarantees the
    # TensorBoard event file is flushed — the JSONL sink is line-buffered,
    # but TB events buffer in the writer thread and are LOST on an exit
    # that skips close() (the abrupt-exit gap this closes).
    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
