"""Utilities: flags/CLI, metrics writers, logging setup (SURVEY.md T4/T5)."""

from . import flags  # noqa: F401
from .metrics import MetricsWriter  # noqa: F401
