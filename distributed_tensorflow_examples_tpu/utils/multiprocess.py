"""Multi-process test harness: the ``MultiProcessRunner`` analog
(SURVEY.md section 4: ``TF/python/distribute/multi_process_runner.py:107``).

Forks one real OS process per cluster task, injects cluster identity via
``TF_CONFIG`` (exercising ``parallel.dist``'s resolver exactly as a reference
launcher would), captures per-task logs, and supports killing a task mid-run
— the fault-injection primitive the reference's harness provides for testing
recovery behavior.

Workers are plain Python scripts (source string or file).  The harness runs
them on the multi-process CPU backend (gloo collectives), giving each process
one CPU device — a real 2+-process cluster without TPU hardware.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_WORKER_PRELUDE = """\
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, {repo_root!r})
from distributed_tensorflow_examples_tpu.parallel import dist
_cluster = dist.initialize()
"""


class MultiProcessRunner:
    """Launch ``num_processes`` copies of ``worker_src`` as a TF_CONFIG
    cluster; each copy runs after a ``dist.initialize()`` prelude (so the
    script body sees a live multi-process JAX runtime).

    Usage::

        r = MultiProcessRunner(2, "print(jax.process_count())")
        results = r.run()          # or: r.start(); ...; r.join()
    """

    def __init__(
        self,
        num_processes: int,
        worker_src: str,
        *,
        env: dict[str, str] | None = None,
        timeout: float = 120.0,
        prelude: bool = True,
        pin_cpu: bool = True,
        fault_plan: str | None = None,
    ):
        """``prelude=False`` skips the ``dist.initialize()`` header: the task
        script manages (or delegates) cluster bootstrap itself — e.g. a
        supervisor task whose *child* joins the coordination service.

        ``fault_plan`` sets ``DTX_FAULT_PLAN`` for every task (see
        ``utils.faults``); each task additionally gets a default fault role
        ``task<i>`` via ``DTX_FAULT_ROLE`` (overridable through ``env``),
        so a plan can target one task of the cluster.  The harness's own
        ``kill_task`` remains the out-of-band SIGKILL fault.

        ``pin_cpu`` (default): every task pins the CPU platform via
        ``jax.config`` before the task body runs — this runner IS the fake
        localhost cluster (SURVEY.md section 4), and under the axon TPU
        tunnel the JAX_PLATFORMS env var alone is overridden by the
        plugin's registration hook (tasks would serialize, or hang, on the
        single real chip).  Pass ``pin_cpu=False`` for a task that must
        see real accelerators."""
        self.n = num_processes
        self.timeout = timeout
        self.port = _free_port()
        self._dir = tempfile.mkdtemp(prefix="dtx_mp_")
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        self._pin_cpu = pin_cpu
        pin = (
            'import jax\njax.config.update("jax_platforms", "cpu")\n'
            if pin_cpu
            else ""
        )
        header = _WORKER_PRELUDE.format(repo_root=repo_root) if prelude else (
            pin + f"import sys\nsys.path.insert(0, {repo_root!r})\n"
        )
        script = header + worker_src
        self.script_path = os.path.join(self._dir, "worker.py")
        with open(self.script_path, "w") as f:
            f.write(script)
        self.extra_env = dict(env or {})
        if fault_plan is not None:
            self.extra_env.setdefault("DTX_FAULT_PLAN", fault_plan)
        self.procs: list[subprocess.Popen] = []
        self.log_paths: list[str] = []
        self._log_files: list = []

    def _tf_config(self, index: int) -> str:
        # Every entry carries the coordinator's port: only workers[0] (the
        # coordinator) binds it, the rest just dial it.
        return json.dumps(
            {
                "cluster": {"worker": [f"localhost:{self.port}"] * self.n},
                "task": {"type": "worker", "index": index},
            }
        )

    def start(self) -> None:
        for i in range(self.n):
            env = dict(os.environ)
            env.pop("XLA_FLAGS", None)  # no virtual-device leakage from pytest
            env["JAX_PLATFORMS"] = "cpu"
            if self._pin_cpu:
                # Belt and braces with the in-script jax.config pin: without
                # this var the axon TPU plugin never registers at all, so a
                # fake-cluster task cannot even touch the tunnel.
                env.pop("PALLAS_AXON_POOL_IPS", None)
            env["TF_CONFIG"] = self._tf_config(i)
            env["DTX_FAULT_ROLE"] = f"task{i}"
            env.update(self.extra_env)
            log_path = os.path.join(self._dir, f"task_{i}.log")
            self.log_paths.append(log_path)
            logf = open(log_path, "w")
            self._log_files.append(logf)
            self.procs.append(
                subprocess.Popen(
                    [sys.executable, self.script_path, str(i)],
                    env=env,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                )
            )

    def kill_task(self, index: int, sig: int = signal.SIGKILL) -> None:
        """Fault injection: kill one task (the reference harness's
        ``terminate`` used to test preemption/recovery)."""
        self.procs[index].send_signal(sig)

    def join(self, timeout: float | None = None) -> list[int]:
        """Wait for all tasks; returns per-task return codes (negative =
        killed by signal).  Tasks still running at timeout are killed and
        reported as -9."""
        deadline = time.monotonic() + (timeout or self.timeout)
        codes: list[int | None] = [None] * self.n
        while time.monotonic() < deadline and any(c is None for c in codes):
            for i, p in enumerate(self.procs):
                if codes[i] is None:
                    codes[i] = p.poll()
            time.sleep(0.05)
        for i, p in enumerate(self.procs):
            if codes[i] is None:
                p.kill()
                p.wait()
                codes[i] = -9
        for f in self._log_files:
            try:
                f.close()
            except OSError:
                pass
        self._log_files.clear()
        return [int(c) for c in codes]

    def cleanup(self) -> None:
        """Remove the temp worker-script/log directory (call after a
        successful run; kept on failure for debugging)."""
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)

    def output(self, index: int) -> str:
        with open(self.log_paths[index]) as f:
            return f.read()

    def run(self) -> list[str]:
        """start + join; raises if any task failed; returns per-task logs."""
        self.start()
        codes = self.join()
        if any(c != 0 for c in codes):
            logs = "\n".join(
                f"--- task {i} (exit {codes[i]}) ---\n{self.output(i)}"
                for i in range(self.n)
            )
            raise RuntimeError(f"multi-process run failed: {codes}\n{logs}")
        outs = [self.output(i) for i in range(self.n)]
        self.cleanup()
        return outs
