"""Flag/CLI layer: absl-flags based, preserving the reference CLIs.

Contract (SURVEY.md section 5.6, BASELINE.json:5): every example keeps its
existing CLI.  The reference scripts take TF-1 cluster flags
(``--ps_hosts/--worker_hosts/--job_name/--task_index``); on TPU the cluster is
a mesh, so those flags are *accepted and mapped*:

- ``--ps_hosts``/``--worker_hosts``/``--job_name``/``--task_index`` are
  parsed, logged, and translated: the worker count informs a requested data-
  parallel size when ``--mesh`` is unset; PS hosts map to nothing (the PS role
  is absorbed by mesh-sharded variables) and a notice explains that.
- New-style control: ``--mesh "data=8,model=2"``, ``--coordinator`` etc.
"""

from __future__ import annotations

import logging

from absl import flags

log = logging.getLogger("dtx.flags")

FLAGS = flags.FLAGS


def _define(kind, name, default, help_str):
    """Define unless an identical-named flag exists (absl.logging already owns
    --log_dir; the reference CLI reuses that name, so we adopt it)."""
    if name in flags.FLAGS:
        return
    getattr(flags, f"DEFINE_{kind}")(name, default, help_str)


def define_training_flags(default_batch_size: int = 128, default_steps: int = 1000):
    """The shared surface every example exposes (ref flag set, SURVEY.md L5).
    Idempotent (``_define``) so bench drivers/tests may import several example
    modules into one process."""
    _define("integer", "batch_size", default_batch_size, "GLOBAL batch size.")
    _define("integer", "train_steps", default_steps, "Stop after this many steps.")
    _define("string", "data_dir", None, "Dataset directory (synthetic if absent).")
    _define("string", "log_dir", None, "Checkpoints + metrics directory.")
    _define("float", "learning_rate", 0.01, "Base learning rate.")
    _define(
        "integer",
        "warmup_steps",
        0,
        "Linear learning-rate warmup from 0 to --learning_rate over this "
        "many optimizer updates (0 = none).  Training-quality knob for "
        "workloads whose early gradients are outsized relative to the "
        "init scale — the async cifar10 path defaults it on (see the "
        "example) so stale first applies cannot collapse the relu stack.",
    )
    _define("integer", "seed", 0, "Global RNG seed (determinism knob).")
    _define(
        "integer", "log_every_steps", 100, "Metric logging cadence (LoggingTensorHook analog)."
    )
    _define(
        "integer", "checkpoint_every_steps", 1000, "CheckpointSaverHook save cadence."
    )
    _define(
        "integer", "unroll", 1, "Steps fused per dispatch (lax.scan multi-step trains)."
    )
    _define(
        "integer",
        "grad_accum",
        1,
        "Gradient-accumulation microbatches per step: activation memory of "
        "batch/k at full-batch numerics (one optimizer update).",
    )
    _define(
        "string",
        "mesh",
        "",
        'Mesh spec, e.g. "data=8,model=2"; empty = all devices on the data axis.',
    )
    _define("bool", "profile", False, "Capture a jax.profiler trace window.")
    _define(
        "string",
        "obs_events_dir",
        "",
        "Observability (r13 dtxobs): directory where each cluster task "
        "dumps its structured-event flight recorder (one "
        "flight-<role>-<pid>.jsonl per process) on fatal conditions — "
        "replication divergence, reconnect-budget exhaustion, injected "
        "deaths.  Exported to child tasks via DTX_OBS_EVENTS_DIR.  Empty "
        "= on-fatal dumps are skipped (live scraping via the STATS ops / "
        "tools/dtxtop.py works regardless).",
    )
    _define(
        "string",
        "platform",
        "",
        'Force the JAX platform (e.g. "cpu") — needed for CPU fake-cluster '
        "runs on hosts whose TPU plugin overrides the JAX_PLATFORMS env var.",
    )
    _define(
        "bool",
        "zero_opt",
        False,
        "ZeRO-1 optimizer-state sharding: shard replicated optimizer slots "
        "over the data axis (reduce-scatter grads, sharded update, "
        "all-gather params — identical numerics, 1/dp the optimizer HBM).",
    )
    _define(
        "bool",
        "watchdog",
        True,
        "Multi-process peer-heartbeat watchdog: exit fast (code 83) when a "
        "peer dies instead of hanging in the next collective, so a "
        "supervisor can restart the job (crash-restart recovery).",
    )
    _define(
        "float",
        "watchdog_grace_secs",
        10.0,
        "Heartbeat staleness after which a peer is declared dead.",
    )
    _define(
        "bool",
        "deterministic",
        False,
        "Run-to-run determinism (enable_op_determinism analog): partitionable "
        "threefry + highest matmul precision.",
    )


def define_legacy_cluster_flags():
    """TF-1 PS/worker cluster flags: accepted for CLI compatibility, mapped to
    mesh topology (SURVEY.md D1/D9 -> mesh)."""
    _define("string", "ps_hosts", "", "(legacy) comma-separated PS host:port list.")
    _define(
        "string", "worker_hosts", "", "(legacy) comma-separated worker host:port list."
    )
    _define("string", "job_name", "", '(legacy) "ps" or "worker".')
    _define("integer", "task_index", 0, "(legacy) task index within the job.")
    _define(
        "bool", "sync_replicas", True, "(legacy) SyncReplicasOptimizer on/off -> sync/async DP."
    )
    _define(
        "bool",
        "ps_emulation",
        False,
        "Run the PS-emulation trainer even in sync mode: token-gated "
        "SyncReplicasOptimizer semantics (accumulate/drop-stale/chief-apply/"
        "token-dequeue) via the native accumulator service (D5).",
    )
    _define(
        "integer",
        "ps_tasks",
        -1,
        "Cross-process PS launch: number of dedicated --job_name=ps "
        "processes in the cluster (-1 = one per --ps_hosts entry, the "
        "reference convention; 0 = no PS task, the chief hosts the state "
        "service in-process).",
    )
    _define(
        "bool",
        "ps_listen_all",
        False,
        "Bind the (unauthenticated) PS state service on ALL interfaces so "
        "workers on other hosts can reach it.  Off = loopback only.  "
        "Required whenever the task's --ps_hosts entry is not a literal "
        "loopback address — network exposure must be an explicit operator "
        "decision, never inferred from hostname spelling (ADVICE r4).",
    )
    _define(
        "integer",
        "ps_shards",
        -1,
        "Sharded parameter store (r9): partition the flat param/gradient "
        "vector over this many PS servers (contiguous ShardLayout slices; "
        "pulls/pushes scatter/gather in parallel, one connection per "
        "shard).  -1 = one shard per --ps_hosts entry (the reference's "
        "replica_device_setter convention); must not exceed the host "
        "count.  1 = the single-server r7 wire, byte-identical.",
    )
    _define(
        "integer",
        "ps_replicas",
        1,
        "PS shard replication (r12): servers holding EACH shard.  2 gives "
        "every shard a primary/backup pair — --ps_hosts then lists "
        "shards*2 entries, the first half primaries, the second half "
        "backups (task i serves shard i%%shards, replica i//shards).  "
        "Primaries forward state-mutating ops to their backup; a client "
        "whose primary dies (or restarts empty) fails over to the backup "
        "with ZERO chief involvement (state-token checked), and a "
        "restarted replica catches up from the survivor via REPL_SYNC "
        "before serving.  1 = the unreplicated pre-r12 wire.",
    )
    _define(
        "integer",
        "ps_layout_version",
        0,
        "PS shard-layout EPOCH (r12): carried in the HELLO shard-identity "
        "word by every server and client of the topology, so a client "
        "from a different epoch (e.g. a stale task surviving a reshard) "
        "fails its dial loudly naming both versions instead of silently "
        "scattering onto the wrong partition.  0 = unversioned.",
    )
    _define(
        "string",
        "ps_reshard_to",
        "",
        "Live PS resharding (r15): makes a --job_name=ps task a JOINER of "
        "a layout-epoch transition.  Format 'V:host:port,host:port,...' — "
        "V is the NEW epoch (> --ps_layout_version) and the list is the "
        "new topology (this task serves entry --task_index).  The joiner "
        "assembles its slice of the flat parameter vector from the OLD "
        "topology (--ps_hosts/--ps_shards/--ps_layout_version) over "
        "slice-ranged REPL_SYNC, announces the transition as the "
        "coordinator's pending record, and heartbeats a 'ps'-kind lease; "
        "the running chief verifies every joiner, republishes current "
        "params, commits the epoch, every client swaps (in-flight pushes "
        "stay at-most-once via epoch-scoped dedup tags), and the old "
        "tasks drain and exit 0.  Empty = a normal (non-joiner) PS task.  "
        "See RUNBOOK 'Live resharding'.",
    )
    _define(
        "integer",
        "ps_restarts",
        3,
        "Cross-process PS launch: run the --job_name=ps task under "
        "utils.supervisor.supervise() with this restart budget, so a PS "
        "crash is healed by PS restart + client reconnect (partial "
        "recovery) instead of the whole-job crash-restart path.  0 "
        "disables supervision (a PS crash then fails the job once the "
        "clients' reconnect budget runs out).",
    )
    _define(
        "string",
        "ps_wire_dtype",
        "f32",
        "Cross-process PS wire encoding: f32 (exact) or bf16 (half the "
        "param/grad bytes; PS stores f32 and converts at the socket "
        "boundary — a bandwidth knob for real networks, negotiated at "
        "connect so mismatched peers fail loudly).  See RUNBOOK 'PS "
        "transport tuning' for when bf16 is accuracy-safe.",
    )
    _define(
        "bool",
        "ps_prefetch",
        True,
        "Async cross-process workers: double-buffer param pulls on a "
        "dedicated background connection so the next step's pull overlaps "
        "the current step's gradient compute (adds at most one step of "
        "parameter staleness; sync mode never prefetches).",
    )
    _define(
        "string",
        "data_service_hosts",
        "",
        "Disaggregated data service: host:port list where --job_name="
        "data_service tasks listen (entry [task_index] is this task's bind "
        "address).  Training workers reach the service via "
        "--data_dir=dsvc://host:port; the task serves the shard files under "
        "its own --data_dir.  Exposure rules follow --ps_listen_all; the "
        "task restarts under --ps_restarts like the PS task.",
    )
    _define(
        "string",
        "serve_hosts",
        "",
        "Online inference plane (r10): host:port list where --job_name="
        "serve model replicas listen (entry [task_index] is this task's "
        "bind address).  Each replica hot-tracks the (sharded) parameter "
        "store at --ps_hosts with versioned pulls and serves micro-batched "
        "predictions under the msrv service tag; clients load-balance "
        "round-robin over the full list (serve.ServePool).  Exposure rules "
        "follow --ps_listen_all; the task restarts under --ps_restarts "
        "like the PS and data-service tasks.",
    )
    _define(
        "integer",
        "serve_max_batch",
        32,
        "Serving replicas: max rows coalesced into one jitted apply "
        "(the dynamic micro-batcher's row budget).",
    )
    _define(
        "float",
        "serve_max_wait_ms",
        5.0,
        "Serving replicas: how long a non-full micro-batch waits for more "
        "requests after its first one arrived — the latency spent buying "
        "coalescing.",
    )
    _define(
        "integer",
        "serve_queue_depth",
        128,
        "Serving replicas: max in-system predict requests before the "
        "replica answers an explicit OVERLOAD status (admission control; "
        "resilient clients rotate/back off instead of piling on).",
    )
    _define(
        "float",
        "serve_queue_deadline_ms",
        0.0,
        "Serving replicas: queue-deadline budget (r18 admission control) — "
        "a predict that waited in the replica's dispatch queue past this "
        "budget is shed with a typed RETRY_LATER answer before a worker "
        "touches it (the caller has abandoned or is about to abandon it). "
        "0 = no server-side policy; only deadlines the CLIENTS stamp on "
        "their frames apply.",
    )
    _define(
        "float",
        "serve_refresh_ms",
        50.0,
        "Serving replicas: parameter-store poll cadence.  Each poll is one "
        "O(header) round trip per shard while the published step is "
        "unchanged (PSTORE_GET_IF_NEWER), so tight cadences stay cheap.",
    )
    _define(
        "string",
        "registry_dir",
        "",
        "Model registry root (r19, serve/registry.py): a directory of "
        "immutable (model_name, version) flat-param snapshots with "
        "fsync'd atomic manifests and lease-style pins.  Training CLIs "
        "PUBLISH their final params here as a new version; a "
        "--job_name=serve replica given --serve_model_version PINS one "
        "version from here instead of hot-tracking the PS (registry GC "
        "never deletes a version a live replica has pinned).  Empty = no "
        "registry (the pre-r19 hot-tracking-only serve plane).",
    )
    _define(
        "integer",
        "serve_model_version",
        0,
        "Serving replicas (r19): pin this registry version from "
        "--registry_dir and serve it IMMUTABLY — the version stamps the "
        "msrv HELLO word, every predict/decode response and STATS, so "
        "pools route and account per version (canary vs stable) and "
        "rolling deploys flip a live pool with zero failed requests.  0 "
        "= hot-track the live training run off the PS (the r10 "
        "behavior).",
    )
    _define(
        "bool",
        "membership_leases",
        True,
        "Elastic membership (r14): async workers and serve replicas "
        "heartbeat a lease on the coordinator PS shard, so the chief, the "
        "data service and tools/dtxtop.py learn the LIVE member set from "
        "the registry instead of static --worker_hosts — a worker can "
        "join or leave mid-run with no restart of anything else, and an "
        "expired lease reassigns the member's in-flight splits "
        "immediately.  Degrades loudly to the static posture against a "
        "pre-r14 PS.  Off = no lease traffic (the pre-r14 wire).",
    )
    _define(
        "float",
        "lease_ttl_s",
        10.0,
        "Membership lease TTL in seconds: a member whose heartbeats stop "
        "for this long is treated as departed (lease pruned, splits "
        "reassigned).  Heartbeats renew at ttl/3, so two missed beats "
        "still keep the lease alive.",
    )
    _define(
        "string",
        "tenant",
        "default",
        "Multi-tenancy (r20): the tenant this task belongs to.  Every PS "
        "object the run creates lives under the 't.<tenant>.' key "
        "namespace, its membership leases / data-service job / served "
        "model are tenant-scoped, and the shared servers account and "
        "admission-control its traffic per tenant — several runs share "
        "one PS/data/serve plane without ever touching each other's "
        "state.  'default' = untagged (byte-identical pre-r20 wire).  "
        "See RUNBOOK 'Multi-tenancy'.",
    )
    _define(
        "string",
        "tenant_quotas",
        "",
        "Multi-tenancy (r20), SERVER tasks (ps/data_service/serve): "
        "per-tenant weighted-fair dispatch weights and quota caps, "
        "'tenant=weight[:max_inflight[:max_dispatch]],...' (e.g. "
        "'runa=3,runb=1:64:8').  Dispatch capacity is divided "
        "weight-proportionally under contention (stride scheduling); a "
        "tenant past a hard cap gets typed RETRY_LATER answers while "
        "other tenants flow.  Unlisted tenants get weight 1, no caps.  "
        "Empty = every tenant weight 1, uncapped.",
    )
    _define(
        "integer",
        "replicas_to_aggregate",
        0,
        "(legacy, sync_replicas) gradients to aggregate per update; 0 = "
        "number of workers.",
    )
    _define(
        "integer",
        "max_staleness",
        0,
        "(async mode) drop gradients older than this many applied steps; "
        "0 = unbounded (the reference's async behavior).",
    )


def is_cross_process_ps(FLAGS) -> bool:
    """True when the CLI requests the reference's one-process-per-task PS
    launch (SURVEY.md sections 3.1/3.2): a PS-emulation mode is selected,
    a PS service address is given, and this process was assigned a task
    role.  In that topology ``--ps_hosts`` is MEANINGFUL — it is where the
    native state service (native/ps_server.cc) listens.  The
    ``data_service`` job is a task of the same launch pattern: a dedicated
    input-worker process serving batches (data/data_service.py) — it needs
    only ``--data_service_hosts``, not a PS service.  The ``serve`` job
    (r10) is a model replica of the inference plane: it needs BOTH a bind
    address (``--serve_hosts``) and the PS topology it pulls params from."""
    if getattr(FLAGS, "job_name", "") == "data_service":
        return bool(getattr(FLAGS, "data_service_hosts", ""))
    if getattr(FLAGS, "job_name", "") == "serve":
        return bool(getattr(FLAGS, "serve_hosts", "")) and bool(
            getattr(FLAGS, "ps_hosts", "")
        )
    return (
        getattr(FLAGS, "job_name", "") in ("chief", "worker", "ps")
        and bool(getattr(FLAGS, "ps_hosts", ""))
        and (getattr(FLAGS, "ps_emulation", False) or not getattr(FLAGS, "sync_replicas", True))
    )


def parse_hostports(spec: str, flag: str = "--ps_hosts") -> list[tuple[str, int]]:
    """Validate a comma-separated ``host:port`` list into addr tuples.
    Malformed entries (empty, missing/non-numeric port, duplicates) fail
    the launch loudly — a typo'd shard list must never silently collapse
    onto fewer servers than the operator asked for."""
    addrs: list[tuple[str, int]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        host, sep, port_s = entry.rpartition(":")
        if not entry or not sep or not host or not port_s.isdigit():
            raise ValueError(
                f"{flag} entry {entry!r} is not host:port (full list: {spec!r})"
            )
        addr = (host, int(port_s))
        if addr in addrs:
            raise ValueError(f"{flag} lists {entry!r} twice ({spec!r})")
        addrs.append(addr)
    return addrs


def ps_shard_topology(FLAGS) -> tuple[list[tuple[str, int]], int, int]:
    """The validated PS shard topology: the FULL ``--ps_hosts`` address
    list plus the resolved shard count (``--ps_shards``; -1 = one shard
    per host) and replica count (``--ps_replicas``, r12).  Shard i's
    PRIMARY is ``addrs[i]`` and replica r of shard i is
    ``addrs[r*shards + i]`` (replica-major) — the ONE place the
    host-order/shard-id correspondence is defined (r9 fix: the pre-r9
    path warned and silently used ``ps_hosts[0]`` only)."""
    addrs = parse_hostports(FLAGS.ps_hosts)
    raw = getattr(FLAGS, "ps_shards", -1)
    n = -1 if raw is None else int(raw)
    r = int(getattr(FLAGS, "ps_replicas", 1) or 1)
    if r not in (1, 2):
        raise ValueError(
            f"--ps_replicas={r} unsupported (1 = unreplicated, 2 = "
            "primary/backup pairs; deeper chains are not implemented)"
        )
    if n < 0:
        if len(addrs) % r:
            raise ValueError(
                f"--ps_replicas={r} does not tile {len(addrs)} --ps_hosts "
                "entries (need shards*replicas hosts)"
            )
        n = len(addrs) // r
    if n == 0 or n * r > len(addrs):
        raise ValueError(
            f"--ps_shards={n} x --ps_replicas={r} invalid for {len(addrs)} "
            f"--ps_hosts entries (need shards*replicas <= {len(addrs)}, "
            "or -1 shards for one shard per host)"
        )
    return addrs, n, r


def parse_reshard_to(spec: str) -> tuple[int, list[tuple[str, int]]]:
    """Validate a ``--ps_reshard_to`` spec: ``V:host:port,host:port,...``
    into ``(new_version, new_addrs)``.  Malformed specs fail the launch
    loudly — a typo'd target topology must never half-join a transition."""
    version_s, sep, hosts = spec.partition(":")
    if not sep or not version_s.isdigit() or int(version_s) <= 0:
        raise ValueError(
            f"--ps_reshard_to {spec!r} must be 'V:host:port,...' with a "
            "positive integer epoch V"
        )
    return int(version_s), parse_hostports(hosts, "--ps_reshard_to")


def resolve_legacy_cluster(FLAGS) -> dict:
    """Interpret legacy cluster flags against the mesh world; returns info for
    the example to log.  A process launched as a PS task has no role in SPMD:
    we exit 0 immediately (the analog of ``server.join()`` never being
    needed) — UNLESS cross-process PS emulation is active, where the PS
    task hosts the native state service for real (is_cross_process_ps).

    Also applies ``--platform`` (must run before first backend use)."""
    if getattr(FLAGS, "platform", ""):
        import jax

        jax.config.update("jax_platforms", FLAGS.platform)
    info = {}
    cross = is_cross_process_ps(FLAGS)
    # Any PS-emulation mode (cross-process OR the single-process thread
    # emulation): --ps_hosts is meaningful topology, never "obsolete".
    emulation = cross or (
        getattr(FLAGS, "ps_emulation", False)
        or not getattr(FLAGS, "sync_replicas", True)
    )
    if getattr(FLAGS, "ps_hosts", ""):
        if emulation:
            # Validate and surface the FULL list (r9 fix: this path used
            # to log entry [0] only, hiding a sharded topology's servers).
            addrs, n_shards, n_replicas = ps_shard_topology(FLAGS)
            info["ps_hosts"] = [f"{h}:{p}" for h, p in addrs]
            info["ps_shards"] = n_shards
            info["ps_replicas"] = n_replicas
            log.info(
                "--ps_hosts given with PS emulation: %d host(s), %d "
                "shard(s) x %d replica(s) — the native state service "
                "serves shard i%%%d, replica i//%d at entry i: %s.",
                len(addrs), n_shards, n_replicas, n_shards, n_shards,
                ",".join(info["ps_hosts"][: n_shards * n_replicas]),
            )
        else:
            info["ps_hosts"] = FLAGS.ps_hosts.split(",")
            log.warning(
                "--ps_hosts given: parameter servers are obsolete on TPU — "
                "variables are mesh-sharded in HBM (replica_device_setter -> "
                "sharding rules). Ignoring %d PS hosts.",
                len(info["ps_hosts"]),
            )
    if getattr(FLAGS, "worker_hosts", ""):
        info["worker_hosts"] = FLAGS.worker_hosts.split(",")
        log.info(
            "--worker_hosts given (%d workers): %s",
            len(info["worker_hosts"]),
            "cross-process PS emulation — one worker process per entry"
            if cross
            else "on TPU the equivalent data-parallel degree comes from the "
            "mesh; launch one process per host with jax.distributed (see "
            "parallel.dist).",
        )
    info["is_legacy_ps_process"] = (
        getattr(FLAGS, "job_name", "") == "ps" and not cross
    )
    return info
