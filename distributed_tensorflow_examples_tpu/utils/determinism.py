"""Determinism controls (SURVEY.md section 5.2).

The reference stack's knob is ``tf.config.experimental.enable_op_determinism``
(``TF/python/framework/config.py:945``) plus fixed seeds.  On TPU, SPMD is
race-free by construction — the nondeterminism sources that remain are
(a) seeds, (b) matmul/reduction precision choices that may vary with fusion
decisions, and (c) host-side data order.  This module centralises the knob:

- every framework RNG flows from one seed (``--seed``; examples already
  fold step/worker ids),
- ``enable()`` pins partitionable threefry (stable keys under sharding) and
  the highest matmul precision so reductions don't vary with tiling,
- data pipelines reshuffle from ``(seed, epoch)`` (see data.pipeline), so
  every host agrees on the permutation.

The async-PS emulation (parallel.async_ps) is *deliberately* nondeterministic
in arrival order by default — that is the semantics being emulated (the
reference's async config is racy by design; SURVEY.md section 5.2).  r4:
``--deterministic`` ALSO switches the async trainer onto the fixed
round-robin interleave (``AsyncPSConfig.fixed_interleave`` — applies still
use stale params, but the schedule, and hence the trajectory, is exactly
reproducible); thread mode's determinism story remains the staleness bound.
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger("dtx.determinism")


def enable(*, matmul_precision: str = "highest") -> None:
    """Turn on run-to-run determinism (the enable_op_determinism analog)."""
    jax.config.update("jax_threefry_partitionable", True)
    jax.config.update("jax_default_matmul_precision", matmul_precision)
    log.info(
        "determinism on: partitionable threefry, matmul precision=%s",
        matmul_precision,
    )
