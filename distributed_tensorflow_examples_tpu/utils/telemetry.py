"""dtxobs core (r13): process-wide metrics registry + event flight recorder.

Every role in the cluster (PS task, data server, serve replica, chief,
worker) accumulates its health into two process-wide singletons:

- :data:`REGISTRY` — a thread-safe metrics registry of named counters,
  gauges and BOUNDED histograms (ring of recent observations reduced to
  p50/p90/p99 at snapshot time).  Instruments are cheap enough for the
  wire hot path (one small lock + an int add per event; percentile math
  is paid only by the scraper), and `snapshot()` flattens everything into
  one JSON-ready ``{name: number}`` table — the payload each service's
  ``STATS`` wire op answers, so one scraper (``tools/dtxtop.py``) can poll
  a live cluster with zero side channels.
- :data:`RECORDER` — a structured-event flight recorder: a bounded ring
  of typed events (connects, reconnects, failovers, reseeds, injected
  faults, divergence latches...).  ``utils/faults.log_event`` feeds every
  structured ``dtx.faults`` line into it, so the ring IS the recent fault/
  recovery history of the process; it is dumped to JSONL on demand and on
  fatal conditions (``REPL_DIVERGED`` latches, reconnect-budget
  exhaustion, injected deaths) so a post-mortem can attribute the failure
  to its cause without having had logging configured in advance.

Naming convention: ``<family>/<metric>`` (``ps_client/reconnects``,
``ps_shard/pull_cache_hits``) — same family idea as
``utils.metrics.shard_scalars``, so dashboards glob one prefix per
subsystem.

The dump directory resolves from the ``DTX_OBS_EVENTS_DIR`` env var
(launchers export it from ``--obs_events_dir``); unset means on-fatal
dumps are skipped (explicit ``dump(path=...)`` always writes).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

#: Env var naming the flight-recorder dump directory (exported to every
#: cluster child by the launchers from ``--obs_events_dir``).
EVENTS_DIR_ENV = "DTX_OBS_EVENTS_DIR"


class Counter:
    """Monotone counter.  ``inc`` is thread-safe (Python int ``+=`` spans
    several bytecodes, so the GIL alone does not make it atomic)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v

    def _reset(self) -> None:
        with self._lock:
            self._v = 0


class Gauge:
    """Last-written value (queue depths, model steps, flags-as-metrics)."""

    __slots__ = ("name", "_v", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    @property
    def value(self) -> float:
        return self._v

    def _reset(self) -> None:
        with self._lock:
            self._v = 0.0


class Histogram:
    """Bounded ring of recent observations -> count/p50/p90/p99/max.

    ``observe`` is O(1) under a lock; the percentile reduction (a sort of
    at most ``capacity`` floats) runs only in :meth:`snapshot` — scrape
    cost lives with the scraper, not the hot path."""

    __slots__ = ("name", "_cap", "_buf", "_n", "_lock")

    def __init__(self, name: str, capacity: int = 512):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = name
        self._cap = int(capacity)
        self._buf: list[float] = [0.0] * self._cap
        self._n = 0  # total ever observed; ring index is _n % _cap
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._buf[self._n % self._cap] = float(v)
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    def snapshot(self) -> dict[str, float]:
        """``{count, p50, p90, p99, max}`` over the retained window (zeros
        when nothing has been observed — scrapers still see the keys)."""
        with self._lock:
            m = min(self._n, self._cap)
            window = sorted(self._buf[:m])
            n = self._n
        if not window:
            return {"count": 0, "p50": 0.0, "p90": 0.0, "p99": 0.0, "max": 0.0}

        def pct(p: float) -> float:
            # Nearest-rank on the sorted window: cheap, monotone, and
            # exact at the edges (p99 of a small window is its max).
            i = min(len(window) - 1, max(0, round(p / 100 * (len(window) - 1))))
            return window[i]

        return {
            "count": n,
            "p50": pct(50),
            "p90": pct(90),
            "p99": pct(99),
            "max": window[-1],
        }

    def _reset(self) -> None:
        with self._lock:
            self._n = 0


class MetricsRegistry:
    """Get-or-create instrument table.  Instrument handles are stable for
    the process lifetime (hot paths cache them at module scope), so
    :meth:`reset` ZEROES values instead of dropping instruments — a cached
    handle keeps counting into the table the next snapshot reads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str, capacity: int = 512) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, capacity)
            return h

    # Convenience one-shot spellings (cold paths that don't cache handles).
    def inc(self, name: str, n: int = 1) -> None:
        self.counter(name).inc(n)

    def set_gauge(self, name: str, v: float) -> None:
        self.gauge(name).set(v)

    def observe(self, name: str, v: float) -> None:
        self.histogram(name).observe(v)

    def snapshot(self) -> dict[str, float]:
        """One flat JSON-ready table: counters and gauges verbatim,
        histograms flattened as ``<name>_count/_p50/_p90/_p99/_max``."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._hists.values())
        out: dict[str, float] = {}
        for c in counters:
            out[c.name] = c.value
        for g in gauges:
            out[g.name] = g.value
        for h in hists:
            for k, v in h.snapshot().items():
                out[f"{h.name}_{k}"] = v
        return out

    def reset(self) -> None:
        """Zero every instrument (test isolation; handles stay valid)."""
        with self._lock:
            instruments = (
                list(self._counters.values())
                + list(self._gauges.values())
                + list(self._hists.values())
            )
        for i in instruments:
            i._reset()


#: The process-wide registry every role instruments onto.
REGISTRY = MetricsRegistry()


class FlightRecorder:
    """Bounded ring of structured events, dumped to JSONL on demand.

    ``record`` is the single write path (``faults.log_event`` calls it for
    every ``dtx.faults`` line, so injected faults and recovery actions are
    captured even when nothing is watching).  ``dump`` writes one JSONL
    file — a ``dump`` header line carrying the reason, then every retained
    event oldest-first — to an explicit path or into the
    ``DTX_OBS_EVENTS_DIR`` directory; with neither configured it is a
    no-op returning None, so fatal-path hooks are always safe to call."""

    def __init__(self, capacity: int = 4096):
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._dumps = 0

    def record(self, event: str, **fields) -> None:
        entry = {"ts": time.time(), "event": str(event), **fields}
        with self._lock:
            self._events.append(entry)

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    @property
    def dumps(self) -> int:
        return self._dumps

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def dump(self, path: str | None = None, *, reason: str = "") -> str | None:
        if path is None:
            d = os.environ.get(EVENTS_DIR_ENV, "")
            if not d:
                return None
            role = os.environ.get("DTX_FAULT_ROLE", "") or "proc"
            path = os.path.join(d, f"flight-{role}-{os.getpid()}.jsonl")
        events = self.events()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps(
                {
                    "ts": time.time(), "event": "dump", "reason": reason,
                    "pid": os.getpid(), "retained": len(events),
                },
                default=str,
            ) + "\n")
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        with self._lock:
            self._dumps += 1
        return path


#: The process-wide flight recorder.
RECORDER = FlightRecorder()


def record_event(event: str, **fields) -> None:
    """Module-level spelling of ``RECORDER.record`` (instrumentation
    sites read better without the singleton plumbing)."""
    RECORDER.record(event, **fields)


def dump_flight_recorder(reason: str, path: str | None = None) -> str | None:
    """Best-effort fatal-path dump: record the reason as its own event,
    then dump the ring.  Never raises — the caller is already on an error
    path and must not trade its diagnostic for an IO failure."""
    try:
        RECORDER.record("fatal", reason=reason)
        return RECORDER.dump(path, reason=reason)
    except Exception:
        return None


def snapshot() -> dict[str, float]:
    """The process registry's flat table (module-level convenience for the
    services' STATS handlers)."""
    return REGISTRY.snapshot()
