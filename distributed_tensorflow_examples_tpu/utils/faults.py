"""Deterministic, seedable fault-injection layer for the PS path.

The reference inherits TF's fault model: a lost PS task stalls every worker
until the runtime tears the session down and the whole job crash-restarts
(SURVEY.md section 5.3).  This module makes faults *injectable, survivable
and tested* instead: a fault plan — activated via the ``DTX_FAULT_PLAN``
env var, so every child process of a ``utils.multiprocess`` cluster (or a
``--job_name`` launch) inherits it — scripts exactly which process drops a
connection, delays an op, or dies, and when.  The recovery machinery under
test lives in ``parallel/ps_service.py`` (deadline/backoff/reconnect/replay)
and ``train/ps_experiment.py`` (PS task under ``supervise()``).

Plan syntax (semicolon-separated specs, ``kind:key=val,key=val``)::

    DTX_FAULT_PLAN='drop_conn:role=worker0,op=25;die:role=ps,after_reqs=120'

Kinds:

- ``drop_conn`` — the matching process's ``PSClient`` closes its socket
  right before its ``op``-th call (1-based, counted per client), forcing
  the reconnect+replay path.  ``count`` (default 1) repeats the fault on
  the following calls too.
- ``delay`` — sleep ``ms`` milliseconds before the ``op``-th call (and the
  next ``count-1`` calls): the slow-PS / slow-network fault.
- ``die`` — the matching PROCESS exits with code ``FAULT_EXIT_CODE`` (43)
  either ``after_s`` seconds after :func:`arm_process_faults`, or once the
  in-process PS server has served ``after_reqs`` requests (the "kill PS at
  step K" fault).  The request count tracks the coordination traffic but
  is not exactly reproducible across machines — idle shutdown-queue polls
  and bounded-wait chunk re-issues add timing-dependent requests — so
  pick triggers with margin (well above startup chatter, well below the
  run's total).  One-shot: a supervisor restarting the task strips the
  spec via :func:`plan_without` so the incarnation that heals is not
  re-killed.
- ``partition`` — drop traffic between two named roles while BOTH stay
  alive: the fault that tests failover and split-brain guards distinctly
  from death.  Two shapes: (a) process-level, ``partition:role=ps0,
  peer=ps2`` — the matching SERVICE process severs its replication link
  toward the peer role by policy (``arm_process_faults(partition_fn=...)``
  — for a replicated PS pair the next mutating op then fails loudly with
  the divergence error instead of silently splitting brains); timing via
  ``after_s``/``after_reqs`` like ``die``, or immediately when neither is
  given.  (b) client-level, ``partition:role=worker0,op=5`` — from the
  ``op``-th call onward, EVERY op on the matching client severs its
  socket first (the persistent-drop analog of ``drop_conn``): the client
  keeps healing by reconnect, so this models a flapping/black-holed link
  rather than a dead peer.

Every spec takes ``role=`` (fnmatch glob, default ``*``) matched against
the process role — set by launchers via the ``DTX_FAULT_ROLE`` env var or
:func:`set_role` (``ps0``, ``chief0``, ``worker1``, ``data_service0``,
``serve0``, ``task2``...).  Per-connection client roles derive from the
process role: a worker's prefetch PS connection is ``worker<i>_pf``, its
data-service connections are ``<role>_ds`` (``data/data_service.py``) and
a process's serving-wire connections are ``<role>_sv``
(``serve/client.py``), so plans can target one transport of a process
without firing on the others; broad globs (``worker0*``) still match them
all.  Client
faults additionally take ``p=``/``seed=`` for probabilistic injection: the
RNG is seeded from ``(seed, role, op-kind)``, and op indices count LOGICAL
client ops (chunk re-issues of one blocking op don't advance the counter),
so a given plan fires at the same logical operation in every run —
deterministic AND seedable.  (``after_reqs`` is the exception: see above.)

Observability: every injected fault and every recovery action logs one
structured line through the ``dtx.faults`` logger (``dtx.faults
event=<name> k=v ...``), so tests — and operators grepping task logs —
can assert the recovery path actually ran.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import logging
import os
import sys
import threading
import time
import zlib

from . import telemetry

log = logging.getLogger("dtx.faults")

#: Exit code of a fault-injected process death ("die" spec).  Distinctive so
#: supervisors/tests can tell an injected kill from an organic crash.
FAULT_EXIT_CODE = 43

_CLIENT_KINDS = ("drop_conn", "delay", "partition")
# Membership event kinds (r14 elasticity): ``leave`` — the matching
# process departs GRACEFULLY (runs its registered leave hooks — release
# the membership lease, stop the service — then exits 0, so a supervisor
# treats it as done, not a crash to heal); ``join`` — an ORCHESTRATOR
# event (only a process that can spawn new tasks can honor it): loadsim
# reads matching specs via :func:`join_specs` and starts the named role at
# ``after_s``; in-process arming skips it loudly.  Together with ``die``
# they script a full kill/join/leave cycle per role.
_KINDS = _CLIENT_KINDS + ("die", "leave", "join")

_role_lock = threading.Lock()
_role: str | None = None

_control_codes: frozenset | None = None


def control_op_codes() -> frozenset:
    """Wire op CODES of every control-plane op, all three services —
    derived from the one registry (``wire.CONTROL_OPS``; codes are
    disjoint across services except the shared HELLO point, so one flat
    set serves every wire's injector).  The client op index SKIPS these:
    ``op=N`` plan indices address logical data-plane ops, and heartbeat/
    scrape/epoch-poll cadence must never shift them (the r15 fault-index
    drift, generalized).  Lazy import: wire is JAX-free, but resolving it
    at module load would order utils before parallel in every importer."""
    global _control_codes
    if _control_codes is None:
        from ..parallel import wire

        registries = {
            "ps": wire.PS_OPS, "dsvc": wire.DSVC_OPS, "msrv": wire.SRV_OPS,
        }
        _control_codes = frozenset(
            registries[svc][name]
            for svc, names in wire.CONTROL_OPS.items()
            for name in names
        )
    return _control_codes


@dataclasses.dataclass
class FaultSpec:
    kind: str
    role: str = "*"  # fnmatch glob against the process role
    op: int = 0  # client faults: 1-based call index the fault fires at
    count: int = 1  # client faults: consecutive calls affected
    ms: float = 0.0  # delay: sleep duration
    after_s: float = 0.0  # die/partition: seconds after arming
    after_reqs: int = 0  # die/partition: server requests served
    p: float = 1.0  # client faults: per-eligible-op probability
    seed: int = 0  # seeds the probabilistic RNG (with role+kind)
    peer: str = "*"  # partition: glob for the OTHER side of the cut link

    def matches_role(self, role: str) -> bool:
        return fnmatch.fnmatchcase(role, self.role)

    def matches_peer(self, role: str) -> bool:
        return fnmatch.fnmatchcase(role, self.peer)


def parse_plan(plan: str) -> list[FaultSpec]:
    """Parse a ``DTX_FAULT_PLAN`` string; raises ValueError on bad syntax so
    a typo'd plan fails the launch instead of silently injecting nothing."""
    specs: list[FaultSpec] = []
    for raw in plan.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        kind, _, rest = raw.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in {raw!r}")
        kw: dict = {}
        for item in filter(None, (s.strip() for s in rest.split(","))):
            key, has_eq, val = item.partition("=")
            if not has_eq:
                raise ValueError(f"bad fault field {item!r} in {raw!r}")
            if key in ("role", "peer"):
                kw[key] = val
            elif key in ("op", "count", "after_reqs", "seed"):
                kw[key] = int(val)
            elif key in ("ms", "after_s", "p"):
                kw[key] = float(val)
            else:
                raise ValueError(f"unknown fault field {key!r} in {raw!r}")
        spec = FaultSpec(kind=kind, **kw)
        # ``partition`` is exempt: its process shape (role+peer, timed like
        # die or immediate) carries no op index; only its op>0 form is a
        # client fault.
        if spec.kind in _CLIENT_KINDS and spec.kind != "partition" \
                and spec.op <= 0:
            raise ValueError(f"{kind} fault needs op=<n> (1-based): {raw!r}")
        if spec.kind in ("die", "leave") and not (
            spec.after_s > 0 or spec.after_reqs > 0
        ):
            raise ValueError(
                f"{kind} fault needs after_s or after_reqs: {raw!r}"
            )
        if spec.kind == "join" and not spec.after_s > 0:
            raise ValueError(
                f"join event needs after_s (orchestrators schedule joins "
                f"by wall time): {raw!r}"
            )
        specs.append(spec)
    return specs


def format_plan(specs: list[FaultSpec]) -> str:
    """Inverse of :func:`parse_plan` (used to strip fired specs on restart)."""
    out = []
    for s in specs:
        fields = []
        defaults = FaultSpec(kind=s.kind)
        for f in dataclasses.fields(FaultSpec):
            if f.name == "kind":
                continue
            v = getattr(s, f.name)
            if v != getattr(defaults, f.name):
                fields.append(f"{f.name}={v}")
        out.append(s.kind + (":" + ",".join(fields) if fields else ""))
    return ";".join(out)


def plan_without(plan: str, kind: str, role: str) -> str:
    """The plan minus specs of ``kind`` whose role glob matches ``role`` —
    how a supervisor avoids re-killing the incarnation that heals the
    fault it just injected."""
    return format_plan(
        [s for s in parse_plan(plan) if not (s.kind == kind and s.matches_role(role))]
    )


def set_role(role: str) -> None:
    """Set this process's fault role (launchers call this; also exported to
    children via ``DTX_FAULT_ROLE``)."""
    global _role
    with _role_lock:
        _role = role
    os.environ["DTX_FAULT_ROLE"] = role


def current_role() -> str:
    with _role_lock:
        if _role is not None:
            return _role
    return os.environ.get("DTX_FAULT_ROLE", "")


def active_plan() -> str:
    return os.environ.get("DTX_FAULT_PLAN", "")


def log_event(event: str, **fields) -> None:
    """One structured ``dtx.faults`` line per fault/recovery action.  A
    stderr handler (and an INFO level) is attached on first use when the
    ambient logging config would swallow the event — recovery evidence
    must reach per-task log files even in processes whose root logger sits
    at the WARNING default.  Propagation stays on, so pytest's caplog (and
    any operator-configured root handler) still sees every event.

    Every line is ALSO retained by the process flight recorder (r13
    dtxobs): injected faults and recovery actions stay attributable
    post-hoc from the recorder's JSONL dump even when no log collector
    was watching the process."""
    try:
        telemetry.record_event(event, **fields)
    except Exception:
        pass  # observability must never fail the recovery path it observes
    if not log.handlers and not log.isEnabledFor(logging.INFO):
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter("%(message)s"))
        log.addHandler(h)
        log.setLevel(logging.INFO)
    kv = " ".join(f"{k}={fields[k]}" for k in sorted(fields))
    log.info("dtx.faults event=%s%s", event, (" " + kv) if kv else "")


class ClientFaultInjector:
    """Per-``PSClient`` hook: consults the plan before every client op.
    Deterministic — the op counter is per client, and the probabilistic RNG
    is seeded from (seed, role, kind).

    Control-plane ops (:func:`control_op_codes`) neither advance the
    counter nor fire faults, so a client that interleaves scrapes or
    epoch polls with its data ops keeps stable plan indices.
    ``count_control_ops=True`` is the opt-in for DEDICATED control
    clients (the ``_lm`` membership legs): their lease stream IS their
    logical op stream, and excluding it would leave them untargetable."""

    def __init__(
        self, role: str | None = None, plan: str | None = None,
        count_control_ops: bool = False,
    ):
        self.role = role if role is not None else current_role()
        raw = plan if plan is not None else active_plan()
        # Only a partition spec's CLIENT shape (an explicit op index)
        # belongs here — its process shape (role+peer) arms at the service
        # host via arm_process_faults and must not also sever the host's
        # own client legs.
        self._specs = [
            s
            for s in (parse_plan(raw) if raw else [])
            if s.kind in _CLIENT_KINDS and s.matches_role(self.role)
            and (s.kind != "partition" or s.op > 0)
        ]
        self._op = 0
        self._rngs: dict[int, "_DetRng"] = {}
        # Resolved only when a plan is live: the no-faults hot path must
        # not import the wire registry.
        self._control: frozenset = (
            frozenset() if (count_control_ops or not self._specs)
            else control_op_codes()
        )

    def _fires(self, i: int, spec: FaultSpec) -> bool:
        if spec.kind == "partition":
            # Persistent from its op index onward (count ignored): a
            # partition stays cut until the plan changes.
            if self._op < spec.op:
                return False
        elif not (spec.op <= self._op < spec.op + spec.count):
            return False
        if spec.p >= 1.0:
            return True
        rng = self._rngs.setdefault(i, _DetRng(spec.seed, self.role, spec.kind))
        return rng.uniform() < spec.p

    def before_op(self, op_code: int) -> bool:
        """Advance the op counter; sleep for matching delays.  Returns True
        when a drop_conn/partition fault fires (the caller must sever its
        socket)."""
        if not self._specs or op_code in self._control:
            return False
        self._op += 1
        drop = False
        for i, spec in enumerate(self._specs):
            if not self._fires(i, spec):
                continue
            if spec.kind == "delay":
                log_event(
                    "inject_delay", role=self.role, op=self._op,
                    op_code=op_code, ms=spec.ms, spec=format_plan([spec]),
                )
                time.sleep(spec.ms / 1000.0)
            elif spec.kind == "drop_conn":
                log_event(
                    "inject_drop_conn", role=self.role, op=self._op,
                    op_code=op_code, spec=format_plan([spec]),
                )
                drop = True
            elif spec.kind == "partition":
                if self._op == spec.op:  # log the cut once, not per op
                    log_event(
                        "inject_partition", role=self.role, op=self._op,
                        op_code=op_code, spec=format_plan([spec]),
                    )
                drop = True
        return drop


class _DetRng:
    """Tiny deterministic uniform stream (no numpy import on the hot path):
    xorshift64* seeded from (seed, role, kind)."""

    def __init__(self, seed: int, role: str, kind: str):
        self._s = (
            (seed * 0x9E3779B97F4A7C15)
            ^ zlib.crc32(f"{role}/{kind}".encode())
        ) & 0xFFFFFFFFFFFFFFFF or 0x2545F4914F6CDD1D

    def uniform(self) -> float:
        x = self._s
        x ^= (x >> 12) & 0xFFFFFFFFFFFFFFFF
        x = (x ^ (x << 25)) & 0xFFFFFFFFFFFFFFFF
        x ^= x >> 27
        self._s = x
        return ((x * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) / 2**64


def client_injector(
    role: str | None = None, *, count_control_ops: bool = False,
) -> ClientFaultInjector | None:
    """A ``ClientFaultInjector`` for this process, or None when the plan has
    no client faults for the role (keeps the no-faults hot path at zero
    cost: one None check per op).  ``count_control_ops``: see
    :class:`ClientFaultInjector` — dedicated control clients only."""
    inj = ClientFaultInjector(role=role, count_control_ops=count_control_ops)
    return inj if inj._specs else None


def join_specs(plan: str, role: str | None = None) -> list[FaultSpec]:
    """The plan's ``join`` events (optionally filtered by a role glob
    match) — the ORCHESTRATOR's half of membership chaos: only a process
    that can spawn cluster tasks (tools/loadsim.py) can honor a join, so
    it reads them from here instead of :func:`arm_process_faults`."""
    return [
        s
        for s in (parse_plan(plan) if plan else [])
        if s.kind == "join" and (role is None or s.matches_role(role))
    ]


# Late-registered graceful-departure hooks (r14): a process arms its
# ``leave`` specs before its services (and their membership leases) exist,
# so the hooks are looked up at FIRE time.  Typical hooks: release the
# lease, stop the server.  Run in reverse registration order, each
# guarded — departure must not hang on a broken service.
_leave_hooks: list = []


def register_leave_hook(fn) -> None:
    _leave_hooks.append(fn)


def _leave(spec: FaultSpec, role: str, leave_fn=None, **fields) -> None:
    log_event(
        "inject_leave", role=role, spec=format_plan([spec]), **fields,
    )
    telemetry.dump_flight_recorder(f"inject_leave role={role}")
    for fn in [leave_fn] + list(reversed(_leave_hooks)):
        if fn is None:
            continue
        try:
            fn()
        except Exception:
            pass
    for h in log.handlers:
        try:
            h.flush()
        except Exception:
            pass
    # Exit 0: a LEAVE is a clean departure — the supervisor (exit-0 =
    # done) must not resurrect a member that scaled itself down.
    os._exit(0)


def _die(spec: FaultSpec, role: str, **fields) -> None:
    log_event(
        "inject_die", role=role, exit=FAULT_EXIT_CODE,
        spec=format_plan([spec]), **fields,
    )
    # The process is about to hard-exit: persist the flight recorder NOW
    # (the injected death plus everything leading up to it), so a chaos
    # run's post-mortem can attribute the kill to its spec.
    telemetry.dump_flight_recorder(f"inject_die role={role}")
    for h in log.handlers:
        try:
            h.flush()
        except Exception:
            pass
    os._exit(FAULT_EXIT_CODE)


def arm_process_faults(
    role: str | None = None, *, request_count_fn=None, partition_fn=None,
    leave_fn=None,
) -> list[threading.Thread]:
    """Arm matching ``die``/``leave`` (and process-shape ``partition``)
    specs for this process.  ``after_s`` specs start a timer thread;
    ``after_reqs`` specs need ``request_count_fn`` (e.g.
    ``ps_service.server_request_count`` in a PS task) and poll it.
    ``partition_fn(spec) -> bool`` is the service host's cut-the-link hook
    (a replicated PS task severs its repl link when the spec's ``peer``
    glob matches its peer's role); partition specs without timing fields
    arm immediately.  ``leave_fn`` is the graceful-departure hook a
    ``leave`` spec runs before exiting 0 (late hooks can also be added via
    :func:`register_leave_hook`).  ``join`` specs are orchestrator events
    (:func:`join_specs`) and are skipped here, loudly.  Returns the
    watcher threads (daemonic; tests may join on a dead process)."""
    role = role if role is not None else current_role()
    raw = active_plan()
    if not raw:
        return []

    def fire_partition(spec):
        if partition_fn(spec):
            log_event(
                "inject_partition", role=role, peer=spec.peer,
                after_s=spec.after_s, after_reqs=spec.after_reqs,
                spec=format_plan([spec]),
            )

    threads: list[threading.Thread] = []
    for spec in parse_plan(raw):
        if spec.kind == "partition" and spec.op <= 0 and \
                spec.matches_role(role):
            if partition_fn is None:
                log_event(
                    "fault_unarmed", role=role, kind="partition",
                    reason="no_partition_hook_in_this_process",
                )
                continue
            if spec.after_s > 0:

                def ptimer(spec=spec):
                    time.sleep(spec.after_s)
                    fire_partition(spec)

                t = threading.Thread(
                    target=ptimer, daemon=True, name="dtx-fault-partition"
                )
                t.start()
                threads.append(t)
            elif spec.after_reqs > 0:
                if request_count_fn is None:
                    # Same contract as the die kind: a timed trigger with
                    # no counter to read must be SKIPPED loudly, never
                    # fired at request 0.
                    log_event(
                        "fault_unarmed", role=role, kind="partition",
                        reason="after_reqs_without_request_counter",
                    )
                    continue

                def ppoller(spec=spec):
                    while True:
                        if request_count_fn() >= spec.after_reqs:
                            fire_partition(spec)
                            return
                        time.sleep(0.02)

                t = threading.Thread(
                    target=ppoller, daemon=True, name="dtx-fault-partition"
                )
                t.start()
                threads.append(t)
            else:
                fire_partition(spec)
            continue
        if spec.kind == "join" and spec.matches_role(role):
            # Only an orchestrator (a process that can SPAWN cluster
            # tasks) can honor a join — skip loudly, like an unarmable
            # after_reqs trigger, so a plan wired to the wrong process is
            # never silently inert.
            log_event(
                "fault_unarmed", role=role, kind="join",
                reason="join_is_orchestrated",
            )
            continue
        if spec.kind not in ("die", "leave") or not spec.matches_role(role):
            continue
        fire = (
            _die
            if spec.kind == "die"
            else lambda spec, role, **kw: _leave(
                spec, role, leave_fn=leave_fn, **kw
            )
        )
        if spec.after_s > 0:

            def timer(spec=spec, fire=fire):
                time.sleep(spec.after_s)
                fire(spec, role, after_s=spec.after_s)

            t = threading.Thread(target=timer, daemon=True, name="dtx-fault-die")
            t.start()
            threads.append(t)
        if spec.after_reqs > 0:
            if request_count_fn is None:
                # Only a PS-server-hosting process has a request counter; a
                # broad role glob (e.g. the '*' default) must not take down
                # chief/worker tasks that merely match it — skip, loudly.
                log_event(
                    "fault_unarmed", role=role, kind=spec.kind,
                    reason="after_reqs_without_request_counter",
                )
                continue

            def poller(spec=spec, fire=fire):
                while True:
                    n = request_count_fn()
                    if n >= spec.after_reqs:
                        fire(spec, role, after_reqs=spec.after_reqs, reqs=n)
                    time.sleep(0.02)

            t = threading.Thread(target=poller, daemon=True, name="dtx-fault-die")
            t.start()
            threads.append(t)
    return threads
