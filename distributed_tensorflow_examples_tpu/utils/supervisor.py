"""Per-task supervisor: the whole-job crash-restart half of fault recovery.

The reference's recovery model (SURVEY.md section 5.3) is crash-restart from
checkpoint: non-chief workers blocked in ``wait_for_session``, the chief
re-``prepare_session``-ed from the newest checkpoint.  The TPU-native analog
has two parts:

1. detection — ``parallel.dist.start_watchdog``: when any peer's heartbeat
   stops, every surviving process exits ``EXIT_PEER_LOST`` promptly rather
   than hanging in the next collective;
2. restart — THIS module: each cluster task runs under ``supervise()``,
   which relaunches its child with the same environment (same TF_CONFIG,
   same flags) whenever it exits nonzero.  All tasks restart within one
   grace period of each other, the coordination service re-forms over the
   fixed process set, and ``TrainSession`` auto-resumes from the last
   checkpoint.

Single-worker *rejoin into a live job* is deliberately NOT supported: the
coordination service and every compiled collective are formed over a fixed
process set, so a restarted process cannot re-enter an existing incarnation
(documented divergence shared with the reference, which was equally
non-elastic).

Usage (one per cluster task, e.g. from a launcher)::

    python -m distributed_tensorflow_examples_tpu.utils.supervisor \
        --max_restarts=3 -- python examples/mnist_mlp.py --log_dir=...
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time

log = logging.getLogger("dtx.supervisor")


def supervise(
    argv: list[str],
    *,
    max_restarts: int = 3,
    backoff_s: float = 1.0,
    env: dict[str, str] | None = None,
) -> int:
    """Run ``argv`` as a child process, restarting it on nonzero exit.

    Returns the final exit code: 0 on eventual success, the child's last
    code once ``max_restarts`` is exhausted.  Each restart logs the incident
    and waits ``backoff_s`` (linearly growing) so all tasks of a job have
    time to die before the new incarnation forms.
    """
    attempt = 0
    while True:
        proc = subprocess.run(argv, env=env)
        if proc.returncode == 0:
            if attempt:
                log.info("supervise: child succeeded after %d restart(s)", attempt)
            return 0
        if attempt >= max_restarts:
            log.error(
                "supervise: child exited %d; restart budget (%d) exhausted",
                proc.returncode,
                max_restarts,
            )
            return proc.returncode
        attempt += 1
        delay = backoff_s * attempt
        log.warning(
            "supervise: child exited %d; restart %d/%d in %.1fs "
            "(whole-job crash-restart — training auto-resumes from the last "
            "checkpoint)",
            proc.returncode,
            attempt,
            max_restarts,
            delay,
        )
        time.sleep(delay)


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    max_restarts, backoff = 3, 1.0
    while argv and argv[0].startswith("--"):
        flag = argv.pop(0)
        if flag == "--":
            break
        key, has_eq, val = flag.lstrip("-").partition("=")
        if key not in ("max_restarts", "backoff_s"):
            print(f"supervisor: unknown flag {flag!r}", file=sys.stderr)
            return 2
        if not has_eq:  # space-separated form: --max_restarts 3
            if not argv:
                print(f"supervisor: flag {flag!r} needs a value", file=sys.stderr)
                return 2
            val = argv.pop(0)
        try:
            if key == "max_restarts":
                max_restarts = int(val)
            else:
                backoff = float(val)
        except ValueError:
            print(f"supervisor: bad value for {flag!r}: {val!r}", file=sys.stderr)
            return 2
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    return supervise(argv, max_restarts=max_restarts, backoff_s=backoff, env=dict(os.environ))


if __name__ == "__main__":
    sys.exit(main())
