"""Per-task supervisor: the whole-job crash-restart half of fault recovery.

The reference's recovery model (SURVEY.md section 5.3) is crash-restart from
checkpoint: non-chief workers blocked in ``wait_for_session``, the chief
re-``prepare_session``-ed from the newest checkpoint.  The TPU-native analog
has two parts:

1. detection — ``parallel.dist.start_watchdog``: when any peer's heartbeat
   stops, every surviving process exits ``EXIT_PEER_LOST`` promptly rather
   than hanging in the next collective;
2. restart — THIS module: each cluster task runs under ``supervise()``,
   which relaunches its child with the same environment (same TF_CONFIG,
   same flags) whenever it exits nonzero.  All tasks restart within one
   grace period of each other, the coordination service re-forms over the
   fixed process set, and ``TrainSession`` auto-resumes from the last
   checkpoint.

Single-worker *rejoin into a live job* is deliberately NOT supported: the
coordination service and every compiled collective are formed over a fixed
process set, so a restarted process cannot re-enter an existing incarnation
(documented divergence shared with the reference, which was equally
non-elastic).

Usage (one per cluster task, e.g. from a launcher)::

    python -m distributed_tensorflow_examples_tpu.utils.supervisor \
        --max_restarts=3 -- python examples/mnist_mlp.py --log_dir=...
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time

log = logging.getLogger("dtx.supervisor")


def supervise(
    argv: list[str],
    *,
    max_restarts: int = 3,
    backoff_s: float = 1.0,
    env: dict[str, str] | None = None,
    mutate_env=None,
) -> int:
    """Run ``argv`` as a child process, restarting it on nonzero exit.

    Returns the final exit code: 0 on eventual success, the child's last
    code once ``max_restarts`` is exhausted.  Each restart logs the incident
    and waits ``backoff_s`` (linearly growing) so all tasks of a job have
    time to die before the new incarnation forms.

    ``mutate_env(env, attempt, returncode) -> env`` runs before each
    restart — e.g. the PS supervisor strips a fired ``die`` fault spec from
    ``DTX_FAULT_PLAN`` so the healing incarnation is not re-killed by the
    plan that killed its predecessor.

    SIGTERM/SIGINT to the supervisor are forwarded to the child and end
    supervision (no restart): killing the supervised task's visible pid
    must kill the real server underneath, not orphan it.
    """
    import signal as _signal

    child: list[subprocess.Popen | None] = [None]
    terminated = [False]

    def _forward(signum, frame):
        terminated[0] = True
        p = child[0]
        if p is not None and p.poll() is None:
            try:
                p.send_signal(signum)
            except (ProcessLookupError, OSError):
                pass

    old_handlers = {}
    for sig in (_signal.SIGTERM, _signal.SIGINT):
        try:
            old_handlers[sig] = _signal.signal(sig, _forward)
        except (ValueError, OSError):  # non-main thread: keep defaults
            pass

    attempt = 0
    returncode = 0
    try:
        while True:
            if terminated[0]:
                # Signal landed while no child was running (backoff window):
                # honor it instead of spawning an incarnation it can't reach.
                log.info("supervise: terminated by signal; not restarting")
                return returncode or 130
            proc = subprocess.Popen(argv, env=env)
            child[0] = proc
            if terminated[0] and proc.poll() is None:
                # Signal raced the spawn (before child[0] was visible to
                # the handler): forward it by hand.
                proc.terminate()
            returncode = proc.wait()
            child[0] = None
            if terminated[0]:
                log.info("supervise: terminated by signal; not restarting")
                return returncode
            if returncode == 0:
                if attempt:
                    log.info(
                        "supervise: child succeeded after %d restart(s)", attempt
                    )
                return 0
            if attempt >= max_restarts:
                log.error(
                    "supervise: child exited %d; restart budget (%d) exhausted",
                    returncode,
                    max_restarts,
                )
                return returncode
            attempt += 1
            if mutate_env is not None:
                env = mutate_env(dict(env if env is not None else os.environ),
                                 attempt, returncode)
            delay = backoff_s * attempt
            log.warning(
                "supervise: child exited %d; restart %d/%d in %.1fs "
                "(whole-job crash-restart — training auto-resumes from the "
                "last checkpoint)",
                returncode,
                attempt,
                max_restarts,
                delay,
            )
            time.sleep(delay)
    finally:
        for sig, handler in old_handlers.items():
            try:
                _signal.signal(sig, handler)
            except (ValueError, OSError):
                pass


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    max_restarts, backoff = 3, 1.0
    while argv and argv[0].startswith("--"):
        flag = argv.pop(0)
        if flag == "--":
            break
        key, has_eq, val = flag.lstrip("-").partition("=")
        if key not in ("max_restarts", "backoff_s"):
            print(f"supervisor: unknown flag {flag!r}", file=sys.stderr)
            return 2
        if not has_eq:  # space-separated form: --max_restarts 3
            if not argv:
                print(f"supervisor: flag {flag!r} needs a value", file=sys.stderr)
                return 2
            val = argv.pop(0)
        try:
            if key == "max_restarts":
                max_restarts = int(val)
            else:
                backoff = float(val)
        except ValueError:
            print(f"supervisor: bad value for {flag!r}: {val!r}", file=sys.stderr)
            return 2
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    return supervise(argv, max_restarts=max_restarts, backoff_s=backoff, env=dict(os.environ))


if __name__ == "__main__":
    sys.exit(main())
