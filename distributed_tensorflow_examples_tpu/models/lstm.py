"""W5: PTB word-level LSTM language model
(SURVEY.md section 2a W5, BASELINE.json:11).

Reference shape: legacy ``BasicLSTMCell`` stacks unrolled over truncated-BPTT
windows, trained multi-worker sync with ``MultiWorkerMirroredStrategy`` (ref
``rnn_cell_impl.py:825``, ``collective_all_reduce_strategy.py:57``).

TPU-native shape: time recurrence is a ``lax.scan`` (compiler-friendly — one
compiled loop, no Python unrolling), batch sharded over ``data``; the LSTM
carry persists across steps through ``model_state`` (the TBPTT convention:
final state of one window is the initial state of the next), sharded over
``data`` alongside the batch rows it belongs to.  The embedding and softmax
tables may shard over ``model`` (the PS-sharded-table analog, as in W4).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import layers


@dataclasses.dataclass(frozen=True)
class Config:
    vocab_size: int = 10000
    dim: int = 200  # embedding + hidden width ("medium" PTB config scale)
    num_layers: int = 2
    keep_prob: float = 1.0  # inverted dropout on non-recurrent connections
    compute_dtype: str = "bfloat16"

    @property
    def dtype(self):
        return jnp.dtype(self.compute_dtype)


def init(cfg: Config, rng: jax.Array, *, batch_size: int):
    """Returns (params, model_state); model_state holds the TBPTT carry
    (c, h per layer), shaped for the GLOBAL batch."""
    rngs = jax.random.split(rng, cfg.num_layers + 2)
    params: dict = {"emb": layers.embedding_init(rngs[0], cfg.vocab_size, cfg.dim)}
    for i in range(cfg.num_layers):
        params[f"lstm_{i}"] = layers.lstm_cell_init(rngs[1 + i], cfg.dim, cfg.dim)
    params["softmax"] = layers.dense_init(rngs[-1], cfg.dim, cfg.vocab_size)
    carry = {
        f"lstm_{i}": {
            "c": jnp.zeros((batch_size, cfg.dim), jnp.float32),
            "h": jnp.zeros((batch_size, cfg.dim), jnp.float32),
        }
        for i in range(cfg.num_layers)
    }
    return params, carry


def reset_carry(model_state):
    """Zero the TBPTT carry (epoch boundary in the PTB convention)."""
    return jax.tree.map(jnp.zeros_like, model_state)


def apply(cfg: Config, params, carry, x, *, rng=None):
    """x: [B, T] int32 -> (logits [B, T, V], new_carry).

    The time loop is one ``lax.scan`` over all layers jointly (inputs flow
    through the stack each timestep) — matching the reference's
    ``MultiRNNCell`` step order exactly.
    """
    emb = layers.embedding_lookup(params["emb"], x, dtype=cfg.dtype)  # [B,T,D]
    if cfg.keep_prob < 1.0 and rng is not None:
        mask = jax.random.bernoulli(rng, cfg.keep_prob, emb.shape)
        emb = jnp.where(mask, emb / cfg.keep_prob, 0).astype(emb.dtype)
    xs = jnp.swapaxes(emb, 0, 1)  # time-major [T,B,D] for scan

    layer_carries = tuple(
        (carry[f"lstm_{i}"]["c"], carry[f"lstm_{i}"]["h"])
        for i in range(cfg.num_layers)
    )

    def step(carries, x_t):
        new_carries = []
        h = x_t
        for i in range(cfg.num_layers):
            c_i, h_i = lstm_carry = carries[i]
            lstm_carry, h = layers.lstm_cell(
                params[f"lstm_{i}"], (c_i, h_i), h, dtype=cfg.dtype
            )
            new_carries.append(lstm_carry)
        return tuple(new_carries), h

    final_carries, hs = jax.lax.scan(step, layer_carries, xs)  # hs: [T,B,D]
    hs = jnp.swapaxes(hs, 0, 1)  # [B,T,D]
    logits = layers.dense(params["softmax"], hs, dtype=cfg.dtype)
    new_carry = {
        f"lstm_{i}": {
            # stop_gradient: TBPTT truncates backprop at the window boundary.
            "c": jax.lax.stop_gradient(final_carries[i][0].astype(jnp.float32)),
            "h": jax.lax.stop_gradient(final_carries[i][1].astype(jnp.float32)),
        }
        for i in range(cfg.num_layers)
    }
    return logits, new_carry


def loss_fn(cfg: Config):
    def f(params, model_state, batch, rng):
        logits, new_carry = apply(cfg, params, model_state, batch["x"], rng=rng)
        v = logits.reshape(-1, cfg.vocab_size)
        labels = batch["y"].reshape(-1)
        loss = layers.softmax_cross_entropy(v, labels)
        return loss, (new_carry, {"loss": loss, "perplexity": jnp.exp(loss)})

    return f


#: Batch-owned carry shards with the batch over ``data``; the big [V, D] /
#: [D, V] tables may shard over ``model`` (clamped to replicated when the
#: mesh has no model axis).
SHARDING_RULES: tuple = (
    (r"lstm_\d+/(c|h)$", P("data", None)),
    (r"emb/table", P("model", None)),
    (r"softmax/kernel", P(None, "model")),
    (r"softmax/bias", P("model")),
)
