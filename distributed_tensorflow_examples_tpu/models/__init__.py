"""Model zoo: the five reference workload models (SURVEY.md section 2a)
plus framework growth models.

All models are *pure functional*: ``init(cfg, rng) -> params`` and
``apply(cfg, params, ...) -> outputs`` over plain dict pytrees — no module
objects, no tracing magic.  This keeps every parameter addressable by path for
sharding rules (``parallel.sharding``) and makes the whole train step a single
traced function XLA can fuse end-to-end.

- ``mlp``      — W1 MNIST MLP (ref: sync PS/worker, SyncReplicasOptimizer)
- ``cnn``      — W2 CIFAR-10 CNN (ref: async parameter-server)
- ``resnet``   — W3 ResNet-50 ImageNet (ref: MirroredStrategy/NCCL)
- ``word2vec`` — W4 skip-gram with mesh-sharded embedding (ref: PS-sharded)
- ``lstm``     — W5 PTB LSTM LM (ref: MultiWorkerMirroredStrategy)
"""

from . import layers  # noqa: F401
from . import mlp  # noqa: F401
from . import cnn  # noqa: F401
from . import resnet  # noqa: F401
from . import word2vec  # noqa: F401
from . import lstm  # noqa: F401
from . import transformer  # noqa: F401
