"""Functional layer library: init/apply pairs over plain dict pytrees.

The building blocks the reference gets from TF ops/Keras (dense, conv2d,
batch-norm, LSTM cell, embedding — SURVEY.md section 1 L4) rebuilt as pure
functions.  Compute-dtype policy: params live in float32; ``apply`` functions
accept a ``dtype`` to run activations/matmuls in bfloat16 on the MXU while
accumulating in float32 (``preferred_element_type``).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


# ----------------------------------------------------------------------------
# Initializers (TF analogs: glorot_uniform, he_normal, truncated_normal)
# ----------------------------------------------------------------------------


def glorot_uniform(rng, shape, in_axis=-2, out_axis=-1, dtype=jnp.float32):
    fan_in, fan_out = shape[in_axis], shape[out_axis]
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def he_normal_conv(rng, shape, dtype=jnp.float32):
    """He init for HWIO conv kernels (fan_in = h*w*cin)."""
    fan_in = shape[0] * shape[1] * shape[2]
    std = jnp.sqrt(2.0 / fan_in)
    return std * jax.random.normal(rng, shape, dtype)


def he_normal(rng, shape, in_axis=-2, dtype=jnp.float32):
    """He (fan-in) init for dense kernels — the relu-correct scale
    (glorot averages fan_in/fan_out and under-scales a relu stack by
    sqrt(2), which compounds per layer)."""
    std = jnp.sqrt(2.0 / shape[in_axis])
    return std * jax.random.normal(rng, shape, dtype)


def uniform_embedding(rng, shape, scale=None, dtype=jnp.float32):
    """word2vec-style U[-1/dim, 1/dim] embedding init."""
    scale = scale if scale is not None else 1.0 / shape[-1]
    return jax.random.uniform(rng, shape, dtype, -scale, scale)


# ----------------------------------------------------------------------------
# Dense
# ----------------------------------------------------------------------------


def dense_init(
    rng, in_dim: int, out_dim: int, *, use_bias: bool = True,
    init: str = "glorot",
):
    """``init``: "glorot" (the default every linear/softmax layer keeps)
    or "he" (fan-in — the relu-correct scale for hidden layers)."""
    kr, _ = jax.random.split(rng)
    if init == "he":
        kernel = he_normal(kr, (in_dim, out_dim))
    elif init == "glorot":
        kernel = glorot_uniform(kr, (in_dim, out_dim))
    else:
        raise ValueError(f"unknown dense init {init!r}")
    p = {"kernel": kernel}
    if use_bias:
        p["bias"] = jnp.zeros((out_dim,), jnp.float32)
    return p


def dense(params, x, *, dtype=None):
    k = params["kernel"]
    if dtype is not None:
        # Pure compute-dtype matmul: on TPU the MXU accumulates bf16 inputs
        # in f32 internally; keeping in/out dtypes uniform keeps the autodiff
        # transpose well-typed (mixed bf16/f32 transposes are rejected).
        x, k = x.astype(dtype), k.astype(dtype)
        y = jnp.matmul(x, k)
        if "bias" in params:
            y = y + params["bias"].astype(dtype)
        return y
    y = jnp.matmul(x, k, preferred_element_type=jnp.float32)
    if "bias" in params:
        y = y + params["bias"]
    return y


# ----------------------------------------------------------------------------
# Conv2D (NHWC x HWIO -> NHWC; the MXU-friendly layout)
# ----------------------------------------------------------------------------


def conv_init(rng, kh: int, kw: int, cin: int, cout: int, *, use_bias: bool = True):
    p = {"kernel": he_normal_conv(rng, (kh, kw, cin, cout))}
    if use_bias:
        p["bias"] = jnp.zeros((cout,), jnp.float32)
    return p


def conv2d(params, x, *, stride=1, padding="SAME", dtype=None):
    k = params["kernel"]
    if dtype is not None:
        x, k = x.astype(dtype), k.astype(dtype)
    strides = (stride, stride) if isinstance(stride, int) else stride
    y = lax.conv_general_dilated(
        x,
        k,
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        # Uniform in/out dtype (see dense): MXU accumulation is f32 either
        # way; mixed-dtype conv transposes fail under autodiff.
        preferred_element_type=None if dtype is not None else jnp.float32,
    )
    if "bias" in params:
        b = params["bias"]
        y = y + (b.astype(dtype) if dtype is not None else b)
    return y


# ----------------------------------------------------------------------------
# BatchNorm (params + mutable running stats threaded through model_state)
# ----------------------------------------------------------------------------


def batchnorm_init(c: int, *, ghost_slices: int = 0):
    """``ghost_slices > 0``: running stats carry a leading per-slice dim
    [S, C] (sharded P('slice', None) by the model's rules) so their EMA
    update never crosses the slice boundary — see batchnorm's ghost path."""
    params = {"scale": jnp.ones((c,), jnp.float32), "bias": jnp.zeros((c,), jnp.float32)}
    shape = (ghost_slices, c) if ghost_slices > 0 else (c,)
    stats = {"mean": jnp.zeros(shape, jnp.float32), "var": jnp.ones(shape, jnp.float32)}
    return params, stats


def _batchnorm_ghost(
    params, stats, x, *, momentum, eps, mesh, relu, ghost_slices: int
):
    """Ghost-batch (slice-local) BN statistics for multi-slice meshes.

    Full SyncBN reduces batch statistics over the WHOLE data axis — on a
    multi-slice deployment that is 2 tiny all-reduces per BN layer
    CROSSING DCN (98 per ResNet-50 step, the honest caveat in BASELINE.md
    r3's hybrid table).  Here the batch dim is reshaped [B] -> [S, B/S]
    with S pinned to the mesh's outermost ('slice') axis, so the
    statistics reduce runs only over the slice-LOCAL sub-axis of data
    (rides ICI) and each slice normalises with its own "ghost batch"
    (batch/S) statistics — the standard mitigation, with the standard
    statistics change (normalisation noise of a batch/S batch; quantified
    in tests/test_models.py).  Running stats stay per-slice [S, C]
    (sharded P('slice', None)) so the EMA update is collective-free;
    evaluation averages them once.  Result: NO BatchNorm traffic ever
    touches DCN — only the gradient all-reduce crosses."""
    S = ghost_slices
    B = x.shape[0]
    if B % S:
        raise ValueError(f"ghost BN: batch {B} not divisible by {S} slices")
    spec_x = P("slice", "data", *([None] * (x.ndim - 1)))

    def pin(t, spec):
        if mesh is None:
            return t
        return jax.lax.with_sharding_constraint(
            t, jax.sharding.NamedSharding(mesh, spec)
        )

    xr = pin(x.reshape(S, B // S, *x.shape[1:]), spec_x)
    xf = xr.astype(jnp.float32)
    axes = tuple(range(1, xr.ndim - 1))  # slice-local batch + spatial
    mean = pin(jnp.mean(xf, axis=axes), P("slice", None))  # [S, C]
    mean_sq = pin(jnp.mean(jnp.square(xf), axis=axes), P("slice", None))
    var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
    new_stats = {
        "mean": momentum * stats["mean"] + (1 - momentum) * mean,
        "var": momentum * stats["var"] + (1 - momentum) * var,
    }
    bshape = (S,) + (1,) * (x.ndim - 1) + (-1,)
    inv = lax.rsqrt(var + eps) * params["scale"]
    y = (xr - mean.reshape(bshape).astype(x.dtype)) * inv.reshape(bshape).astype(
        x.dtype
    ) + params["bias"].astype(x.dtype)
    if relu:
        y = jax.nn.relu(y)
    return pin(y.reshape(x.shape), P(("slice", "data"), *([None] * (x.ndim - 1)))), new_stats


def batchnorm(
    params, stats, x, *, train: bool, momentum=0.9, eps=1e-5, mesh=None,
    relu: bool = False, ghost_slices: int = 0,
):
    """Returns (y, new_stats).  In train mode the batch statistics are
    computed over the *global* batch: under jit with the batch sharded on the
    data axis, the mean/var reductions become cross-replica (XLA inserts the
    all-reduce) — matching SyncBatchNorm semantics, which is what mirrored
    data-parallel training wants.

    ``mesh`` (TPU): opts into the EXPERIMENTAL fused statistics path
    (ops/bn.py — Pallas kernels or MXU-matmul forms, gradient-exact vs this
    path).  Measured end-to-end on the current XLA/axon stack it is SLOWER
    than the XLA path (layout-conversion copies / algebraic re-simplification
    — BASELINE.md r3 table), so no shipped model threads a mesh in by
    default; the code is retained as measured evidence and for stacks where
    those compiler behaviors change.  Callers without a mesh always get the
    XLA path (a pallas_call on an implicitly-sharded array would force a
    gather).

    ``relu``: apply ReLU to the output INSIDE this layer.  On the fused
    path the backward then recomputes the mask in-kernel instead of
    materialising the masked gradient (the r3 profile's +29 ms trap);
    semantically identical to relu(batchnorm(x))."""
    if train and ghost_slices > 0:
        return _batchnorm_ghost(
            params, stats, x, momentum=momentum, eps=eps, mesh=mesh,
            relu=relu, ghost_slices=ghost_slices,
        )
    if train:
        from ..ops import bn as bn_ops

        if mesh is not None and bn_ops._use_pallas():
            y, mean, var = bn_ops.batchnorm_train(
                params["scale"], params["bias"], x, eps, mesh, relu
            )
            mean, var = jax.lax.stop_gradient((mean, var))
            new_stats = {
                "mean": momentum * stats["mean"] + (1 - momentum) * mean,
                "var": momentum * stats["var"] + (1 - momentum) * var,
            }
            return y, new_stats
        axes = tuple(range(x.ndim - 1))
        # One-pass stats: E[x] and E[x^2] share a single read of the
        # activation (XLA fuses sibling reductions), where mean+var is two
        # passes — measured ~15% of the ResNet-50 fwd step on v5e.
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        mean_sq = jnp.mean(jnp.square(xf), axis=axes)
        # Clamp: f32 cancellation can push E[x^2]-E[x]^2 slightly negative
        # for near-constant channels, and rsqrt(var+eps) would NaN.
        var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        new_stats = {
            "mean": momentum * stats["mean"] + (1 - momentum) * mean,
            "var": momentum * stats["var"] + (1 - momentum) * var,
        }
    else:
        mean, var = stats["mean"], stats["var"]
        if mean.ndim == 2:
            # Ghost-trained stats [S, C]: evaluation recovers the exact
            # GLOBAL moments by the law of total variance — mean of the
            # within-slice variances PLUS the variance of the slice means
            # (averaging the variances alone systematically undershoots
            # when slices are not iid).  This is the one cross-slice
            # reduction, paid at EVAL, not per step.
            gmean = jnp.mean(mean, axis=0)
            var = jnp.mean(var, axis=0) + jnp.mean(
                jnp.square(mean - gmean), axis=0
            )
            mean = gmean
        new_stats = stats
    inv = lax.rsqrt(var + eps) * params["scale"]
    y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + params["bias"].astype(x.dtype)
    if relu:
        y = jax.nn.relu(y)
    return y, new_stats


# ----------------------------------------------------------------------------
# Embedding
# ----------------------------------------------------------------------------


def embedding_init(rng, vocab: int, dim: int):
    return {"table": uniform_embedding(rng, (vocab, dim))}


def embedding_lookup(params, ids, *, dtype=None):
    """Gather rows.  When the table is sharded over the ``model`` mesh axis
    (rule: ``("embedding/table", P("model", None))``), XLA turns this into a
    per-shard gather + collective — the in-compiler equivalent of the
    reference's cross-network PS-shard gather (SURVEY.md section 3.5)."""
    t = params["table"]
    if dtype is not None:
        t = t.astype(dtype)
    return jnp.take(t, ids, axis=0)


# ----------------------------------------------------------------------------
# LSTM cell (the legacy_rnn BasicLSTMCell analog, scan-ready)
# ----------------------------------------------------------------------------


def lstm_cell_init(rng, in_dim: int, hidden: int):
    kr, _ = jax.random.split(rng)
    return {
        "kernel": glorot_uniform(kr, (in_dim + hidden, 4 * hidden)),
        "bias": jnp.zeros((4 * hidden,), jnp.float32),
    }


def lstm_cell(params, carry, x, *, forget_bias=1.0, dtype=None):
    """One LSTM step: carry = (c, h).  Gate order i, g, f, o.  Designed to be
    the body of ``lax.scan`` over time (compiler-friendly control flow — no
    Python loops inside jit)."""
    c, h = carry
    k = params["kernel"]
    if dtype is not None:
        x, h, k = x.astype(dtype), h.astype(dtype), k.astype(dtype)
        z = jnp.matmul(jnp.concatenate([x, h], axis=-1), k)
        z = (z + params["bias"].astype(dtype)).astype(jnp.float32)
    else:
        z = jnp.matmul(
            jnp.concatenate([x, h], axis=-1), k, preferred_element_type=jnp.float32
        )
        z = z + params["bias"]
    i, g, f, o = jnp.split(z, 4, axis=-1)
    new_c = jax.nn.sigmoid(f + forget_bias) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    new_h = jax.nn.sigmoid(o) * jnp.tanh(new_c)
    return (new_c, new_h), new_h


# ----------------------------------------------------------------------------
# Losses / metrics
# ----------------------------------------------------------------------------


def softmax_cross_entropy(logits, labels, num_classes=None):
    """Mean cross-entropy over the batch (global mean under jit+sharding —
    this mean is what makes data-parallel gradient averaging automatic)."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
