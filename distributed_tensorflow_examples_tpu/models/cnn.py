"""W2: CIFAR-10 CNN — the reference's async parameter-server workload
(SURVEY.md section 2a W2, BASELINE.json:8).

Model shape follows the classic TF CIFAR-10 tutorial net the reference genre
uses: two conv+pool blocks then two dense layers — all MXU-friendly (NHWC,
bf16 compute, f32 accumulation).  The *async* PS semantics are a
training-loop concern (SURVEY.md section 7 step 6), not a model concern; this
module is the pure model, usable under sync or async-emulated DP.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import layers


@dataclasses.dataclass(frozen=True)
class Config:
    num_classes: int = 10
    channels: tuple[int, ...] = (64, 64)
    dense: tuple[int, ...] = (384, 192)
    compute_dtype: str = "bfloat16"

    @property
    def dtype(self):
        return jnp.dtype(self.compute_dtype)


def init(cfg: Config, rng: jax.Array, *, image_size: int = 32, in_channels: int = 3):
    n_conv, n_dense = len(cfg.channels), len(cfg.dense)
    rngs = jax.random.split(rng, n_conv + n_dense + 1)
    params = {}
    cin = in_channels
    for i, cout in enumerate(cfg.channels):
        params[f"conv_{i}"] = layers.conv_init(rngs[i], 5, 5, cin, cout)
        cin = cout
    # Each conv block pools 2x; flattened feature size after the conv stack:
    feat = (image_size // (2 ** n_conv)) ** 2 * cin
    din = feat
    for j, dout in enumerate(cfg.dense):
        # He (fan-in) init for the relu'd hidden denses (r19 convergence
        # fix): glorot under-scales a relu stack by sqrt(2) per layer,
        # and on this 2-dense head the compounded deficit left the async
        # run's early dynamics on the 2.303 plateau after upstream RNG
        # drift moved the draw.  He restores the TF-tutorial-era scale.
        params[f"dense_{j}"] = layers.dense_init(
            rngs[n_conv + j], din, dout, init="he"
        )
        din = dout
    params["logits"] = layers.dense_init(rngs[-1], din, cfg.num_classes)
    # Small-stddev softmax init, the TF CIFAR tutorial's exact choice
    # (stddev = 1/192): glorot-scale logits on 192 inputs start the loss
    # at ~4.6 instead of ln(10), and the resulting ~50x-too-big first
    # gradients collapse the relu stack to the uniform plateau (observed:
    # 400 steps stuck at loss 2.303) or NaN outright at lr>=0.1.  The r10
    # zero-init avoided that too but also ZEROED the gradient into every
    # layer below for the first apply(s) — with the r19 convergence-rate
    # fix (He hidden denses + LR warmup) the tutorial's tiny-but-nonzero
    # scale keeps the whole stack learning from step 1 at ln(10) loss.
    kr = jax.random.split(rngs[-1])[0]
    params["logits"]["kernel"] = (1.0 / din) * jax.random.normal(
        kr, (din, cfg.num_classes), jnp.float32
    )
    return params


def apply(cfg: Config, params, x):
    """x: [B, H, W, C] float -> logits [B, num_classes]."""
    for i in range(len(cfg.channels)):
        x = layers.conv2d(params[f"conv_{i}"], x, dtype=cfg.dtype)
        x = jax.nn.relu(x)
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
    x = x.reshape(x.shape[0], -1)
    for j in range(len(cfg.dense)):
        x = layers.dense(params[f"dense_{j}"], x, dtype=cfg.dtype)
        x = jax.nn.relu(x)
    return layers.dense(params["logits"], x, dtype=cfg.dtype)


def loss_fn(cfg: Config):
    def f(params, model_state, batch, rng):
        logits = apply(cfg, params, batch["image"])
        loss = layers.softmax_cross_entropy(logits, batch["label"])
        acc = layers.accuracy(logits, batch["label"])
        return loss, (model_state, {"loss": loss, "accuracy": acc})

    return f


#: Mirrored variables (the async-PS placement maps to replication + the
#: accumulator service, not to sharding).
SHARDING_RULES: tuple = ()
